"""Continuous-batching generation server — paged KV blocks, per-step
admission, chunked prefill.

The static serving path runs ``generate()`` once per request: a request's
batch owns the device for its whole lifetime, a long prefill stalls every
co-batched decode (BENCH_r05: stream TTFT 2012 ms while the isolated
decode arm does 75k tok/s), and the int8-KV / shared-prefix / speculative
wins only exist in bench arms because nothing on the serving path
composes them.  This module is the scheduler shape production TPU serving
stacks use instead (Orca/vLLM-style):

  * **Paged KV pool** — one process-wide per-layer block pool
    (``models/generate.py init_block_pool``: ``[num_blocks, block_size,
    KV, hd]``); sequences hold block tables, the :class:`BlockAllocator`
    does alloc/free/eviction (preempt-youngest recompute) and occupancy
    accounting.  Shared prefixes are written once and PINNED: every
    sequence's table references the same physical blocks.
  * **Per-step admission** — each scheduler iteration admits newly
    arrived sequences into the in-flight decode batch, runs one decode
    ROUND (``span`` single-token steps as one ``lax.scan`` — one device
    program, one host sync), retires finished rows (the device-side
    after-eos latch composing with the ``mask_after_eos`` output
    contract), and hands tokens to the per-request streams.
  * **Chunked prefill** — prompts are consumed ``prefill_chunk`` tokens
    at a time, interleaved between decode rounds, so a 512-token prompt
    stalls in-flight streams for at most one chunk instead of a full
    prefill.
  * **Composition** — int8 KV pools, shared-prefix block reuse, and
    speculative draft/verify rounds (``paged_spec_round``) all run
    through the same admission/retirement machinery, so their bench-arm
    wins apply to actual served traffic.

Greedy scheduler output is token-identical to one-shot ``generate()``
(tests/test_genserver.py pins it); sampled decoding uses per-SEQUENCE
PRNG keys, so co-batched requests cannot couple through a shared batch
key (a deliberate improvement over the static path's batch-coupled
sampling — same quality, decoupled streams).

Tuning knobs (docs/operations.md "tuning the generation scheduler"):
``SELDON_TPU_GEN_BLOCK_SIZE`` (16), ``SELDON_TPU_GEN_POOL_BLOCKS``
(1024), ``SELDON_TPU_GEN_SLOTS`` (64), ``SELDON_TPU_GEN_SPAN`` (8),
``SELDON_TPU_GEN_PREFILL_CHUNK`` (128, the interleave floor),
``SELDON_TPU_GEN_PREFILL_CHUNK_MAX`` (512, the adaptive-chunk
ceiling).  Kill switch:
``SELDON_TPU_GEN_CONTINUOUS=0`` restores the static per-request path
(runtime/engine.py).
"""

from __future__ import annotations

import concurrent.futures
import logging
import os
import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from seldon_core_tpu.messages import LoadShedError
from seldon_core_tpu.runtime.autopilot import SHED_INFO_PREFIX
from seldon_core_tpu.runtime.brownout import BROWNOUT, BROWNOUT_INFO_PREFIX
from seldon_core_tpu.runtime.qos import current_tier, tier_rank
from seldon_core_tpu.utils.costledger import costledger_enabled
from seldon_core_tpu.utils.hotrecord import SPINE
from seldon_core_tpu.utils.perf import OBSERVATORY
from seldon_core_tpu.utils.telemetry import RECORDER

__all__ = ["BlockAllocator", "GenRequest", "GenServer"]

logger = logging.getLogger(__name__)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length() if n > 1 else 1


class BlockAllocator:
    """Host-side free-list allocator over the device block pool.

    Block 0 is the scratch block (masked/pad writes) and is never handed
    out.  ``pin`` marks shared-prefix blocks permanent: they count toward
    occupancy once and ``free`` refuses them, so a retiring sequence can
    never return a block every other sequence's table still references.
    Freed ids go back on the free list FIFO — fragmentation cannot exist
    by construction (any free block serves any sequence; the table adds
    the indirection), which is the point of paging.

    Thread-safety + the remote-import path (runtime/servingmesh.py): the
    relay handler reserves blocks for an in-flight KV handoff from the
    event-loop thread while the scheduler thread allocs/frees for live
    sequences, so every mutation takes the internal lock.  ``reserve``
    puts blocks in a typed RESERVED state: they are out of the free list
    (so eviction pressure cannot re-allocate them mid-import — victims
    only ever free blocks owned by a live sequence, and a reserved block
    belongs to none) and ``free`` REFUSES them until ``commit_reserved``
    turns them into normally-owned blocks or ``release_reserved``
    reclaims them (torn handoff) — a double release can't corrupt the
    free list either way."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("pool needs at least 2 blocks (1 is scratch)")
        self.num_blocks = int(num_blocks)
        self._free: deque = deque(range(1, self.num_blocks))
        self._pinned: set = set()
        self._reserved: set = set()
        self._lock = threading.Lock()
        self.high_water = 0

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1  # scratch excluded

    @property
    def used(self) -> int:
        return self.capacity - len(self._free)

    def can_alloc(self, n: int) -> bool:
        return len(self._free) >= n

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks or None — the caller queues (never crashes) on a full
        pool."""
        with self._lock:
            if n < 0 or len(self._free) < n:
                return None
            out = [self._free.popleft() for _ in range(n)]
            self.high_water = max(self.high_water, self.used)
            return out

    def reserve(self, n: int) -> Optional[List[int]]:
        """Allocate n blocks into the RESERVED state for an in-flight
        remote import — invisible to eviction, refused by ``free``."""
        blocks = self.alloc(n)
        if blocks is not None:
            with self._lock:
                self._reserved.update(blocks)
        return blocks

    def commit_reserved(self, blocks: List[int]) -> None:
        """Reserved -> owned: the import committed and a live sequence's
        table now references these blocks (normal free applies)."""
        with self._lock:
            self._reserved.difference_update(blocks)

    def release_reserved(self, blocks: List[int]) -> None:
        """Reclaim a torn handoff's reservation back to the free list."""
        with self._lock:
            for b in blocks:
                if b in self._reserved:
                    self._reserved.discard(b)
                    self._free.append(b)

    def pin(self, blocks: List[int]) -> None:
        with self._lock:
            self._pinned.update(blocks)

    def free(self, blocks: List[int]) -> None:
        with self._lock:
            for b in blocks:
                if b not in self._pinned and b not in self._reserved:
                    self._free.append(b)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "total": self.capacity,
                "used": self.used,
                "pinned": len(self._pinned),
                "reserved": len(self._reserved),
                "high_water": self.high_water,
            }


class _Sequence:
    """One row of one request riding the scheduler."""

    __slots__ = (
        "sid", "request", "row", "prompt", "prompt0", "max_new", "state",
        "n_valid", "blocks", "draft_blocks", "pending", "prefill_pos",
        "emitted", "done", "key_data", "admit_order", "retire_reason",
        "t_start", "events",
    )
    WAITING, PREFILL, RUNNING, DONE = range(4)

    def __init__(self, sid: int, request: "GenRequest", row: int,
                 prompt: np.ndarray, max_new: int):
        self.sid = sid
        self.request = request
        self.row = row
        self.prompt = prompt            # int32 [S] (suffix when prefixed)
        self.prompt0 = prompt           # as submitted: preempt rebuild base
        self.max_new = int(max_new)
        self.state = self.WAITING
        self.n_valid = 0                # cache positions written (global)
        self.blocks: List[int] = []     # PRIVATE blocks only
        self.draft_blocks: List[int] = []   # speculative mode
        self.pending: Optional[int] = None  # sampled, not yet in cache
        self.prefill_pos = 0            # prompt tokens consumed
        self.emitted: List[int] = []
        self.done = False
        self.key_data: Optional[np.ndarray] = None  # per-seq PRNG key
        self.admit_order = -1
        self.retire_reason = ""
        self.t_start = 0.0              # epoch at admission (span base)
        #: lifecycle timeline (enqueue -> admit -> prefill chunks ->
        #: decode rounds -> retire, with preemption/recompute events) —
        #: populated ONLY for sampled traces, emitted as one
        #: "gen_sequence" span's events at retirement
        self.events: List[Dict[str, Any]] = []


class _KvImport:
    """One in-flight remote-block import on a decode replica: reserved
    pool blocks + host-side staging buffers, keyed by handoff id.
    reserve -> receive -> commit; a torn handoff (abort, or the TTL
    reaper) releases the reservation with zero leaked blocks."""

    __slots__ = ("hid", "meta", "blocks", "staged", "received",
                 "created", "created_epoch", "seq", "trace_ctx")

    def __init__(self, hid: bytes, meta, blocks: List[int], staged):
        self.hid = hid
        self.meta = meta
        self.blocks = blocks
        self.staged = staged          # per-layer host arrays [n, bs, ...]
        self.received = np.zeros((meta.n_blocks,), bool)
        self.created = time.monotonic()
        self.created_epoch = time.time()
        self.seq: Optional[_Sequence] = None
        #: the handoff span's context off the relay sidecar (the BEGIN
        #: frame's traceparent) — decode-side import/decode spans parent
        #: under the prefill side's kv_handoff span through this
        self.trace_ctx = None

    def receive(self, first: int, layers) -> None:
        from seldon_core_tpu.runtime.kvstream import KvWireError

        n = layers[0]["k"].shape[0] if layers else 0
        if first < 0 or first + n > self.meta.n_blocks:
            raise KvWireError(
                f"block chunk [{first}, {first + n}) outside the "
                f"announced {self.meta.n_blocks} blocks")
        for stage, chunk in zip(self.staged, layers):
            for name, arr in chunk.items():
                stage[name][first:first + n] = arr
        self.received[first:first + n] = True

    def complete(self) -> bool:
        return bool(self.received.all())


class GenRequest:
    """One client request: N sequences plus the delivery surface — a
    Future holding the assembled ``[B, max_new]`` token array (unary) or
    a bounded queue of ``[B, <=chunk]`` arrays (streaming)."""

    def __init__(self, rows: int, chunk: Optional[int], max_new: int,
                 tier: Optional[str] = None):
        self.rows = rows
        self.chunk = chunk              # None = unary
        self.max_new = int(max_new)
        #: latency tier (runtime/qos.py): admission prefers interactive
        #: sequences, and preemption prefers victims from lower tiers
        self.tier = tier or "interactive"
        self.seqs: List[_Sequence] = []
        self.future: concurrent.futures.Future = concurrent.futures.Future()
        # unbounded on purpose: a stream buffers at most max_new tokens
        # per row, so the natural bound is the generation length — a
        # bounded queue could deadlock a slow consumer against the
        # scheduler thread
        self.queue: "queue.Queue" = queue.Queue()
        self.delivered = 0              # stream tokens handed out per row
        self.cancelled = False
        self.t_submit = time.perf_counter()
        self.ttft_recorded = False
        self.admit_recorded = False
        # the submitting request's trace context + QoS identity, captured
        # on the CALLER's thread (contextvars don't cross into the
        # scheduler thread): per-sequence prefill/decode spans parent
        # under the request span, and handoff sidecars carry the tenant
        from seldon_core_tpu.runtime.qos import current_tenant
        from seldon_core_tpu.utils.tracing import current_trace_context

        self.trace_ctx = current_trace_context()
        self.tenant = current_tenant() or ""

    def cancel(self) -> None:
        self.cancelled = True


class GenServer:
    """The continuous-batching scheduler for one generator deployment.

    Device work and all bookkeeping run on ONE daemon worker thread
    (started lazily at the first submit; jax dispatch from a single
    thread, callers bridge through thread-safe queues/futures).  The
    engine builds one of these from the unit's ``continuous_spec``
    (runtime/engine.py); ``SELDON_TPU_GEN_CONTINUOUS=0`` keeps the old
    static path."""

    def __init__(
        self,
        params,
        cfg,
        *,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        eos_token: int = -1,
        max_new_tokens: int = 32,
        prefix_cache=None,
        draft_params=None,
        draft_cfg=None,
        spec_k: int = 4,
        seed: int = 0,
        block_size: Optional[int] = None,
        num_blocks: Optional[int] = None,
        slots: Optional[int] = None,
        span: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        mesh=None,
        role: str = "unified",
        coordinator=None,
    ):
        self.params = params
        self.cfg = cfg
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token = int(eos_token)
        self.max_new_tokens = int(max_new_tokens)
        self.prefix_cache = prefix_cache
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.spec = draft_params is not None
        self.spec_k = int(spec_k)
        self.seed = int(seed)
        if self.spec and (self.temperature > 0.0
                          or cfg.kv_quant == "int8"
                          or prefix_cache is not None):
            # mirror speculative_generate's guards: greedy, float KV
            raise ValueError(
                "speculative continuous mode is greedy/float-KV only")
        if self.spec and role in ("prefill", "decode"):
            # a handoff would need the draft pool streamed too — out of
            # the disaggregation contract; serve speculative unified
            raise ValueError(
                "speculative decoding does not compose with "
                "disaggregated prefill/decode roles")
        self.block_size = block_size or _env_int(
            "SELDON_TPU_GEN_BLOCK_SIZE", 16)
        self.num_blocks = num_blocks or _env_int(
            "SELDON_TPU_GEN_POOL_BLOCKS", 1024)
        self.slots = slots or _env_int("SELDON_TPU_GEN_SLOTS", 64)
        self.span = span or _env_int("SELDON_TPU_GEN_SPAN", 8)
        self.prefill_chunk = prefill_chunk or _env_int(
            "SELDON_TPU_GEN_PREFILL_CHUNK", 128)
        # bounded admission queue: sustained overload must fail typed
        # (retryable 503 via LoadShedError) with flat memory, never grow
        # the waiting deques without limit.  Generous by default — the
        # bound exists to cap the failure mode, not to shape traffic
        # (token buckets and the brownout ladder do that)
        self.max_waiting = _env_int("SELDON_TPU_GEN_MAX_WAITING", 4096)
        # dispatch-latency-aware adaptive chunking: prefill_chunk is the
        # FLOOR (the guaranteed interleave grain); when a prefill tick's
        # wall time is dispatch-dominated — doubling the chunk leaves the
        # wall nearly flat, the relay/queueing signature — the effective
        # chunk probes upward toward PREFILL_CHUNK_MAX, because a bigger
        # chunk then shortens every TTFT path at zero stall cost.  When
        # doubling makes the tick materially slower (compute-bound:
        # directly-attached device, big model), it backs off and latches.
        self.prefill_chunk_max = max(
            _env_int("SELDON_TPU_GEN_PREFILL_CHUNK_MAX", 512),
            self.prefill_chunk,
        )
        self._chunk_eff = self.prefill_chunk
        self._chunk_wall: Dict[int, List[float]] = {}  # C -> [ema_s, n]
        self._chunk_latched = self._chunk_eff >= self.prefill_chunk_max
        # scheduler state (worker thread only, except arrivals)
        self._arrivals: deque = deque()
        self._waiting: deque = deque()
        self._prefilling: List[_Sequence] = []
        self._active: List[_Sequence] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self._pool = None
        self._device_ready = False
        self._device_init_lock = threading.Lock()
        self._draft_pool = None
        self._allocator: Optional[BlockAllocator] = None
        self._draft_allocator: Optional[BlockAllocator] = None
        self._prefix_blocks: List[int] = []     # shared full blocks
        self._prefix_len = 0
        self._seq_counter = 0
        self._admit_counter = 0
        # disaggregated serving mesh (runtime/servingmesh.py): the
        # replica's generation role, the optional device mesh the paged
        # pool (and the unit's params) shard over, and — prefill role —
        # the coordinator that streams finished KV blocks to a decode
        # peer.  Unified role with no mesh is bit-for-bit the PR-7 path.
        self.role = role if role in ("unified", "prefill", "decode") \
            else "unified"
        self.mesh = mesh
        self.coordinator = coordinator
        #: finished handoffs coming back from the coordinator thread:
        #: (seq, tokens-or-exception) drained on the scheduler thread
        self._handoff_done: deque = deque()
        #: sequences whose handoff is in flight (exported, not yet
        #: drained) — they live in no scheduler list, so _fail_all must
        #: fail them from here or their requests hang at stop()
        self._handoff_seqs: "dict" = {}
        self._handoff_inflight = 0
        #: decode role: in-flight remote imports keyed by handoff id
        #: (reserve -> receive -> commit; the TTL reaper reclaims torn
        #: ones) and committed imports awaiting scheduler admission
        self._imports: Dict[bytes, Any] = {}
        self._remote_arrivals: deque = deque()
        self._import_ttl_s = float(
            _env_int("SELDON_TPU_KV_HANDOFF_TTL_S", 30))
        self.imports_committed_total = 0
        self.imports_reclaimed_total = 0
        # lifetime counters for /stats + the gen_* Prometheus families
        self.admitted_total = 0
        self.retired_total: Dict[str, int] = {}
        self.preempted_total = 0
        self.steps_total: Dict[str, int] = {}
        self.tokens_emitted_total = 0
        self.tick_errors_total = 0
        # flight-recorder scratch (utils/genperf.py): the bubble ledger
        # stamps the END of every tick and classifies the gap before the
        # NEXT one by how this one ended; the per-tick accumulators are
        # reset at tick start and folded into one enriched HOP_GEN_STEP
        # record by _publish.  Scheduler thread only.
        self._last_tick_end = 0.0
        self._bubble_cause = "idle"
        self._pool_dry = False               # _admit broke on a dry pool
        self._dev_s: Dict[str, float] = {}   # phase -> fenced device s
        self._tick_rows = 0                  # padded rows dispatched
        self._tick_real_rows = 0             # real rows dispatched
        self._tick_dev_steps = 0             # single-token device steps
        self._tick_kv_pos = 0                # cache positions streamed
        self._tick_kv_blocks = 0             # blocks the tables covered
        self._tick_kv_ages: List[tuple] = []  # (n_blocks, age_s) freed
        # cost-ledger scratch (utils/costledger.py): per-phase tenant
        # splits of the tick's padded capacity + KV-block-seconds freed
        # this tick.  None when the ledger kill switch is off — the
        # accumulators then cost nothing, and the tick record carries no
        # "attr" payload (so the spine never sets WANT_COST)
        self._tick_attr: Optional[Dict[str, Any]] = None
        self._tick_kv_attr: List[tuple] = []   # (tenant, block_s) freed
        #: deployment identity on /costs rows; the engine stamps it
        self.cost_deployment = ""
        # this scheduler's waiting queue is an overload signal: the
        # brownout ladder reads it as queue depth.  Registered through a
        # weakref (and finalized) so the registry never pins a scheduler
        # a test dropped without stop()
        import weakref

        self._brownout_key = f"genserver:{id(self)}"
        ref = weakref.ref(self)
        BROWNOUT.register_depth(
            self._brownout_key,
            # len() on deques is safe without the lock; this is a
            # signal read, not an invariant
            lambda: (lambda s: 0 if s is None else
                     len(s._waiting) + len(s._arrivals))(ref()),
        )
        weakref.finalize(self, BROWNOUT.unregister_depth,
                         self._brownout_key)

    # -- client surface (any thread) ------------------------------------

    def submit(self, rows, max_new: Optional[int] = None,
               tier: Optional[str] = None) -> GenRequest:
        """Unary generation: rows [B, S] (float wire rows fine — the
        sanitize_prompt clamp applies).  Returns the request handle; its
        ``future`` resolves to the eos-padded int32 ``[B, max_new]``
        array — exactly ``generate()``'s output contract."""
        return self._enqueue(rows, chunk=None, max_new=max_new, tier=tier)

    def stream(self, rows, chunk: int = 8, max_new: Optional[int] = None,
               tier: Optional[str] = None):
        """Streaming generation: a plain generator of ``[B, <=chunk]``
        int32 arrays whose concatenation equals the unary output —
        the stream_tokens contract, served by the scheduler."""
        req = self._enqueue(rows, chunk=max(1, int(chunk)),
                            max_new=max_new, tier=tier)

        def _iter():
            try:
                while True:
                    item = req.queue.get()
                    if item is None:
                        break
                    if isinstance(item, BaseException):
                        raise item
                    yield item
            finally:
                if not req.future.done():
                    req.cancel()
                    with self._wake:
                        self._wake.notify_all()

        return _iter()

    def _enqueue(self, rows, chunk, max_new,
                 tier: Optional[str] = None) -> GenRequest:
        if self.role == "decode":
            # phase routing contract (runtime/servingmesh.py): decode
            # replicas serve KV handoffs only — a client generation
            # request landing here is a routing misconfig, answered
            # typed + retryable so the gateway can re-route
            from seldon_core_tpu.runtime.servingmesh import (
                RoleMismatchError,
            )

            raise RoleMismatchError(
                "this replica is decode-only (--gen-role decode): client "
                "generation requests route to prefill/unified replicas")
        tier = tier or current_tier()
        if BROWNOUT.sheds_tier(tier):
            # typed, retryable, BEFORE anything is allocated or queued —
            # the ladder's contract (runtime/brownout.py)
            RECORDER.record_brownout_shed(tier)
            raise LoadShedError(
                f"{BROWNOUT_INFO_PREFIX}: {tier!r}-tier generation shed "
                f"at brownout stage {BROWNOUT.stage()} — retry later or "
                "resubmit as a higher tier"
            )
        rows = np.asarray(rows, dtype=np.float64)
        if rows.ndim < 2:
            rows = rows.reshape(1, -1)
        # sanitize_prompt's clamp, host-side: NaN -> 0, clip to vocab
        prompts = np.clip(
            np.nan_to_num(rows), 0, self.cfg.vocab - 1
        ).astype(np.int32)
        max_new = int(max_new or self.max_new_tokens)
        scale = BROWNOUT.gen_max_new_scale()
        if scale < 1.0:
            # stage-2 degradation: shorter generations free KV blocks and
            # slots sooner; clamped at admission so a request's contract
            # (its future's [B, max_new] shape) is consistent throughout
            max_new = max(1, int(max_new * scale))
        req = GenRequest(len(prompts), chunk, max_new, tier=tier)
        with self._wake:
            if self._stopped:
                raise RuntimeError("generation scheduler stopped")
            waiting = len(self._waiting) + len(self._arrivals)
            if (self.max_waiting > 0
                    and waiting + len(prompts) > self.max_waiting):
                # bounded admission: beyond the cap the queue would only
                # grow memory, never goodput — fail typed and retryable
                # (503 downstream; composes with breakers/retry budget)
                RECORDER.record_autopilot_shed("gen_queue")
                # the shed prefix is the wire contract (autopilot.py):
                # without it the gateway would count this deliberate
                # backpressure as a replica fault AND feed the ~1 ms
                # refusal into the routing EWMA, herding MORE traffic
                # onto the saturated replica
                raise LoadShedError(
                    f"{SHED_INFO_PREFIX}: generation admission queue "
                    f"full ({waiting}/{self.max_waiting} sequences "
                    "waiting; grow SELDON_TPU_GEN_MAX_WAITING or add "
                    "replicas)"
                )
            for r, p in enumerate(prompts):
                self._seq_counter += 1
                seq = _Sequence(self._seq_counter, req, r, p, req.max_new)
                if self.temperature > 0.0:
                    import jax

                    seq.key_data = np.asarray(jax.random.key_data(
                        jax.random.fold_in(
                            jax.random.key(self.seed), self._seq_counter)
                    ))
                req.seqs.append(seq)
                # caller-thread stamp: the lifecycle timeline's origin
                self._seq_event(seq, "enqueue", prompt_len=len(p))
                self._arrivals.append(seq)
            self._ensure_thread()
            self._wake.notify_all()
        return req

    def prewarm(self, widths=()) -> int:
        """Compile the serving-path executables before traffic: one probe
        request per prompt width runs admission -> chunked prefill ->
        decode rounds end to end (backed by the persistent compile
        cache).  Returns the number of probes served."""
        if self.role != "unified":
            # prefill probes would fire real handoffs at peers that may
            # not be up yet; decode replicas reject submits by contract.
            # Both compile on first traffic (persistent compile cache).
            return 0
        count = 0
        for width in list(widths) or [4]:
            w = width if isinstance(width, int) else int(np.prod(width))
            probe = np.zeros((1, max(1, min(w, 4096))))
            req = self.submit(probe, max_new=min(self.span + 1,
                                                 self.max_new_tokens))
            try:
                req.future.result(timeout=900)
                count += 1
            except Exception as e:  # noqa: BLE001 - prewarm best-effort
                logger.warning("genserver prewarm width %s failed: %s",
                               width, e)
        return count

    _LEDGER_STATES = {_Sequence.WAITING: "waiting",
                      _Sequence.PREFILL: "prefill",
                      _Sequence.RUNNING: "running",
                      _Sequence.DONE: "done"}

    def snapshot(self) -> Dict[str, Any]:
        alloc = self._allocator
        now = time.time()
        with self._lock:
            waiting = len(self._waiting) + len(self._arrivals)
            inflight = len(self._active) + len(self._prefilling)
            tiers: Dict[str, int] = {}
            ledger: List[Dict[str, Any]] = []
            for coll in (self._waiting, self._arrivals,
                         self._prefilling, self._active):
                for s in coll:
                    t = s.request.tier
                    tiers[t] = tiers.get(t, 0) + 1
                    # the sequence ledger: enough per-sequence progress
                    # (prompt length, tokens emitted so far, remaining
                    # budget) for an operator — or a failover peer doing
                    # re-prefill resume — to reconstruct where a killed
                    # replica's streams stood.  The gateway's own resume
                    # path keeps the emitted tokens client-side; this is
                    # the server-side journal of the same truth.
                    ledger.append({
                        "sid": s.sid,
                        "tier": t,
                        "state": self._LEDGER_STATES.get(s.state, "?"),
                        "prompt_len": int(s.prompt0.shape[-1]),
                        "emitted": len(s.emitted),
                        "max_new": s.max_new,
                        "streaming": s.request.chunk is not None,
                        "age_s": round(now - s.t_start, 3)
                        if s.t_start else None,
                    })
        doc = {
            "mode": "speculative" if self.spec else "decode",
            # disaggregated serving mesh: this replica's generation role
            # plus the handoff/import flow (the /stats block the
            # gateway's scrape and the disagg runbook read)
            "role": self.role,
            "mesh": (
                None if self.mesh is None
                else dict(zip(self.mesh.axis_names,
                              self.mesh.devices.shape))
            ),
            "slots": self.slots,
            "inflight_sequences": inflight,
            "waiting_sequences": waiting,
            "max_waiting": self.max_waiting,
            "sequences_by_tier": tiers,
            "kv_blocks": alloc.snapshot() if alloc is not None else {
                "total": self.num_blocks - 1, "used": 0, "pinned": 0,
                "high_water": 0,
            },
            "block_size": self.block_size,
            "span": self.span,
            "prefill_chunk": self.prefill_chunk,
            "prefill_chunk_effective": self._chunk_eff,
            "admitted_total": self.admitted_total,
            "retired_total": dict(self.retired_total),
            "preempted_total": self.preempted_total,
            "steps_total": dict(self.steps_total),
            "tokens_emitted_total": self.tokens_emitted_total,
            "tick_errors_total": self.tick_errors_total,
            "sequence_ledger": ledger,
        }
        if self.spec:
            dalloc = self._draft_allocator
            doc["draft_kv_blocks"] = (
                dalloc.snapshot() if dalloc is not None else {})
        if self.role == "prefill":
            doc["disagg"] = (
                self.coordinator.snapshot()
                if self.coordinator is not None else None
            )
            doc["handoff_inflight"] = self._handoff_inflight
        if self.role == "decode":
            doc["imports"] = {
                "pending": len(self._imports),
                "committed_total": self.imports_committed_total,
                "reclaimed_total": self.imports_reclaimed_total,
            }
        return doc

    def chunk_history(self) -> Dict[str, Any]:
        """The adaptive prefill-chunk probe's state for ``GET /genperf``:
        floor/ceiling/effective width, whether the probe latched, and
        the per-width EMA walls the latch decision was made from."""
        return {
            "floor": self.prefill_chunk,
            "max": self.prefill_chunk_max,
            "effective": self._chunk_eff,
            "latched": self._chunk_latched,
            "wall_ema_s": {
                str(c): {"ema_s": round(v[0], 6), "ticks": v[1]}
                for c, v in sorted(self._chunk_wall.items())
            },
        }

    def stop(self) -> None:
        BROWNOUT.unregister_depth(self._brownout_key)
        with self._wake:
            self._stopped = True
            self._wake.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10)
        if self.coordinator is not None:
            self.coordinator.close()

    # -- worker thread ---------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="genserver", daemon=True)
            self._thread.start()

    def _ensure_device(self) -> None:
        if self._device_ready:
            return
        with self._device_init_lock:
            if not self._device_ready:
                self._init_device()
                self._device_ready = True

    def _init_device(self) -> None:
        # normally scheduler-thread-only; a decode replica's relay
        # handler also lands here when a KV handoff arrives before any
        # local tick ran (the init lock makes that safe — pool MUTATION
        # stays scheduler-thread-only afterwards)
        from seldon_core_tpu.models.generate import (
            init_block_pool,
            paged_write_prefix_blocks_jit,
        )

        self._pool = init_block_pool(
            self.cfg, self.num_blocks, self.block_size)
        self._allocator = BlockAllocator(self.num_blocks)
        if self.mesh is not None:
            # tensor-parallel dispatch (runtime/servingmesh.py): the
            # paged pool lays out over the unit's device mesh (KV heads
            # over 'tp' when divisible) so the scheduler's compiled
            # prefill/decode programs partition across chips together
            # with the mesh-sharded params
            from seldon_core_tpu.runtime.servingmesh import shard_gen_pool

            self._pool = shard_gen_pool(self.mesh, self._pool)
        if self.spec:
            self._draft_pool = init_block_pool(
                self.draft_cfg, self.num_blocks, self.block_size)
            self._draft_allocator = BlockAllocator(self.num_blocks)
        self._register_decode_costs()
        if self.prefix_cache is not None:
            P = int(self.prefix_cache["l0"]["k"].shape[2])
            self._prefix_len = P
            full = P // self.block_size
            if full:
                blocks = self._allocator.alloc(full)
                if blocks is None:
                    raise RuntimeError(
                        f"KV pool ({self.num_blocks} blocks) smaller than "
                        f"the shared prefix ({full} blocks)")
                self._pool = paged_write_prefix_blocks_jit(
                    self._pool, self.prefix_cache, tuple(blocks),
                    cfg=self.cfg)
                self._allocator.pin(blocks)
                self._prefix_blocks = blocks

    def _register_decode_costs(self) -> None:
        """Analytic per-token cost features for the SERVED decode lane,
        registered once at device init under ``gen_decode_step`` — the
        read side is ``OBSERVATORY.cost_features`` in utils/genperf.py,
        which prices served decode MFU / HBM-BW utilization against
        REAL tokens.  Same arithmetic as bench.py's kernel decode arm
        (matmul weights at serving dtype, two KV tensors per position
        plus int8 scales), so served-vs-kernel ratios compare like with
        like.  Never raises: accounting must not block serving."""
        try:
            cfg = self.cfg
            d, L = cfg.d_model, cfg.n_layers
            ff, v = cfg.d_ff, cfg.vocab
            kvh = getattr(cfg, "kv_heads", 0) or cfg.n_heads
            hd = d // cfg.n_heads
            qkv_out = d + 2 * kvh * hd
            per_layer = d * qkv_out + d * d + 2 * d * ff
            wb = 1 if getattr(cfg, "quant", "none") == "int8" else 2
            kv_int8 = getattr(cfg, "kv_quant", "none") == "int8"
            kvb = 1 if kv_int8 else 2
            OBSERVATORY.record_compile("gen_decode_step", {
                # matmul FLOPs per generated token (attention's
                # position-dependent term excluded — documented in
                # docs/benchmarking.md's served-MFU methodology)
                "flops": float(2 * (L * per_layer + d * v)),
                # HBM bytes ONE device step streams regardless of batch:
                # every matmul'd weight once, the bf16 unembed once
                "bytes_accessed": float(wb * L * per_layer + 2 * d * v),
                "output_bytes": 0.0,
                # HBM bytes per CACHE POSITION a step's attention reads
                # (k + v across layers, + f32 scales when int8 KV)
                "kv_bytes_per_position": float(
                    L * (2 * kvh * hd * kvb + (8 * kvh if kv_int8 else 0))
                ),
            }, None)
        except Exception:  # noqa: BLE001 - accounting must not block serving
            logger.debug("decode cost-feature registration failed",
                         exc_info=True)

    def _run(self) -> None:
        while True:
            with self._wake:
                while (not self._stopped and not self._arrivals
                       and not self._waiting and not self._prefilling
                       and not self._active and not self._remote_arrivals
                       and not self._handoff_done):
                    if self._imports:
                        # an in-flight remote import holds reserved
                        # blocks: wake periodically so the TTL reaper
                        # can reclaim a torn handoff even when no other
                        # work arrives
                        self._wake.wait(1.0)
                        break
                    self._wake.wait()
                if self._stopped:
                    break
                while self._arrivals:
                    self._waiting.append(self._arrivals.popleft())
            try:
                progress = self._tick()
            except Exception as e:  # noqa: BLE001 - fail loudly per request
                logger.exception("genserver tick failed")
                # a silently-erroring scheduler must be visible beyond
                # process logs: count it (/stats + the
                # seldon_tpu_gen_tick_errors_total family) and stamp an
                # error span into any sampled trace riding this tick
                self.tick_errors_total += 1
                RECORDER.record_gen_tick_error()
                from seldon_core_tpu.utils.genperf import GENPERF

                GENPERF.observe_tick_error()
                self._stamp_tick_error(e)
                self._fail_all(e)
                progress = True
            if not progress:
                # queued work that cannot run yet (pool dry, waiting on a
                # retirement that cannot come this tick): don't spin hot
                with self._wake:
                    self._wake.wait(0.005)
        self._fail_all(RuntimeError("generation scheduler stopped"))

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            committed = list(self._remote_arrivals)
            seqs = (list(self._waiting) + list(self._prefilling)
                    + list(self._active) + list(self._arrivals)
                    + [imp.seq for imp in committed]
                    # sequences whose handoff is at the coordinator (or
                    # already completed into _handoff_done): they live in
                    # no scheduler list, but their requests still await
                    + list(self._handoff_seqs))
            seqs = list(dict.fromkeys(seqs))
            self._waiting.clear()
            self._arrivals.clear()
            self._remote_arrivals.clear()
            self._handoff_seqs.clear()
            self._handoff_done.clear()
            self._prefilling, self._active = [], []
            imports = list(self._imports.values())
            self._imports.clear()
        for imp in imports + committed:
            # committed-but-unadmitted imports still hold RESERVED
            # blocks (commit_reserved only runs at admission) — release
            # them too or each aborted tick permanently shrinks the pool
            if self._allocator is not None:
                self._allocator.release_reserved(imp.blocks)
        for seq in seqs:
            self._release_blocks(seq)
            req = seq.request
            if not req.future.done():
                req.future.set_exception(exc)
            # plain put, not put_nowait-under-except-Full: the per-request
            # queues are unbounded today, so Full is impossible — but a
            # future bounded-queue change must BLOCK here rather than
            # silently drop the shutdown error a consumer is waiting on
            req.queue.put(exc)

    # -- the scheduler step ----------------------------------------------

    def _tick(self) -> bool:
        """One scheduler iteration: admit, one prefill chunk, one decode
        round, retire, account.  Exactly one fused telemetry record per
        step (utils/hotrecord.py HOP_GEN_STEP) — enriched with the
        flight-recorder decomposition: per-phase host walls, the fenced
        device walls the phase methods accumulated, and the inter-tick
        bubble classified by how the PREVIOUS tick ended.  Returns False
        when no work could run (the loop then backs off instead of
        spinning)."""
        t0 = time.perf_counter()
        bubble_s = (max(t0 - self._last_tick_end, 0.0)
                    if self._last_tick_end > 0.0 else 0.0)
        bubble_cause = self._bubble_cause
        self._pool_dry = False
        self._dev_s = {}
        self._tick_rows = self._tick_real_rows = 0
        self._tick_dev_steps = self._tick_kv_pos = self._tick_kv_blocks = 0
        self._tick_attr = {} if costledger_enabled() else None
        self._tick_kv_attr = []
        self._ensure_device()
        self._drop_cancelled()
        ta = time.perf_counter()
        admitted = self._admit()
        admitted += self._import_admit()
        handed_back = self._drain_handoff_done()
        self._reap_stale_imports()
        phases = {"admit": time.perf_counter() - ta}
        kind = None
        tokens = 0
        if self._prefilling:
            kind = "prefill"
            tp = time.perf_counter()
            tokens = self._prefill_tick()
            phases["prefill"] = time.perf_counter() - tp
        # a first token can finish a sequence (eos / max_new == 1): retire
        # BEFORE the round so it neither wastes a slot nor a dispatch
        tr = time.perf_counter()
        retired = self._retire_finished()
        phases["retire"] = time.perf_counter() - tr
        if self._active:
            if kind is None:
                kind = "spec" if self.spec else "decode"
            else:
                kind = "mixed"
            td = time.perf_counter()
            tokens += (self._spec_round() if self.spec
                       else self._decode_round())
            phases["decode"] = time.perf_counter() - td
        tr = time.perf_counter()
        retired += self._retire_finished()
        phases["retire"] += time.perf_counter() - tr
        # idle spins count explicitly: a hot-spinning scheduler must
        # read as a bubble on /genperf, not as silence in steps_total
        self.steps_total[kind or "idle"] = (
            self.steps_total.get(kind or "idle", 0) + 1)
        if kind is not None:
            self.tokens_emitted_total += tokens
        wall = time.perf_counter() - t0
        ages, self._tick_kv_ages = self._tick_kv_ages, []
        detail = {
            "wall_s": wall,
            "device_s": sum(self._dev_s.values()),
            "phases": phases,
            "device_phases": dict(self._dev_s),
            "rows": self._tick_rows,
            "real_rows": self._tick_real_rows,
            "tokens": tokens,
            "steps": self._tick_dev_steps,
            "kv_positions": self._tick_kv_pos,
            "kv_blocks": self._tick_kv_blocks,
            "kv_ages": tuple(ages),
        }
        if bubble_s > 0.0:
            detail["bubble_s"] = bubble_s
            detail["bubble_cause"] = bubble_cause
        if self._tick_attr is not None:
            # cost-ledger payload: per-phase tenant splits of the padded
            # capacity, KV-block-seconds freed this tick, deployment
            # identity.  Attached even on idle ticks so bubbles fold to
            # the ledger's idle bucket (its accounting identity needs
            # every second of wall, busy or not)
            detail["attr"] = {
                "dep": self.cost_deployment,
                "phases": {
                    phase: {
                        "padded": d["padded"],
                        "tenants": [
                            (t, tr, u, r, tok)
                            for (t, tr), (u, r, tok)
                            in d["tenants"].items()
                        ],
                    }
                    for phase, d in self._tick_attr.items()
                },
                "kv": tuple(self._tick_kv_attr),
            }
        self._publish(admitted, retired, kind or "idle", tokens, wall,
                      detail=detail)
        progress = (kind is not None or admitted > 0 or retired > 0
                    or handed_back > 0)
        # the bubble ledger: stamp this tick's end and decide what the
        # gap before the NEXT tick will mean.  Progress means the loop
        # re-enters immediately — the gap is scheduler host work.  A dry
        # pool means the device idles until a retirement frees blocks;
        # queued-but-unadmitted work is an admission stall; otherwise
        # the device is idle because there is simply no work.
        self._last_tick_end = time.perf_counter()
        if progress:
            self._bubble_cause = "host"
        elif self._pool_dry:
            self._bubble_cause = "pool_exhaustion"
        elif self._waiting or self._arrivals:
            self._bubble_cause = "admission_stall"
        else:
            self._bubble_cause = "idle"
        return progress

    def _drop_cancelled(self) -> None:
        for coll in (self._waiting, self._prefilling, self._active):
            for seq in [s for s in coll if s.request.cancelled]:
                coll.remove(seq)
                self._retire(seq, "cancelled")

    def _blocks_needed(self, upto: int) -> int:
        return -(-upto // self.block_size)  # ceil

    def _ensure_capacity(self, seq: _Sequence, upto: int,
                         draft: bool = False) -> bool:
        """Grow ``seq``'s table to cover positions [0, upto), evicting
        (preempt-youngest, recompute-on-readmit) when the pool is dry."""
        alloc = self._draft_allocator if draft else self._allocator
        shared = 0 if draft else len(self._prefix_blocks)
        owned = seq.draft_blocks if draft else seq.blocks
        need = self._blocks_needed(upto) - shared - len(owned)
        if need <= 0:
            return True
        while not alloc.can_alloc(need):
            victim = self._pick_victim(exclude=seq)
            if victim is None:
                return False
            self._preempt(victim)
        got = alloc.alloc(need)
        if got is None:
            return False
        owned.extend(got)
        return True

    def _pick_victim(self, exclude: _Sequence) -> Optional[_Sequence]:
        pool = [s for s in self._active + self._prefilling
                if s is not exclude]
        if not pool:
            return None
        # tier-aware preempt-youngest: victims come from the LOWEST
        # priority tier present (offline before batch before
        # interactive), youngest-within-tier — interactive sequences
        # keep their KV blocks while any lower-tier victim exists
        return max(pool, key=lambda s: (tier_rank(s.request.tier),
                                        s.admit_order))

    def _preempt(self, seq: _Sequence) -> None:
        """Evict a running sequence: free its blocks and push it to the
        FRONT of the waiting queue for recompute.  Its already-delivered
        tokens become part of the re-prefill prompt and the pending token
        is restored (never re-sampled), so the stream resumes exactly
        where it stopped."""
        for coll in (self._active, self._prefilling):
            if seq in coll:
                coll.remove(seq)
        self._seq_event(seq, "preempt", n_valid=seq.n_valid,
                        emitted=len(seq.emitted))
        self._release_blocks(seq)
        if seq.emitted:
            # rebuild from the ORIGINAL prompt: emitted keeps growing, so
            # folding into the already-folded prompt would duplicate
            # context on a second preemption
            seq.prompt = np.concatenate(
                [seq.prompt0,
                 np.asarray(seq.emitted[:-1], np.int32)]).astype(np.int32)
            seq.pending = seq.emitted[-1]
        seq.prefill_pos = 0
        seq.n_valid = 0
        seq.state = _Sequence.WAITING
        self._waiting.appendleft(seq)
        self.preempted_total += 1
        # mirrored into retired_total so /stats per-reason retirement
        # sums to the same figure as seldon_tpu_gen_retired_total
        self.retired_total["preempted"] = (
            self.retired_total.get("preempted", 0) + 1)
        RECORDER.record_gen_retired("preempted")

    def _attr_note(self, phase: str, padded_units: float,
                   rows) -> None:
        """Cost-ledger accumulation: ``rows`` increments of
        ``(tenant, tier, real_units, requests, tokens)`` against the
        tick's ``phase`` bucket.  No-op when the ledger is off."""
        if self._tick_attr is None:
            return
        d = self._tick_attr.setdefault(
            phase, {"padded": 0.0, "tenants": {}})
        d["padded"] += padded_units
        for tenant, tier, units, requests, toks in rows:
            row = d["tenants"].setdefault((tenant, tier), [0.0, 0.0, 0])
            row[0] += units
            row[1] += requests
            row[2] += toks

    def _release_blocks(self, seq: _Sequence) -> None:
        if self._allocator is not None and seq.blocks:
            if seq.t_start > 0.0:
                # KV residency at release — the pool-sizing histogram
                # (seldon_tpu_gen_kv_block_age_seconds via the spine fold)
                self._tick_kv_ages.append(
                    (len(seq.blocks), time.time() - seq.t_start))
                if self._tick_attr is not None:
                    # KV-block-seconds (blocks x held-time) land on the
                    # owning tenant at retire/preempt — the ledger's
                    # memory-residency axis
                    self._tick_kv_attr.append((
                        seq.request.tenant or "",
                        len(seq.blocks) * (time.time() - seq.t_start),
                    ))
            self._allocator.free(seq.blocks)
        seq.blocks = []
        if self._draft_allocator is not None and seq.draft_blocks:
            self._draft_allocator.free(seq.draft_blocks)
        seq.draft_blocks = []

    def _next_waiting_index(self) -> int:
        """Admission order: highest-priority tier first, FIFO within a
        tier — the genserver's latency-tier lane.  With homogeneous
        traffic (everything interactive, the default) this is index 0,
        i.e. exactly the old FIFO."""
        best, best_rank = 0, None
        for i, s in enumerate(self._waiting):
            r = tier_rank(s.request.tier)
            if best_rank is None or r < best_rank:
                best, best_rank = i, r
                if r == 0:
                    break  # nothing outranks interactive
        return best

    def _admit(self) -> int:
        """Tier-priority FIFO admission into free slots; a sequence whose
        FIRST chunk of blocks cannot be allocated stays queued (pool
        exhaustion queues, never crashes).  A sequence that cannot fit
        even with the scheduler otherwise EMPTY can never be served —
        that one fails with a typed error instead of deadlocking the
        queue."""
        admitted = 0
        while self._waiting and (
            len(self._active) + len(self._prefilling) < self.slots
        ):
            idx = self._next_waiting_index()
            seq = self._waiting[idx]
            first = min(len(seq.prompt), self.prefill_chunk)
            upto = self._prefix_len + first
            shared = len(self._prefix_blocks)
            need = self._blocks_needed(upto) - shared
            d_need = self._blocks_needed(first) if self.spec else 0
            if (not self._allocator.can_alloc(need)
                    or (self.spec
                        and not self._draft_allocator.can_alloc(d_need))):
                if not self._active and not self._prefilling:
                    # nothing will ever retire to free blocks: the pool
                    # is smaller than one request's first chunk
                    del self._waiting[idx]
                    self._finish_error(seq, RuntimeError(
                        f"KV pool ({self.num_blocks} blocks of "
                        f"{self.block_size}) cannot hold one prefill "
                        "chunk (grow SELDON_TPU_GEN_POOL_BLOCKS)"))
                    continue
                self._pool_dry = True   # bubble ledger: pool_exhaustion
                break  # pool dry: wait for a retirement to free blocks
            del self._waiting[idx]
            seq.blocks = self._allocator.alloc(need) or []
            if self.spec:
                seq.draft_blocks = (
                    self._draft_allocator.alloc(d_need) or [])
            # shared-prefix tail: the partially-filled boundary block is
            # private — copy the tail K/V into this sequence's first block
            p0 = len(self._prefix_blocks) * self.block_size
            if self._prefix_len > p0 and seq.blocks:
                import jax.numpy as jnp

                from seldon_core_tpu.models.generate import (
                    paged_write_prefix_tail_jit,
                )

                self._pool = paged_write_prefix_tail_jit(
                    self._pool, self.prefix_cache,
                    jnp.int32(seq.blocks[0]), cfg=self.cfg, p0=p0)
            seq.n_valid = self._prefix_len
            seq.state = _Sequence.PREFILL
            seq.prefill_pos = 0
            seq.t_start = time.time()
            self._seq_event(seq, "admit", blocks=len(seq.blocks),
                            recompute=bool(seq.emitted))
            self._admit_counter += 1
            seq.admit_order = self._admit_counter
            self._prefilling.append(seq)
            self.admitted_total += 1
            admitted += 1
            RECORDER.record_gen_admitted()
            if not seq.request.admit_recorded:
                # admission wait is this lane's queue wait — same family
                # the MicroBatcher feeds, so /stats reads unchanged
                seq.request.admit_recorded = True
                RECORDER.observe_queue_wait(
                    time.perf_counter() - seq.request.t_submit)
        return admitted

    def _table(self, seq: _Sequence, nblk: int, draft: bool = False
               ) -> np.ndarray:
        blocks = (seq.draft_blocks if draft
                  else self._prefix_blocks + seq.blocks)
        row = np.zeros((nblk,), np.int32)
        row[: len(blocks)] = blocks[:nblk]
        return row

    # -- prefill ----------------------------------------------------------

    def _prefill_tick(self) -> int:
        """Consume one chunk of EVERY prefilling sequence's prompt as a
        single batched device program — the interleave grain that keeps a
        long prompt from stalling in-flight decode for more than ~one
        chunk's worth of time, without serializing one dispatch per
        prompt (16 co-arriving 512-token prompts at chunk 128 are 4
        batched ticks, not 64 sequential ones — on a dispatch-latency
        relay that difference IS the TTFT p50)."""
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.generate import (
            paged_forward_jit,
            sample_token,
        )

        t0 = time.perf_counter()
        # brownout stage >= 2: drop to the floor grain (the guaranteed
        # interleave) so in-flight decode stalls minimally; the adaptive
        # probe pauses rather than learning from degraded-mode walls
        floored = BROWNOUT.gen_chunk_floor()
        C = self.prefill_chunk if floored else self._chunk_eff
        # capacity pass first: eviction inside it may requeue OTHER
        # prefilling sequences, so the batch is built only afterwards
        for seq in list(self._prefilling):
            if seq not in self._prefilling:
                continue  # preempted by an earlier row's eviction
            w = min(C, len(seq.prompt) - seq.prefill_pos)
            upto = self._prefix_len + seq.prefill_pos + w
            ok = self._ensure_capacity(seq, upto)
            if ok and self.spec:
                # draft pool sized like the target pool; best effort
                self._ensure_capacity(
                    seq, seq.prefill_pos + w, draft=True)
            if not ok:
                # cannot even hold this chunk: re-queue and wait.
                # _admit OVERWRITES seq.blocks on re-admission (and
                # resets prefill_pos — recompute-on-readmit), so the
                # blocks held so far must go back to the pool now
                self._prefilling.remove(seq)
                self._release_blocks(seq)
                if not self._active and not self._prefilling:
                    # alone and still failing: no retirement can ever
                    # free more — the prompt simply exceeds the pool.
                    # Requeueing would livelock (admit -> prefill ->
                    # requeue at full device utilization, forever)
                    self._finish_error(seq, RuntimeError(
                        f"KV pool ({self.num_blocks} blocks of "
                        f"{self.block_size}) too small for prompt "
                        f"length {len(seq.prompt)} (grow "
                        "SELDON_TPU_GEN_POOL_BLOCKS)"))
                    continue
                self._waiting.appendleft(seq)
                seq.state = _Sequence.WAITING
        batch = list(self._prefilling)
        if not batch:
            return 0
        B = _pow2(len(batch))
        toks = np.zeros((B, C), np.int32)
        start = np.zeros((B,), np.int32)
        width = np.zeros((B,), np.int32)
        widths = []
        for i, seq in enumerate(batch):
            lo = seq.prefill_pos
            w = min(C, len(seq.prompt) - lo)
            toks[i, :w] = seq.prompt[lo:lo + w]
            start[i] = self._prefix_len + lo
            width[i] = w
            widths.append(w)
        nblk = _pow2(max(
            self._blocks_needed(int(start[i]) + widths[i])
            for i in range(len(batch))
        ))
        tables = np.zeros((B, nblk), np.int32)
        for i, seq in enumerate(batch):
            tables[i] = self._table(seq, nblk)
        OBSERVATORY.note_padding(len(batch), B)
        self._tick_rows += B
        self._tick_real_rows += len(batch)
        # cost attribution: real units are this chunk's REAL prompt
        # tokens per sequence; the dispatched capacity is B x C (pad
        # rows and pad columns both burn the same device program)
        self._attr_note("prefill", B * C, [
            (s.request.tenant, s.request.tier, int(widths[i]), 0, 0)
            for i, s in enumerate(batch)
        ])
        self._tick_kv_blocks += sum(
            self._blocks_needed(int(start[i]) + widths[i])
            for i in range(len(batch)))
        td = time.perf_counter()
        logits, self._pool = paged_forward_jit(
            self.params, jnp.asarray(toks), self._pool,
            jnp.asarray(tables), jnp.asarray(start), jnp.asarray(width),
            cfg=self.cfg, last_only=True,
        )
        if self.spec:
            d_nblk = _pow2(max(
                self._blocks_needed(seq.prefill_pos + widths[i])
                for i, seq in enumerate(batch)
            ))
            d_tables = np.zeros((B, d_nblk), np.int32)
            d_start = np.zeros((B,), np.int32)
            for i, seq in enumerate(batch):
                d_tables[i] = self._table(seq, d_nblk, draft=True)
                d_start[i] = seq.prefill_pos
            _, self._draft_pool = paged_forward_jit(
                self.draft_params, jnp.asarray(toks), self._draft_pool,
                jnp.asarray(d_tables), jnp.asarray(d_start),
                jnp.asarray(width), cfg=self.draft_cfg, last_only=True,
            )
        # flight recorder: fence the dispatched step.  The greedy path
        # host-syncs these logits a few lines down anyway — this only
        # MOVES the sync so device wall is attributable to the phase
        jax.block_until_ready(logits)
        self._dev_s["prefill"] = (
            self._dev_s.get("prefill", 0.0) + time.perf_counter() - td)
        logits_host = None
        emitted = 0
        for i, seq in enumerate(batch):
            seq.prefill_pos += widths[i]
            self._seq_event(seq, "prefill_chunk", pos=seq.prefill_pos,
                            width=int(widths[i]))
            seq.n_valid = int(start[i]) + widths[i]
            if seq.prefill_pos < len(seq.prompt):
                continue
            # prompt fully consumed: sample (or restore) the first token
            self._prefilling.remove(seq)
            # the per-sequence prefill span (admission -> prompt fully
            # cached): the "prefill dispatch" leg of a federated trace's
            # critical path.  One record per sequence, trace-gated — the
            # per-step hot-path budget is untouched when tracing is off
            self._record_seq_span(seq, "prefill", "prefill")
            if seq.pending is None:
                if self.temperature > 0.0:
                    key = jax.random.wrap_key_data(
                        jnp.asarray(seq.key_data))
                    k0, key = jax.random.split(key)
                    seq.key_data = np.asarray(jax.random.key_data(key))
                    first = int(sample_token(
                        logits[i:i + 1], k0, self.temperature,
                        self.top_k, self.top_p,
                    )[0])
                else:
                    if logits_host is None:
                        logits_host = np.asarray(logits)
                    first = int(np.argmax(logits_host[i]))
                seq.pending = first
                self._emit_tokens(seq, [first])
                emitted += 1
                # one completed prefill = one request for the ledger's
                # per-request usage normalization; the first served token
                self._attr_note("prefill", 0, [
                    (seq.request.tenant, seq.request.tier, 0, 1, 1)])
            if self.role == "prefill":
                if seq.done:
                    # the first token already finished the sequence
                    # (max_new==1 / immediate eos): nothing to hand off
                    self._retire(seq, seq.retire_reason or "length")
                else:
                    self._handoff_out(seq)
            else:
                seq.state = _Sequence.RUNNING
                self._active.append(seq)
        if max(widths) == C and not floored:
            # only adapt on SATURATED ticks: short prompts never use a
            # wider executable, so probing one would compile it for
            # nothing (and the wall of an unsaturated tick says nothing
            # about width-C compute anyway)
            self._adapt_chunk(C, time.perf_counter() - t0)
        return emitted

    def _adapt_chunk(self, C: int, wall_s: float) -> None:
        """Probe the effective prefill chunk upward while ticks stay
        dispatch-dominated.  Evidence rule: after >= 2 ticks at width C,
        if doubling from C/2 left the EMA wall under 1.6x (compute would
        have doubled it), keep probing; if the doubled width is >1.6x
        slower, shrink back and LATCH — the floor is the configured
        interleave grain and the ceiling is PREFILL_CHUNK_MAX."""
        ema = self._chunk_wall.setdefault(C, [wall_s, 0])
        ema[0] = 0.5 * ema[0] + 0.5 * wall_s
        ema[1] += 1
        if self._chunk_latched or ema[1] < 2:
            return
        prev = self._chunk_wall.get(C // 2)
        if C > self.prefill_chunk and prev and ema[0] > 1.6 * prev[0]:
            self._chunk_eff = C // 2
            self._chunk_latched = True
        elif C < self.prefill_chunk_max:
            self._chunk_eff = min(2 * C, self.prefill_chunk_max)
        else:
            self._chunk_latched = True

    # -- decode -----------------------------------------------------------

    def _decode_round(self) -> int:
        """One ``span``-step decode round for every RUNNING sequence as a
        single device program; the only host sync is the token readback
        the streams need anyway."""
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.generate import paged_decode_round_jit

        batch = sorted(self._active, key=lambda s: s.sid)
        for seq in batch:
            if seq not in self._active:
                continue  # preempted by an earlier row's eviction
            if not self._ensure_capacity(seq, seq.n_valid + self.span):
                # pool exhausted even after eviction: this sequence is
                # alone and cannot fit — surface a typed failure
                self._active.remove(seq)
                self._finish_error(seq, RuntimeError(
                    "KV pool too small for sequence length "
                    f"{seq.n_valid + self.span} (grow "
                    "SELDON_TPU_GEN_POOL_BLOCKS)"))
                return 0
        batch = sorted(self._active, key=lambda s: s.sid)
        if not batch:
            return 0
        B = _pow2(len(batch))
        nblk = _pow2(max(
            self._blocks_needed(s.n_valid + self.span) for s in batch))
        tables = np.zeros((B, nblk), np.int32)
        token = np.zeros((B,), np.int32)
        n_valid = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        seen = np.zeros((B,), bool)
        for i, s in enumerate(batch):
            tables[i] = self._table(s, nblk)
            token[i] = s.pending
            n_valid[i] = s.n_valid
            active[i] = True
            seen[i] = (self.eos_token >= 0
                       and self.eos_token in s.emitted)
        if self.temperature > 0.0:
            kd = np.stack([
                s.key_data if s.key_data is not None
                else np.zeros_like(batch[0].key_data)
                for s in batch
            ] + [np.zeros_like(batch[0].key_data)] * (B - len(batch)))
            keys = jax.random.wrap_key_data(jnp.asarray(kd))
        else:
            keys = jnp.zeros((B,), jnp.uint32)
        OBSERVATORY.note_padding(len(batch), B)
        self._tick_rows += B
        self._tick_real_rows += len(batch)
        # cost attribution: one real unit per LIVE sequence, capacity B
        # (the pow-2 row padding is the decode round's whole pad tax)
        self._attr_note("decode", B, [
            (s.request.tenant, s.request.tier, 1, 0, 0) for s in batch])
        self._tick_kv_blocks += sum(
            self._blocks_needed(s.n_valid + self.span) for s in batch)
        # cache positions the round streams (served HBM-BW accounting):
        # each of the span steps attends over ~n_valid + step positions
        self._tick_kv_pos += sum(
            self.span * (s.n_valid + self.span // 2) for s in batch)
        self._tick_dev_steps += self.span
        td = time.perf_counter()
        toks, self._pool, _tok, _nv, _seen, keys_out = (
            paged_decode_round_jit(
                self.params, self._pool, jnp.asarray(tables),
                jnp.asarray(token), jnp.asarray(n_valid),
                jnp.asarray(active), jnp.asarray(seen), keys,
                self.cfg, span=self.span, temperature=self.temperature,
                top_k=self.top_k, top_p=self.top_p,
                eos_token=self.eos_token,
            )
        )
        # fence = the sync np.asarray was about to pay anyway, moved
        # here so decode device wall lands in its own phase
        jax.block_until_ready(toks)
        self._dev_s["decode"] = (
            self._dev_s.get("decode", 0.0) + time.perf_counter() - td)
        toks = np.asarray(toks)  # the per-round host sync
        if self.temperature > 0.0:
            kd_out = np.asarray(jax.random.key_data(keys_out))
        emitted = 0
        for i, s in enumerate(batch):
            if self.temperature > 0.0:
                s.key_data = kd_out[i]
            remaining = s.max_new - len(s.emitted)
            take = min(self.span, remaining)
            s.n_valid += self.span
            s.pending = int(toks[i, -1])
            self._emit_tokens(s, [int(t) for t in toks[i, :take]])
            self._seq_event(s, "decode_round", n_valid=s.n_valid,
                            take=take)
            emitted += take
            if take > 0:
                self._attr_note("decode", 0, [
                    (s.request.tenant, s.request.tier, 0, 0, take)])
        return emitted

    def _spec_round(self) -> int:
        """One speculative draft/verify round for every RUNNING sequence
        (greedy): up to k+1 tokens per row per device program."""
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.generate import paged_spec_round_jit

        W = self.spec_k + 1
        batch = sorted(self._active, key=lambda s: s.sid)
        for seq in batch:
            if seq not in self._active:
                continue  # preempted by an earlier row's eviction
            ok = (self._ensure_capacity(seq, seq.n_valid + W)
                  and self._ensure_capacity(seq, seq.n_valid + W,
                                            draft=True))
            if not ok:
                self._active.remove(seq)
                self._finish_error(seq, RuntimeError(
                    "KV pool too small for speculative round (grow "
                    "SELDON_TPU_GEN_POOL_BLOCKS)"))
                return 0
        batch = sorted(self._active, key=lambda s: s.sid)
        if not batch:
            return 0
        B = _pow2(len(batch))
        nblk = _pow2(max(
            self._blocks_needed(s.n_valid + W) for s in batch))
        # draft tables mirror the target's coverage: spec mode forbids
        # prefix caches, the only source of asymmetry
        d_nblk = nblk
        tables = np.zeros((B, nblk), np.int32)
        d_tables = np.zeros((B, d_nblk), np.int32)
        token = np.zeros((B,), np.int32)
        n_valid = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        for i, s in enumerate(batch):
            tables[i] = self._table(s, nblk)
            d_tables[i] = self._table(s, d_nblk, draft=True)
            token[i] = s.pending
            n_valid[i] = s.n_valid
            active[i] = True
        OBSERVATORY.note_padding(len(batch), B)
        self._tick_rows += B
        self._tick_real_rows += len(batch)
        self._attr_note("decode", B, [
            (s.request.tenant, s.request.tier, 1, 0, 0) for s in batch])
        self._tick_kv_blocks += sum(
            self._blocks_needed(s.n_valid + W) for s in batch)
        self._tick_kv_pos += sum(
            W * (s.n_valid + W // 2) for s in batch)
        # k sequential draft steps + one verify pass per round
        self._tick_dev_steps += W
        td = time.perf_counter()
        new_toks, gained, corrected, self._pool, self._draft_pool = (
            paged_spec_round_jit(
                self.params, self.draft_params, self._pool,
                self._draft_pool, jnp.asarray(tables),
                jnp.asarray(d_tables), jnp.asarray(token),
                jnp.asarray(n_valid), jnp.asarray(active),
                self.cfg, self.draft_cfg, k=self.spec_k,
            )
        )
        jax.block_until_ready(new_toks)
        self._dev_s["decode"] = (
            self._dev_s.get("decode", 0.0) + time.perf_counter() - td)
        new_toks = np.asarray(new_toks)
        gained = np.asarray(gained)
        corrected = np.asarray(corrected)
        emitted = 0
        accept_sum, accept_rounds = 0.0, 0
        for i, s in enumerate(batch):
            g = int(gained[i])
            remaining = s.max_new - len(s.emitted)
            take = min(g, remaining)
            s.n_valid += g
            s.pending = int(corrected[i])
            self._emit_tokens(s, [int(t) for t in new_toks[i, :take]])
            self._seq_event(s, "decode_round", n_valid=s.n_valid,
                            take=take, gained=g)
            emitted += take
            if take > 0:
                self._attr_note("decode", 0, [
                    (s.request.tenant, s.request.tier, 0, 0, take)])
            accept_sum += (g - 1) / max(self.spec_k, 1)
            accept_rounds += 1
        if accept_rounds:
            RECORDER.observe_accept_ratio(accept_sum / accept_rounds)
        return emitted

    # -- disaggregated handoff: prefill side ------------------------------

    def _handoff_out(self, seq: _Sequence) -> None:
        """Export a finished prefill (its private KV blocks + sampling
        state) and hand it to the coordinator; the blocks go straight
        back to the pool — the prefill replica's whole point is that its
        residency recycles at prompt cadence, not generation cadence."""
        from seldon_core_tpu.runtime import kvstream
        from seldon_core_tpu.runtime.servingmesh import HandoffError

        if self.coordinator is None:
            self._finish_error(seq, HandoffError(
                "prefill-role replica has no decode peers configured "
                "(--decode-peers / ENGINE_DECODE_PEERS)"))
            return
        l0 = self._pool["l0"]
        meta = kvstream.KvBeginMeta(
            n_layers=len(self._pool),
            block_size=self.block_size,
            kv_heads=int(l0["k"].shape[2]),
            head_dim=int(l0["k"].shape[3]),
            dtype=kvstream.pool_dtype_name(self._pool),
            n_blocks=len(seq.blocks),
            n_valid=seq.n_valid,
            pending=int(seq.pending),
            max_new=int(seq.max_new),
            prefix_len=self._prefix_len,
            prompt=np.asarray(seq.prompt, np.int32),
            emitted=list(seq.emitted),
            key_data=seq.key_data,
            tier=seq.request.tier,
        )
        export = kvstream.KvExport(
            meta=meta,
            # device->host gather NOW, on the scheduler thread, before
            # the pool is donated into the next dispatch
            layers=kvstream.export_blocks(self._pool, seq.blocks),
            tenant=getattr(seq.request, "tenant", "") or "",
        )
        # mint the kv_handoff span's identity UP FRONT: its traceparent
        # rides the relay sidecar on every frame, so the decode side's
        # import/decode spans parent under a span id that already exists
        # when they are recorded; the coordinator records the span itself
        # when the stream completes (runtime/servingmesh.py)
        from seldon_core_tpu.utils.tracing import TRACER

        req_ctx = getattr(seq.request, "trace_ctx", None)
        if req_ctx is not None and req_ctx.sampled and TRACER.enabled:
            export.trace_ctx = req_ctx.child(req_ctx.puid)
            export.parent_span_id = req_ctx.span_id
            export.puid = req_ctx.puid
        self._seq_event(seq, "handoff", n_valid=seq.n_valid)
        self._release_blocks(seq)
        seq.state = _Sequence.DONE
        self._handoff_inflight += 1
        self._handoff_seqs[seq] = True

        def _done(result, seq=seq):
            self._handoff_done.append((seq, result))
            with self._wake:
                self._wake.notify_all()

        self.coordinator.submit(export, _done)

    def _drain_handoff_done(self) -> int:
        """Fold completed handoffs back into the request surfaces: the
        decode peer's token array becomes the sequence's emitted stream
        (first token unchanged — it was emitted at prefill time), or a
        typed failure fails the request retryably."""
        n = 0
        while self._handoff_done:
            seq, result = self._handoff_done.popleft()
            self._handoff_seqs.pop(seq, None)
            self._handoff_inflight -= 1
            n += 1
            if isinstance(result, BaseException):
                self._finish_error(seq, result)
                continue
            toks = [int(t) for t in np.asarray(result).reshape(-1)]
            prev = len(seq.emitted)
            seq.emitted = toks[: seq.max_new]
            if len(seq.emitted) < seq.max_new:
                # defensive eos-padding; the decode side pads already
                pad = (self.eos_token if self.eos_token >= 0
                       else (seq.emitted[-1] if seq.emitted else 0))
                seq.emitted += [pad] * (seq.max_new - len(seq.emitted))
            self.tokens_emitted_total += max(0, len(seq.emitted) - prev)
            seq.done = True
            self._retire(seq, "handoff")
        return n

    # -- disaggregated handoff: decode side (relay-handler threads) -------

    def kv_reserve(self, hid: bytes, meta) -> None:
        """BEGIN: validate the handoff against this pool and reserve its
        blocks.  Raises typed — KvWireError for geometry/dtype/prefix
        mismatches (a deployment misconfig), LoadShedError when the pool
        cannot hold the blocks (retryable: the prefill side's p2c walks
        to the next peer)."""
        from seldon_core_tpu.runtime import kvstream

        self._ensure_device()
        kvstream.validate_against_pool(
            meta, self._pool, self.block_size, self._prefix_len)
        blocks = self._allocator.reserve(meta.n_blocks)
        if blocks is None:
            RECORDER.record_kv_handoff("refused")
            raise LoadShedError(
                f"{SHED_INFO_PREFIX}: decode KV pool cannot hold "
                f"{meta.n_blocks} handoff blocks "
                f"({self._allocator.used}/{self._allocator.capacity} "
                "used) — try another decode replica")
        names = (("k", "v", "k_s", "v_s") if meta.dtype == "int8"
                 else ("k", "v"))
        dt = (np.int8 if meta.dtype == "int8"
              else kvstream._np_dtype(meta.dtype))
        staged = []
        for _ in range(meta.n_layers):
            layer = {}
            for name in names:
                if name.endswith("_s"):
                    shape = (meta.n_blocks, meta.block_size,
                             meta.kv_heads)
                    layer[name] = np.zeros(shape, np.float32)
                else:
                    shape = (meta.n_blocks, meta.block_size,
                             meta.kv_heads, meta.head_dim)
                    layer[name] = np.zeros(shape, dt)
            staged.append(layer)
        imp = _KvImport(hid, meta, blocks, staged)
        # the relay sidecar bound the BEGIN frame's traceparent around
        # this handler (udsrelay.py): capture it so the import + decode
        # spans of this handoff parent under the prefill side's
        # kv_handoff span
        from seldon_core_tpu.utils.tracing import current_trace_context

        imp.trace_ctx = current_trace_context()
        with self._wake:
            if self._stopped:
                self._allocator.release_reserved(blocks)
                raise RuntimeError("generation scheduler stopped")
            self._imports[hid] = imp
            # the scheduler thread must run while a reservation is
            # outstanding: it IS the TTL reaper for torn handoffs
            self._ensure_thread()
            self._wake.notify_all()

    def kv_receive(self, hid: bytes, first: int, layers) -> None:
        """KV_BLOCKS: stage one chunk host-side (nothing touches the
        device pool until commit — the scheduler thread owns it)."""
        from seldon_core_tpu.runtime.kvstream import KvWireError

        imp = self._imports.get(hid)
        if imp is None:
            raise KvWireError("unknown or expired handoff id")
        imp.receive(first, layers)

    def kv_commit(self, hid: bytes) -> GenRequest:
        """KV_COMMIT: the import is complete — build the sequence and
        queue it for scheduler admission (the device scatter happens on
        the scheduler thread).  Returns the request whose future
        resolves to the finished ``[1, max_new]`` token array."""
        from seldon_core_tpu.runtime.kvstream import KvWireError

        # pop FIRST: the claim on this handoff must be atomic against
        # the scheduler's TTL reaper (which also pops before releasing).
        # A get-then-pop would let a commit landing exactly at the TTL
        # admit a reservation the reaper already returned to the free
        # list — two sequences sharing blocks, silently
        imp = self._imports.pop(hid, None)
        if imp is None:
            raise KvWireError("unknown or expired handoff id")
        if not imp.complete():
            # torn: the sender committed before streaming every block
            self._allocator.release_reserved(imp.blocks)
            self.imports_reclaimed_total += 1
            RECORDER.record_kv_handoff("reclaimed")
            raise KvWireError(
                "commit before every block was received — torn handoff "
                "reclaimed")
        meta = imp.meta
        req = GenRequest(1, None, meta.max_new, tier=meta.tier)
        if imp.trace_ctx is not None:
            # parent the decode-side spans under the kv_handoff span the
            # BEGIN sidecar named (the COMMIT may arrive on a different
            # relay connection — the BEGIN-time capture is authoritative)
            req.trace_ctx = imp.trace_ctx
        from seldon_core_tpu.utils.tracing import TRACER

        if imp.trace_ctx is not None and TRACER.enabled:
            # the import leg: reserve -> every block staged -> commit
            TRACER.record_span(
                "kv_import", kind="kv_import", method="kv_handoff",
                start_s=imp.created_epoch,
                duration_ms=(time.time() - imp.created_epoch) * 1e3,
                ctx=imp.trace_ctx, blocks=len(imp.blocks),
                n_valid=int(meta.n_valid),
            )
        with self._wake:
            if self._stopped:
                self._allocator.release_reserved(imp.blocks)
                raise RuntimeError("generation scheduler stopped")
            self._seq_counter += 1
            seq = _Sequence(self._seq_counter, req, 0,
                            np.asarray(meta.prompt, np.int32),
                            meta.max_new)
            seq.n_valid = int(meta.n_valid)
            seq.pending = int(meta.pending)
            seq.emitted = list(meta.emitted)
            seq.key_data = (np.asarray(meta.key_data)
                            if meta.key_data is not None else None)
            req.seqs.append(seq)
            imp.seq = seq
            self._remote_arrivals.append(imp)
            self._ensure_thread()
            self._wake.notify_all()
        return req

    def kv_abort(self, hid: bytes) -> bool:
        imp = self._imports.pop(hid, None)
        if imp is None:
            return False
        self._allocator.release_reserved(imp.blocks)
        self.imports_reclaimed_total += 1
        RECORDER.record_kv_handoff("reclaimed")
        return True

    def kv_stats(self) -> Dict[str, int]:
        """The free-KV-block score a prefill coordinator's p2c reads
        (KV_STATS frame) — cheap enough to answer before the device pool
        even exists."""
        alloc = self._allocator
        if alloc is not None:
            snap = alloc.snapshot()
            free = snap["total"] - snap["used"]
            total = snap["total"]
        else:
            free = total = self.num_blocks - 1
        with self._lock:
            waiting = len(self._waiting) + len(self._arrivals)
            inflight = len(self._active) + len(self._prefilling)
        return {"free": free, "total": total, "waiting": waiting,
                "inflight": inflight}

    # -- disaggregated handoff: decode side (scheduler thread) ------------

    def _import_admit(self) -> int:
        """Committed imports enter the decode loop: one compiled chunk
        scatter writes the staged blocks into the pool, the reservation
        becomes ownership, and the sequence joins ``_active`` mid-
        stream — exactly where the unified path would have put it after
        local prefill."""
        if not self._remote_arrivals:
            return 0
        from seldon_core_tpu.runtime import kvstream

        n = 0
        while self._remote_arrivals:
            imp = self._remote_arrivals.popleft()
            self._pool = kvstream.scatter_staged(
                self._pool, imp.blocks, imp.staged)
            self._allocator.commit_reserved(imp.blocks)
            seq = imp.seq
            seq.blocks = list(imp.blocks)
            seq.state = _Sequence.RUNNING
            seq.t_start = time.time()
            self._seq_event(seq, "admit", blocks=len(seq.blocks),
                            imported=True)
            self._admit_counter += 1
            seq.admit_order = self._admit_counter
            self._active.append(seq)
            self.admitted_total += 1
            self.imports_committed_total += 1
            RECORDER.record_gen_admitted()
            RECORDER.record_kv_handoff("imported")
            n += 1
        return n

    def _reap_stale_imports(self) -> None:
        """Torn-handoff backstop: a reservation never committed within
        the TTL goes back to the pool — the leak bound is TTL, not
        forever."""
        if not self._imports:
            return
        now = time.monotonic()
        for hid, imp in list(self._imports.items()):
            if now - imp.created > self._import_ttl_s:
                if self._imports.pop(hid, None) is not None:
                    self._allocator.release_reserved(imp.blocks)
                    self.imports_reclaimed_total += 1
                    RECORDER.record_kv_handoff("reclaimed")
                    logger.warning(
                        "reclaimed torn KV handoff (%d blocks) after "
                        "%.0fs TTL", len(imp.blocks), self._import_ttl_s)

    # -- emission / retirement --------------------------------------------

    def _emit_tokens(self, seq: _Sequence, toks: List[int]) -> None:
        if not toks or seq.done:
            return
        seq.emitted.extend(toks)
        if self.eos_token >= 0 and self.eos_token in seq.emitted:
            # finished early: eos-pad the tail now so assembly never
            # waits on a retired row (the mask_after_eos output contract)
            first = seq.emitted.index(self.eos_token)
            seq.emitted = (
                seq.emitted[: first + 1]
                + [self.eos_token] * (seq.max_new - first - 1)
            )
            seq.retire_reason = "eos"
            seq.done = True
        elif len(seq.emitted) >= seq.max_new:
            seq.emitted = seq.emitted[: seq.max_new]
            seq.retire_reason = "length"
            seq.done = True
        req = seq.request
        if not req.ttft_recorded:
            req.ttft_recorded = True
            if req.chunk is not None:
                # TTFT is a STREAMING-lane metric (one observation per
                # stream, the scheduler is its canonical recorder now);
                # unary requests only surface total latency
                RECORDER.observe_ttft(time.perf_counter() - req.t_submit)
        self._deliver(req)

    def _deliver(self, req: GenRequest) -> None:
        """Assemble per-request output from the per-row sequences: stream
        chunks when every row has them, the final array at completion."""
        if req.cancelled or req.future.done():
            return
        if req.chunk is not None:
            while True:
                avail = min(len(s.emitted) for s in req.seqs)
                n = min(req.chunk, req.max_new - req.delivered)
                if n <= 0 or avail - req.delivered < n:
                    break
                arr = np.asarray(
                    [s.emitted[req.delivered:req.delivered + n]
                     for s in req.seqs], np.int32)
                req.delivered += n
                req.queue.put(arr)
        if all(s.done for s in req.seqs):
            out = np.asarray([s.emitted for s in req.seqs], np.int32)
            elapsed = time.perf_counter() - req.t_submit
            if req.chunk is not None and elapsed > 0:
                # like TTFT above: the decode-rate SLO family is fed once
                # per STREAM (matching the static path, where the unary
                # lane ran generate(eager=False) and recorded nothing)
                RECORDER.observe_decode_rate(out.size / elapsed)
            if not req.future.done():
                req.future.set_result(out)
            if req.chunk is not None:
                req.queue.put(None)

    def _retire_finished(self) -> int:
        retired = 0
        for seq in [s for s in self._active if s.done]:
            self._active.remove(seq)
            self._retire(seq, seq.retire_reason or "length")
            retired += 1
        return retired

    def _record_seq_span(self, seq: _Sequence, name: str,
                         method: str) -> None:
        """One per-sequence span (prefill / decode leg) parented under
        the request's captured trace context — the scheduler's phases
        become visible legs of a (federated) trace tree.  No-op unless
        tracing is on AND the request's trace was sampled; ``record_span``
        enforces both."""
        from seldon_core_tpu.utils.tracing import TRACER

        ctx = getattr(seq.request, "trace_ctx", None)
        if ctx is None or not TRACER.enabled or seq.t_start <= 0.0:
            return
        TRACER.record_span(
            name, kind="dispatch", method=method, start_s=seq.t_start,
            duration_ms=(time.time() - seq.t_start) * 1e3, ctx=ctx,
            rows=1, n_valid=seq.n_valid, tokens=len(seq.emitted),
            role=self.role,
        )

    def _seq_event(self, seq: _Sequence, name: str, **attrs: Any) -> None:
        """Append one lifecycle event to a SAMPLED sequence's timeline.
        Strictly a no-op for untraced requests — the per-tick hot path
        pays one attribute read and one boolean test."""
        ctx = getattr(seq.request, "trace_ctx", None)
        if ctx is None:
            return
        from seldon_core_tpu.utils.tracing import TRACER

        if not ctx.sampled and not (
            getattr(ctx, "pm", False) and TRACER.pm_hook is not None
        ):
            # not sampled AND not under postmortem tail capture: the
            # preempt/admit timeline would reach no surface — skip it
            return
        if not TRACER.enabled or len(seq.events) >= 512:
            return
        ev: Dict[str, Any] = {"name": name, "ts": round(time.time(), 6)}
        if attrs:
            ev["attrs"] = attrs
        seq.events.append(ev)

    def _emit_seq_timeline(self, seq: _Sequence, reason: str) -> None:
        """One ``gen_sequence`` span per retired SAMPLED sequence,
        carrying the whole lifecycle (enqueue -> admit -> prefill chunks
        -> decode rounds -> retire, preemptions included) as span events
        — the per-sequence leg of the causal trace tree."""
        if not seq.events:
            return
        ctx = getattr(seq.request, "trace_ctx", None)
        if ctx is None:
            return
        from seldon_core_tpu.utils.tracing import TRACER, Span, new_span_id

        pm_only = not ctx.sampled
        if pm_only and not (
            getattr(ctx, "pm", False) and TRACER.pm_hook is not None
        ):
            return
        if not TRACER.enabled:
            return
        start_s = seq.events[0]["ts"]
        TRACER.add(Span(
            puid=ctx.puid, name="gen_sequence", kind="gen_seq",
            method=reason, start_s=start_s,
            duration_ms=(time.time() - start_s) * 1e3,
            attrs={"sid": seq.sid, "row": seq.row,
                   "tokens": len(seq.emitted), "n_valid": seq.n_valid,
                   "role": self.role},
            trace_id=ctx.trace_id, span_id=new_span_id(),
            parent_span_id=ctx.span_id, events=list(seq.events),
            pm_only=pm_only,
        ))
        seq.events = []

    def _stamp_tick_error(self, exc: BaseException) -> None:
        """Error-path visibility in traces: stamp one ``gen_tick_error``
        span under any sampled request riding the failing tick (the
        batch is about to be failed wholesale by ``_fail_all``)."""
        from seldon_core_tpu.utils.tracing import TRACER

        if not TRACER.enabled:
            return
        for s in list(self._active) + list(self._prefilling):
            ctx = getattr(s.request, "trace_ctx", None)
            if ctx is not None and ctx.sampled:
                TRACER.record_span(
                    "gen_tick_error", kind="gen_step", method="error",
                    start_s=time.time(), duration_ms=0.0, ctx=ctx,
                    error=repr(exc)[:200],
                )
                return

    def _retire(self, seq: _Sequence, reason: str) -> None:
        self._release_blocks(seq)
        seq.state = _Sequence.DONE
        if self.role == "decode" and reason not in ("cancelled",):
            # the decode leg of a disaggregated generation: one span per
            # imported sequence, parented under the prefill side's
            # kv_handoff span (the context rode the relay sidecar)
            self._record_seq_span(seq, "decode", "decode")
        self.retired_total[reason] = self.retired_total.get(reason, 0) + 1
        RECORDER.record_gen_retired(reason)
        self._seq_event(seq, "retire", reason=reason,
                        emitted=len(seq.emitted))
        self._emit_seq_timeline(seq, reason)
        self._deliver(seq.request)

    def _finish_error(self, seq: _Sequence, exc: BaseException) -> None:
        self._retire(seq, "error")
        req = seq.request
        if not req.future.done():
            req.future.set_exception(exc)
        # plain put (see _fail_all): the unbounded queue makes Full
        # impossible, and a silent drop here would hang a stream consumer
        req.queue.put(exc)
        # the request is dead: its sibling rows must not keep decoding
        # (or holding KV blocks) for a client that already got the error
        # — _drop_cancelled sweeps them at the next tick
        req.cancelled = True

    # -- accounting --------------------------------------------------------

    def _publish(self, admitted: int, retired: int, kind: str,
                 tokens: int, duration_s: float,
                 detail: Optional[Dict[str, Any]] = None) -> None:
        alloc = self._allocator
        used = alloc.used if alloc is not None else 0
        total = alloc.capacity if alloc is not None else 0
        hw = alloc.high_water if alloc is not None else 0
        with self._lock:
            waiting = len(self._waiting) + len(self._arrivals)
        inflight = len(self._active) + len(self._prefilling)
        RECORDER.set_gen_scheduler(
            inflight=inflight, waiting=waiting, blocks_used=used,
            blocks_total=total, blocks_high_water=hw,
        )
        RECORDER.set_kv_slots(
            active=used * self.block_size,
            reserved=(total - used) * self.block_size,
        )
        # idle spins included: steps_total["idle"] + the /genperf duty
        # cycle make a hot-spinning scheduler visible (satellite of the
        # flight-recorder PR — idle used to be invisible here)
        RECORDER.record_gen_step(kind)
        # a traced sequence in this step tags the record so the step's
        # seldon_tpu_dispatch_seconds observation carries its trace_id as
        # an OpenMetrics exemplar — on a decode replica that is the
        # handoff's trace, so exemplars join handoffs to federated traces
        trace_id = ""
        if kind != "idle":
            from seldon_core_tpu.utils.tracing import TRACER

            if TRACER.enabled:
                for s in self._active + self._prefilling:
                    ctx = getattr(s.request, "trace_ctx", None)
                    if ctx is not None and ctx.sampled:
                        trace_id = ctx.trace_id
                        break
        SPINE.record_gen_step(
            kind=kind, duration_s=duration_s, active=inflight,
            waiting=waiting, admitted=admitted, retired=retired,
            blocks_used=used, blocks_total=total, tokens=tokens,
            executable="" if kind == "idle" else f"gen_step:{kind}",
            trace_id=trace_id, detail=detail,
        )
