"""REST servers (aiohttp) — external prediction API + internal microservice API.

External (per-predictor engine, mirroring engine RestClientController.java):
  POST /api/v0.1/predictions   JSON body or form field ``json=``
  POST /api/v0.1/feedback
  GET  /ping /ready /pause /unpause (admin drain,
       engine RestClientController.java:57-99)
  GET  /prometheus             metric exposition
  GET  /stats                  flight-recorder JSON snapshot (batcher,
       latency percentiles, generation telemetry — utils/telemetry.py)

Internal (single-unit microservice, mirroring wrappers/python/
model_microservice.py REST routes):
  POST /predict /transform-input /transform-output /route /aggregate
       /send-feedback

Both accept the reference's form-encoded ``json=`` convention
(engine InternalPredictionService.java:240-242) as well as a plain JSON body.
"""

from __future__ import annotations

import json
from typing import Optional

from aiohttp import web

from seldon_core_tpu.graph.interpreter import InProcessNodeRuntime
from seldon_core_tpu.graph.spec import GraphSpecError
from seldon_core_tpu.messages import (
    Feedback,
    SeldonMessage,
    SeldonMessageError,
    SeldonMessageList,
)
from seldon_core_tpu.runtime.engine import EngineService
from seldon_core_tpu.runtime.resilience import (
    DEADLINE_HEADER,
    current_deadline,
    deadline_ms_header,
    maybe_deadline_scope,
)
from seldon_core_tpu.utils.metrics import CONTENT_TYPE_LATEST
from seldon_core_tpu.utils.tracing import (
    TRACEPARENT_HEADER,
    parse_traceparent,
    trace_scope,
)

__all__ = ["make_engine_app", "make_unit_app", "serve_app"]

#: binary tensor wire contract (runtime/wire.py)
_WIRE_CTYPE = "application/x-seldon-tensor"


async def _payload_text(request: web.Request) -> str:
    """JSON body or form-encoded ``json=`` field.  curl sends
    ``application/x-www-form-urlencoded`` by default even for raw JSON
    bodies, so a form without a ``json`` field falls back to the raw body."""
    body = await request.read()
    ctype = request.content_type or ""
    if "form" in ctype:
        from urllib.parse import parse_qs

        form = parse_qs(body.decode("utf-8", "replace"), keep_blank_values=True)
        if "json" in form:
            return form["json"][0]
    return body.decode("utf-8", "replace")


def _msg_response(msg: SeldonMessage, status: int = 200) -> web.Response:
    return web.Response(
        text=msg.to_json(), status=status, content_type="application/json"
    )


def _error_response(info: str, code: int = 400) -> web.Response:
    return _msg_response(SeldonMessage.failure(info, code=code), status=code)


def _request_budget_s(request: web.Request) -> Optional[float]:
    """Deadline budget from the ``Seldon-Deadline-Ms`` header (None when
    absent/malformed — resilience layer, gRPC-style deadline
    propagation)."""
    return deadline_ms_header(request.headers.get(DEADLINE_HEADER))


def _request_trace_scope(request: web.Request):
    """Adopt the caller's W3C ``traceparent`` context (None/malformed →
    fresh trace) so this process's spans join the caller's tree."""
    return trace_scope(parse_traceparent(request.headers.get(TRACEPARENT_HEADER)))


def _request_qos_scope(request: web.Request):
    """Adopt the caller's tenant/tier identity (``Seldon-Tenant`` /
    ``Seldon-Tier`` — the gateway forwards both) so engine-side
    admission, the brownout ladder and the genserver's tier lanes see
    the same QoS identity the ingress resolved."""
    from seldon_core_tpu.runtime.qos import (
        TENANT_HEADER,
        TIER_HEADER,
        qos_scope,
    )

    return qos_scope(request.headers.get(TENANT_HEADER),
                     request.headers.get(TIER_HEADER))


async def _quality_reference(request: web.Request) -> web.Response:
    """POST /quality/reference — freeze/reset the drift reference window
    (one handler shared by the engine and unit apps; the fast lane
    adapts the same parse in httpfast.py)."""
    from seldon_core_tpu.utils.quality import QUALITY, parse_reference_action

    try:
        action, node = parse_reference_action(
            await request.read(),
            request.query.get("action"), request.query.get("node"),
        )
    except ValueError as e:
        return _error_response(str(e))
    return web.json_response(QUALITY.reference_control(action, node=node))


# ---------------------------------------------------------------------------
# Engine app
# ---------------------------------------------------------------------------


def make_engine_app(engine: EngineService) -> web.Application:
    app = web.Application(client_max_size=256 * 1024 * 1024)

    async def predictions(request: web.Request) -> web.Response:
        if (request.content_type or "") == _WIRE_CTYPE:
            return await predictions_wire(request)
        try:
            with _request_trace_scope(request), \
                    maybe_deadline_scope(_request_budget_s(request)), \
                    _request_qos_scope(request):
                text, status = await engine.predict_json(
                    await _payload_text(request)
                )
        except SeldonMessageError as e:
            return _error_response(str(e), code=e.http_code)
        return web.Response(
            text=text, status=status or 200, content_type="application/json"
        )

    async def predictions_wire(request: web.Request) -> web.Response:
        """``Content-Type: application/x-seldon-tensor`` — the binary
        tensor wire contract (runtime/wire.py): frame in, frame out, no
        JSON round trip.  Header-bound deadline/trace/QoS still apply
        (the frame sidecar tightens/joins them, never loosens)."""
        from seldon_core_tpu.runtime import wire
        from seldon_core_tpu.utils.telemetry import RECORDER

        if not wire.wire_enabled():
            return _error_response(
                "binary wire lane disabled (SELDON_TPU_WIRE=0)", code=415
            )
        body = await request.read()
        RECORDER.record_wire_request("rest", "binary")
        wire.account_copy(len(body))
        try:
            with _request_trace_scope(request), \
                    maybe_deadline_scope(_request_budget_s(request)), \
                    _request_qos_scope(request):
                status, parts = await engine.predict_wire(body)
        except wire.WireError as e:
            # unparseable bytes answer as JSON the peer can always read
            return _error_response(str(e), code=e.http_code)
        except SeldonMessageError as e:
            return _error_response(str(e), code=e.http_code)
        return web.Response(
            body=wire.join_parts(parts), status=status,
            content_type=_WIRE_CTYPE,
        )

    async def predict_alias(request: web.Request) -> web.Response:
        # internal-API alias: an engine IS a model from a parent graph's
        # perspective (the gRPC lane's Model/Predict alias, grpc_server.py)
        # — POST /predict lets a RestNodeRuntime dial an engine as a MODEL
        # leaf of a larger cross-process graph
        return await predictions(request)

    async def feedback(request: web.Request) -> web.Response:
        try:
            with _request_trace_scope(request), \
                    maybe_deadline_scope(_request_budget_s(request)):
                fb = Feedback.from_json(await _payload_text(request))
                ack = await engine.send_feedback(fb)
        except SeldonMessageError as e:
            return _error_response(str(e), code=e.http_code)
        status = 200 if ack.status is None or ack.status.status == "SUCCESS" else ack.status.code
        return _msg_response(ack, status=status or 200)

    async def ping(_): return web.Response(text="pong")

    async def ready(_):
        if not engine.ready():
            return web.Response(text="paused", status=503)
        open_breakers = engine.open_breakers()
        if open_breakers:
            # still ready (the graph serves, degraded) but the condition is
            # surfaced where orchestration probes look first
            return web.Response(
                text="ready (breakers open: %s)" % ",".join(open_breakers)
            )
        return web.Response(text="ready")

    async def pause(_):
        engine.pause()
        return web.Response(text="paused")

    async def unpause(_):
        engine.unpause()
        return web.Response(text="unpaused")

    async def prometheus(request: web.Request):
        # CONTENT_TYPE_LATEST carries the exposition-format version parameter;
        # aiohttp's content_type= kwarg rejects parameters, so set the header.
        # OpenMetrics (Accept-negotiated, or ?format=openmetrics for lane
        # parity with httpfast) carries the trace_id exemplars on
        # seldon_tpu_dispatch_seconds buckets
        openmetrics = (
            "application/openmetrics-text" in request.headers.get("Accept", "")
            or request.query.get("format") == "openmetrics"
        )
        from seldon_core_tpu.utils.metrics import OPENMETRICS_CONTENT_TYPE

        return web.Response(
            body=engine.metrics.exposition(openmetrics=openmetrics),
            headers={"Content-Type": (
                OPENMETRICS_CONTENT_TYPE if openmetrics else CONTENT_TYPE_LATEST
            )},
        )

    async def stats(_):
        # flight-recorder snapshot: batcher/bucket state, latency
        # percentiles, generation SLO telemetry — zero-dependency JSON
        return web.json_response(engine.stats())

    async def perf(_):
        # performance observatory: per-executable cost/MFU/roofline table
        # + HBM watermarks (utils/perf.py; docs/operations.md runbook)
        return web.json_response(engine.perf_document())

    async def genperf(_):
        # generation-lane flight recorder: per-tick latency percentiles,
        # host/device phase splits, bubble ledger, served decode MFU,
        # KV-block residency (utils/genperf.py; docs/operations.md
        # "reading the /genperf page" runbook)
        return web.json_response(engine.genperf_document())

    async def quality(_):
        # prediction-quality observatory: per-node drift table, feedback
        # reward/accuracy, outlier bridge, SLO burn rates
        # (utils/quality.py; docs/operations.md runbook)
        return web.json_response(engine.quality_document())

    async def overhead(_):
        # telemetry overhead budget: per-subsystem framework-time
        # decomposition from the fused hop records (utils/hotrecord.py;
        # docs/operations.md "telemetry overhead budget" runbook)
        return web.json_response(engine.overhead_document())

    async def autopilot(_):
        # learned cost-model autopilot: per-executable/pad-bucket latency
        # model table, knobs, misprediction distribution, shed counters
        # (runtime/autopilot.py; docs/operations.md runbook)
        return web.json_response(engine.autopilot_document())

    async def corpus(_):
        # durable perf corpus: per-key quantile sketches + segment state
        # (utils/perfcorpus.py; docs/operations.md runbook)
        return web.json_response(engine.corpus_document())

    async def costs(_):
        # resource-attribution ledger: per-tenant/deployment/phase
        # device-seconds, pad tax, KV-block-seconds, capacity
        # (utils/costledger.py; docs/operations.md runbook)
        return web.json_response(engine.costs_document())

    async def postmortems(request: web.Request) -> web.Response:
        # tail-sampled worst-request exemplars with automatic explainers
        # (utils/postmortem.py); ?puid= returns one full document
        return web.json_response(engine.postmortems_document(
            puid=request.query.get("puid", "")))

    async def trace(request: web.Request) -> web.Response:
        from seldon_core_tpu.utils.tracing import TRACER, trace_document

        return web.json_response(trace_document(
            TRACER,
            puid=request.query.get("puid", ""),
            trace_id=request.query.get("trace_id", ""),
            limit=int(request.query.get("limit", "100")),
        ))

    async def trace_export(request: web.Request) -> web.Response:
        # Chrome trace-event JSON — load in Perfetto / chrome://tracing.
        # The process track is named replica/role so exports merged
        # across the mesh (the gateway's federated export) read legibly
        from seldon_core_tpu.utils.tracing import TRACER, export_document

        return web.json_response(export_document(
            TRACER,
            puid=request.query.get("puid", ""),
            trace_id=request.query.get("trace_id", ""),
            limit=int(request.query.get("limit", "1000")),
            process_name=engine.process_track_name(),
        ))

    async def trace_enable(_):
        from seldon_core_tpu.utils.tracing import TRACER

        TRACER.enable()
        return web.Response(text="tracing enabled")

    async def trace_disable(_):
        from seldon_core_tpu.utils.tracing import TRACER

        TRACER.disable()
        return web.Response(text="tracing disabled")

    async def profile_start(request: web.Request) -> web.Response:
        # the per-engine half of a coordinated fleet profile window
        # (gateway/fleet.py): open a bounded jax.profiler trace in THIS
        # process; overlapping windows answer 409, never queue
        from seldon_core_tpu.utils.tracing import (
            ProfileBusyError,
            profile_window_start_request,
        )

        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 - empty body = defaults
            body = {}
        if not isinstance(body, dict):
            body = {}
        try:
            return web.json_response(profile_window_start_request(body))
        except ProfileBusyError as e:
            return web.json_response({"error": str(e)}, status=409)

    async def profile_stop(_):
        from seldon_core_tpu.utils.tracing import profile_window_stop

        return web.json_response(profile_window_stop())

    async def profile_get(_):
        from seldon_core_tpu.utils.tracing import profile_window_status

        return web.json_response(profile_window_status())

    async def generate_stream(request: web.Request):
        """SSE token streaming (beyond-reference; see engine.generate_stream).
        Payload = SeldonMessage prompt + optional top-level ``chunk``."""
        try:  # full validation BEFORE any bytes: problems are a plain 400
            text, chunk = engine.prepare_stream_request(
                await _payload_text(request)
            )
        except SeldonMessageError as e:
            return _error_response(str(e))
        # tier rides task-locally for the stream's lifetime so the
        # genserver admits it on the right lane (runtime/qos.py)
        from seldon_core_tpu.runtime.qos import (
            TENANT_HEADER,
            TIER_HEADER,
            bind_qos,
        )

        bind_qos(request.headers.get(TENANT_HEADER),
                 request.headers.get(TIER_HEADER))
        agen = engine.generate_stream(text, chunk=chunk)
        # prime the generator BEFORE the 200 goes out: genserver
        # admission sheds (brownout tier shed, SELDON_TPU_GEN_MAX_WAITING
        # bound) raise on the first __anext__, and the shed contract
        # promises a typed retryable 503 — not a 200 with an in-band
        # error frame that status-code retry logic can never see
        first = None
        try:
            first = await agen.__anext__()
        except StopAsyncIteration:
            pass
        except SeldonMessageError as e:
            await agen.aclose()
            return _error_response(str(e), code=e.http_code)
        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"},
        )
        await resp.prepare(request)
        try:
            if first is not None:
                await resp.write(b"data: " + first.encode() + b"\n\n")
            async for event in agen:
                await resp.write(b"data: " + event.encode() + b"\n\n")
        except Exception as e:  # mid-stream: terminal error frame
            import json as _json

            await resp.write(
                b'data: {"done": true, "error": %s}\n\n'
                % _json.dumps(str(e)).encode()
            )
        finally:
            await agen.aclose()
        await resp.write_eof()
        return resp

    async def events(_):
        # documented external surface, stubbed exactly like the reference
        # (engine RestClientController.java:177-180 returns "Not
        # Implemented" with 200 on any method)
        return web.Response(text="Not Implemented")

    app.router.add_post("/api/v0.1/predictions", predictions)
    app.router.add_post("/predict", predict_alias)
    app.router.add_post("/api/v0.1/feedback", feedback)
    app.router.add_post("/api/v0.1/generate/stream", generate_stream)
    app.router.add_route("*", "/api/v0.1/events", events)
    app.router.add_get("/ping", ping)
    app.router.add_get("/ready", ready)
    app.router.add_get("/pause", pause)
    app.router.add_get("/unpause", unpause)
    app.router.add_get("/prometheus", prometheus)
    app.router.add_get("/stats", stats)
    app.router.add_get("/perf", perf)
    app.router.add_get("/genperf", genperf)
    app.router.add_get("/quality", quality)
    app.router.add_get("/overhead", overhead)
    app.router.add_get("/autopilot", autopilot)
    app.router.add_get("/corpus", corpus)
    app.router.add_get("/costs", costs)
    app.router.add_get("/postmortems", postmortems)
    app.router.add_post("/quality/reference", _quality_reference)
    app.router.add_get("/trace", trace)
    app.router.add_get("/trace/export", trace_export)
    # POST-only: the PR-3 deprecation window for the GET mutation aliases
    # is closed — GET /trace/enable|disable now answers 405
    app.router.add_post("/trace/enable", trace_enable)
    app.router.add_post("/trace/disable", trace_disable)
    app.router.add_get("/profile", profile_get)
    app.router.add_post("/profile/start", profile_start)
    app.router.add_post("/profile/stop", profile_stop)
    return app


# ---------------------------------------------------------------------------
# Unit (microservice) app
# ---------------------------------------------------------------------------


def make_unit_app(runtime: InProcessNodeRuntime) -> web.Application:
    """Serve one unit over the internal microservice API — what
    ``microservice.py <UserClass> REST`` builds in the reference."""
    app = web.Application(client_max_size=256 * 1024 * 1024)

    def handler(method_name):
        async def handle(request: web.Request) -> web.Response:
            import time as _time

            from seldon_core_tpu.utils.telemetry import RECORDER

            t0 = _time.perf_counter()
            try:
                # deadline propagation: the engine's node client forwards the
                # remaining request budget; nested work in this unit (and a
                # unit that is itself an engine facade) draws from it.  The
                # traceparent metadata makes this unit's spans children of
                # the engine's client span — one tree across processes
                with _request_trace_scope(request), \
                        maybe_deadline_scope(_request_budget_s(request)):
                    dl = current_deadline()
                    if dl is not None and dl.expired:
                        return _error_response(
                            "request deadline exhausted on arrival", code=504
                        )
                    return await _dispatch(method_name, request)
            except (SeldonMessageError, GraphSpecError) as e:
                return _error_response(str(e), code=getattr(e, "http_code", 400))
            except NotImplementedError as e:
                return _error_response(str(e), code=501)
            finally:
                RECORDER.request_latency(
                    f"unit:{method_name}", _time.perf_counter() - t0
                )

        return handle

    async def _dispatch(method_name: str, request: web.Request) -> web.Response:
        from seldon_core_tpu.utils.tracing import TRACER, current_trace_puid

        text = await _payload_text(request)
        if method_name == "aggregate":
            msgs = SeldonMessageList.from_json(text)
            puid = current_trace_puid() or (
                msgs.messages[0].meta.puid if msgs.messages else ""
            )
            with TRACER.span(puid, runtime.node.name, kind="server",
                             method=method_name):
                resp = await runtime.aggregate(msgs.messages)
        elif method_name == "send_feedback":
            fb = Feedback.from_json(text)
            routing = (
                fb.response.meta.routing if fb.response is not None else {}
            )
            branch = int(routing.get(runtime.node.name, -1))
            with TRACER.span(fb.puid() or current_trace_puid(),
                             runtime.node.name,
                             kind="server", method=method_name):
                await runtime.send_feedback(fb, branch)
            resp = SeldonMessage()
        elif method_name == "route":
            msg = SeldonMessage.from_json(text)
            with TRACER.span(msg.meta.puid, runtime.node.name, kind="server",
                             method=method_name) as sp:
                branch = await runtime.route(msg)
                if isinstance(sp, dict):
                    sp["branch"] = branch
            # branch wrapped as 1x1 tensor like the reference wrapper
            # (wrappers/python/router_microservice.py:39-56)
            import numpy as np

            resp = msg.with_array(np.array([[branch]], dtype=np.float64))
        else:
            msg = SeldonMessage.from_json(text)
            with TRACER.span(msg.meta.puid, runtime.node.name, kind="server",
                             method=method_name):
                resp = await getattr(runtime, method_name)(msg)
        return _msg_response(resp)

    app.router.add_post("/predict", handler("predict"))
    app.router.add_post("/transform-input", handler("transform_input"))
    app.router.add_post("/transform-output", handler("transform_output"))
    app.router.add_post("/route", handler("route"))
    app.router.add_post("/aggregate", handler("aggregate"))
    app.router.add_post("/send-feedback", handler("send_feedback"))

    async def ping(_): return web.Response(text="pong")

    async def stats(_):
        # unit pods carry the process-level flight recorder too (compile
        # cache, generation telemetry of in-unit generators)
        from seldon_core_tpu.utils.telemetry import RECORDER

        return web.json_response({
            "unit": {"name": runtime.node.name,
                     "type": getattr(runtime.node.type, "name", None)},
            "telemetry": RECORDER.snapshot(),
        })

    async def perf(_):
        # unit pods own a TPU runtime too: whatever this process compiled
        # and dispatched shows up in its process-global observatory
        from seldon_core_tpu.utils.perf import OBSERVATORY

        return web.json_response({
            "unit": {"name": runtime.node.name,
                     "type": getattr(runtime.node.type, "name", None)},
            **OBSERVATORY.document(),
        })

    async def quality(_):
        # per-node drift windows recorded by InProcessNodeRuntime.predict
        # land in the process-global quality observatory
        from seldon_core_tpu.utils.quality import QUALITY

        return web.json_response({
            "unit": {"name": runtime.node.name,
                     "type": getattr(runtime.node.type, "name", None)},
            **QUALITY.document(),
        })

    async def overhead(_):
        # unit pods carry the process-global telemetry spine too
        from seldon_core_tpu.utils.hotrecord import SPINE

        return web.json_response({
            "unit": {"name": runtime.node.name,
                     "type": getattr(runtime.node.type, "name", None)},
            **SPINE.overhead_document(),
        })

    async def autopilot(_):
        # whatever this unit process dispatched trains the process-global
        # cost model; its table is inspectable on unit pods too
        from seldon_core_tpu.runtime.autopilot import AUTOPILOT
        from seldon_core_tpu.utils.hotrecord import SPINE

        SPINE.drain()
        return web.json_response({
            "unit": {"name": runtime.node.name,
                     "type": getattr(runtime.node.type, "name", None)},
            **AUTOPILOT.document(),
        })

    app.router.add_get("/ping", ping)
    app.router.add_get("/stats", stats)
    app.router.add_get("/perf", perf)
    app.router.add_get("/quality", quality)
    app.router.add_get("/overhead", overhead)
    app.router.add_get("/autopilot", autopilot)
    app.router.add_post("/quality/reference", _quality_reference)
    return app


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


async def serve_app(app: web.Application, host: str, port: int):
    """Start an app; returns the runner (caller is responsible for cleanup)."""
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    return runner
