"""Firehose replay — vet a candidate predictor on recorded traffic before
it ever sees a user.

The gateway's audit firehose (gateway/firehose.py, PR 1) keeps one JSONL
line per served request: ``{puid, deployment, ts, request, response}``.
This module replays those lines against a *candidate* predictor and diffs
every answer against the recorded live one:

  * **prediction disagreement** — ``messages.prediction_delta``, the same
    rule the shadow mirror applies to live traffic, so an offline verdict
    and a live shadow read on the same scale;
  * **error delta** — recorded FAILURE rate vs the candidate's;
  * **latency** — the candidate's own percentiles (recorded lines carry
    no latency, so there is nothing dishonest to compare against);
  * **prediction drift** — PSI between the recorded and candidate
    prediction distributions (utils/quality.py ``psi`` over a shared
    histogram), i.e. "would the quality observatory have paged".

Pacing: ``max`` replays as fast as the candidate admits (``concurrency``
in flight), ``recorded`` honors the recorded inter-arrival gaps scaled by
``speed`` (2.0 = twice as fast — the time-warp knob).

The outcome is a **verdict artifact** (JSON): counters, percentiles, the
gates that were checked, and ``verdict: "pass"|"fail"`` with the breached
reasons — the document a rollout pipeline checks before ever granting a
candidate stage 1 of live traffic (operator/rollouts.py).

Targets: an in-process engine-like object (anything with ``async
predict(SeldonMessage)``), a base URL (the engine REST contract,
``POST /api/v0.1/predictions``), or a deployment spec file + predictor
name (boots a throwaway in-process EngineService).  CLI::

    python -m seldon_core_tpu.runtime.replay firehose.jsonl \
        --spec examples/canary_deployment.json --predictor canary \
        --out replay_verdict.json [--pace recorded --speed 10]
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from seldon_core_tpu.messages import (
    SeldonMessage,
    SeldonMessageError,
    prediction_delta,
)
from seldon_core_tpu.utils.telemetry import Reservoir

__all__ = ["ReplayGates", "ReplayTarget", "replay_events", "replay_file",
           "load_firehose_events"]


@dataclass
class ReplayGates:
    """Verdict thresholds; None disables a gate."""

    max_disagreement: Optional[float] = 0.02   # mean per-request disagree
    max_error_rate_delta: Optional[float] = 0.01
    max_prediction_psi: Optional[float] = 0.25
    max_latency_p50_ms: Optional[float] = None
    min_requests: int = 10

    def to_json_dict(self) -> dict:
        return {
            "max_disagreement": self.max_disagreement,
            "max_error_rate_delta": self.max_error_rate_delta,
            "max_prediction_psi": self.max_prediction_psi,
            "max_latency_p50_ms": self.max_latency_p50_ms,
            "min_requests": self.min_requests,
        }


class ReplayTarget:
    """Uniform async predict over the three target shapes."""

    def __init__(self, target: Any):
        self.target = target
        self._session = None

    @property
    def kind(self) -> str:
        return "inprocess" if hasattr(self.target, "predict") else "http"

    async def predict(self, msg: SeldonMessage) -> SeldonMessage:
        if self.kind == "inprocess":
            return await self.target.predict(msg)
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        url = str(self.target).rstrip("/") + "/api/v0.1/predictions"
        try:
            async with self._session.post(
                url, data=msg.to_json(),
                timeout=aiohttp.ClientTimeout(total=30),
            ) as r:
                return SeldonMessage.from_json(await r.text())
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            return SeldonMessage.failure(
                f"candidate unreachable: {e}", code=503
            )
        except SeldonMessageError as e:
            return SeldonMessage.failure(
                f"candidate answered garbage: {e}", code=502
            )

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


def load_firehose_events(path: str,
                         deployment: Optional[str] = None,
                         limit: Optional[int] = None) -> List[dict]:
    """Parse a firehose JSONL file into replayable events — request lines
    only (control-plane events like rollbacks carry no request), oldest
    first, optionally filtered by deployment."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line mid-write: skip, like the consumer
            if "request" not in ev or "response" not in ev:
                continue
            if deployment is not None and ev.get("deployment") != deployment:
                continue
            events.append(ev)
            if limit is not None and len(events) >= limit:
                break
    return events


def _prediction_rows(msg: Optional[SeldonMessage]) -> Optional[np.ndarray]:
    if msg is None or msg.data is None:
        return None
    try:
        arr = np.asarray(msg.array(), dtype=np.float64)
    except (SeldonMessageError, ValueError):
        return None
    return arr if arr.size else None


def _prediction_psi(recorded: List[np.ndarray],
                    candidate: List[np.ndarray]) -> Optional[float]:
    """PSI between the two prediction-value distributions over a shared
    histogram whose edges come from the RECORDED side's quantiles — the
    exact framing the quality observatory uses for prediction drift."""
    from seldon_core_tpu.utils.quality import psi

    if not recorded or not candidate:
        return None
    ref = np.concatenate([r.ravel() for r in recorded])
    live = np.concatenate([c.ravel() for c in candidate])
    if ref.size < 8 or live.size < 8:
        return None
    edges = np.quantile(ref, np.linspace(0.0, 1.0, 11)[1:-1])
    edges = np.unique(edges)
    if edges.size == 0:
        return 0.0 if np.allclose(ref.mean(), live.mean()) else None
    ref_counts = np.histogram(ref, bins=np.concatenate(
        ([-np.inf], edges, [np.inf])))[0]
    live_counts = np.histogram(live, bins=np.concatenate(
        ([-np.inf], edges, [np.inf])))[0]
    return float(np.sum(psi(
        ref_counts[None, :], live_counts[None, :]
    )))


async def replay_events(
    events: List[dict],
    target: Any,
    pace: str = "max",
    speed: float = 1.0,
    concurrency: int = 8,
    gates: Optional[ReplayGates] = None,
) -> dict:
    """Replay ``events`` against ``target`` and return the verdict
    document.  ``pace="recorded"`` honors recorded inter-arrival gaps
    divided by ``speed``; ``pace="max"`` keeps ``concurrency`` requests
    in flight."""
    if pace not in ("max", "recorded"):
        raise ValueError(f"pace must be 'max' or 'recorded', got {pace!r}")
    gates = gates or ReplayGates()
    rt = target if isinstance(target, ReplayTarget) else ReplayTarget(target)
    latency_ms = Reservoir(4096)
    disagreement = Reservoir(4096)
    recorded_preds: List[np.ndarray] = []
    candidate_preds: List[np.ndarray] = []
    counts = {
        "replayed": 0, "recorded_errors": 0, "candidate_errors": 0,
        "incomparable": 0, "disagreed": 0,
    }
    sem = asyncio.Semaphore(max(int(concurrency), 1))

    async def one(ev: dict) -> None:
        try:
            req = SeldonMessage.from_json_dict(ev["request"])
            recorded = SeldonMessage.from_json_dict(ev["response"])
        except (SeldonMessageError, TypeError, KeyError):
            counts["incomparable"] += 1
            return
        async with sem:
            t0 = time.perf_counter()
            cand = await rt.predict(req)
            latency_ms.observe((time.perf_counter() - t0) * 1e3)
        counts["replayed"] += 1
        rec_err = recorded.status is not None and \
            recorded.status.status == "FAILURE"
        cand_err = cand.status is not None and \
            cand.status.status == "FAILURE"
        if rec_err:
            counts["recorded_errors"] += 1
        if cand_err:
            counts["candidate_errors"] += 1
        # recorded UNCONDITIONALLY, same rationale as the shadow mirror:
        # matched failures agree (0.0), a contract break (shape/kind
        # mismatch, one-sided failure) is maximal divergence (1.0) — a
        # candidate that changes the output shape must fail the vet, not
        # fall out of the disagreement window
        delta = prediction_delta(recorded, cand)
        disagreement.observe(delta["disagree"])
        if delta["disagree"] > 0:
            counts["disagreed"] += 1
        if not delta["comparable"] and not (rec_err or cand_err):
            counts["incomparable"] += 1  # contract mismatch, not errors
        rp, cp = _prediction_rows(recorded), _prediction_rows(cand)
        if rp is not None:
            recorded_preds.append(rp)
        if cp is not None:
            candidate_preds.append(cp)

    t_start = time.perf_counter()
    try:
        if pace == "recorded":
            base_ts = events[0].get("ts", 0.0) if events else 0.0
            t0 = time.perf_counter()
            pending = []
            for ev in events:
                offset = max(ev.get("ts", base_ts) - base_ts, 0.0) / max(
                    speed, 1e-6
                )
                delay = offset - (time.perf_counter() - t0)
                if delay > 0:
                    await asyncio.sleep(delay)
                pending.append(asyncio.ensure_future(one(ev)))
            if pending:
                await asyncio.gather(*pending)
        else:
            await asyncio.gather(*(one(ev) for ev in events))
    finally:
        if not isinstance(target, ReplayTarget):
            await rt.close()
    wall_s = time.perf_counter() - t_start

    replayed = counts["replayed"]
    dis = disagreement.snapshot()
    rec_rate = counts["recorded_errors"] / replayed if replayed else 0.0
    cand_rate = counts["candidate_errors"] / replayed if replayed else 0.0
    pred_psi = _prediction_psi(recorded_preds, candidate_preds)

    reasons = []
    if replayed < gates.min_requests:
        reasons.append(
            f"insufficient_traffic: {replayed} < {gates.min_requests}"
        )
    if gates.max_disagreement is not None and \
            dis["mean"] > gates.max_disagreement:
        reasons.append(
            f"disagreement: mean {dis['mean']:.4f} > "
            f"{gates.max_disagreement}"
        )
    if gates.max_error_rate_delta is not None and \
            (cand_rate - rec_rate) > gates.max_error_rate_delta:
        reasons.append(
            f"error_rate: candidate {cand_rate:.4f} vs recorded "
            f"{rec_rate:.4f}"
        )
    if gates.max_prediction_psi is not None and pred_psi is not None and \
            pred_psi > gates.max_prediction_psi:
        reasons.append(
            f"prediction_psi: {pred_psi:.4f} > {gates.max_prediction_psi}"
        )
    lat = latency_ms.snapshot()
    if gates.max_latency_p50_ms is not None and replayed and \
            lat["p50"] > gates.max_latency_p50_ms:
        reasons.append(
            f"latency: p50 {lat['p50']:.1f}ms > {gates.max_latency_p50_ms}"
        )

    return {
        "verdict": "pass" if not reasons else "fail",
        "reasons": reasons,
        "target": rt.kind,
        "pace": pace,
        "speed": speed,
        "wall_s": round(wall_s, 3),
        "replayed_per_s": round(replayed / wall_s, 1) if wall_s > 0 else None,
        "counts": counts,
        "disagreement": {
            "mean": round(dis["mean"], 6),
            "p95": round(dis["p95"], 6),
            "count": dis["count"],
        },
        "error_rate": {
            "recorded": round(rec_rate, 6),
            "candidate": round(cand_rate, 6),
        },
        "prediction_psi": (
            None if pred_psi is None else round(pred_psi, 6)
        ),
        "candidate_latency_ms": lat,
        "gates": gates.to_json_dict(),
    }


async def replay_file(path: str, target: Any, deployment: Optional[str] = None,
                      limit: Optional[int] = None, **kw) -> dict:
    events = load_firehose_events(path, deployment=deployment, limit=limit)
    doc = await replay_events(events, target, **kw)
    doc["source"] = {"path": path, "deployment": deployment,
                     "events": len(events)}
    return doc


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(
        description="replay a firehose log against a candidate predictor"
    )
    parser.add_argument("firehose", help="JSONL firehose file (gateway/"
                                         "firehose.py format)")
    parser.add_argument("--url", default=None,
                        help="candidate engine base URL")
    parser.add_argument("--spec", default=None,
                        help="deployment spec JSON: boot an in-process "
                             "candidate engine instead of dialing one")
    parser.add_argument("--predictor", default=None,
                        help="predictor name inside --spec")
    parser.add_argument("--deployment", default=None,
                        help="filter recorded lines to one deployment")
    parser.add_argument("--pace", choices=("max", "recorded"), default="max")
    parser.add_argument("--speed", type=float, default=1.0,
                        help="time-warp factor for --pace recorded")
    parser.add_argument("--concurrency", type=int, default=8)
    parser.add_argument("--limit", type=int, default=None)
    parser.add_argument("--max-disagreement", type=float, default=0.02)
    parser.add_argument("--max-error-rate-delta", type=float, default=0.01)
    parser.add_argument("--max-prediction-psi", type=float, default=0.25)
    parser.add_argument("--out", default=None,
                        help="write the verdict artifact here")
    args = parser.parse_args(argv)
    if bool(args.url) == bool(args.spec):
        raise SystemExit("exactly one of --url / --spec is required")

    async def run() -> dict:
        engine = None
        if args.spec is not None:
            from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
            from seldon_core_tpu.runtime.engine import EngineService

            with open(args.spec) as f:
                spec = SeldonDeploymentSpec.from_json_dict(json.load(f))
            engine = EngineService(spec, args.predictor)
            target: Any = engine
        else:
            target = args.url
        try:
            return await replay_file(
                args.firehose, target,
                deployment=args.deployment,
                limit=args.limit,
                pace=args.pace, speed=args.speed,
                concurrency=args.concurrency,
                gates=ReplayGates(
                    max_disagreement=args.max_disagreement,
                    max_error_rate_delta=args.max_error_rate_delta,
                    max_prediction_psi=args.max_prediction_psi,
                ),
            )
        finally:
            if engine is not None:
                await engine.close()

    doc = asyncio.run(run())
    text = json.dumps(doc, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    if doc["verdict"] != "pass":
        raise SystemExit(3)


if __name__ == "__main__":
    main()
