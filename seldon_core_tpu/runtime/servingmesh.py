"""Disaggregated prefill/decode serving mesh.

ROADMAP item 1: everything so far serves one smallish model per engine
on one chip, capping ``served_gen_tok_s`` at a single chip's decode
ceiling.  This module splits the generation lane into separately-scaled
replica pools (Podracer-style sheets of role-specialized workers, arxiv
2104.06272; the Gemma-serving workload of arxiv 2605.25645) and lets one
model span chips:

* **Roles** — ``engine_main --gen-role {prefill,decode,unified}`` boots
  role-specialized GenServers (runtime/genserver.py).  Prefill replicas
  run chunked cross-sequence prefill only; each finished sequence's KV
  blocks + sampling state export as a typed handoff.  Decode replicas
  import those blocks (reserve -> receive -> commit, torn handoffs
  reclaim) and run the continuous decode loop.  Unified replicas are
  bit-for-bit the PR-7 scheduler; ``SELDON_TPU_DISAGG=0`` forces every
  role back to unified — the kill switch.
* **The coordinator** — :class:`DisaggCoordinator` runs on the prefill
  side: it scores decode peers by FREE KV BLOCKS (scraped over the
  relay's KV_STATS frame, the same signal the /stats genserver block
  exposes), picks the handoff target power-of-two-choices, streams the
  blocks chunked over the PR-8 relay lane (runtime/kvstream.py wire
  format — length-prefixed tensor frames, no JSON/base64), and returns
  the decoded tokens to the waiting request.
* **Tensor-parallel dispatch** — :func:`shard_gen_pool` lays the paged
  KV pool out over a ``parallel.mesh`` device mesh (KV heads sharded
  over the ``tp`` axis when divisible) so the scheduler's compiled
  prefill/decode executables partition across chips together with the
  unit's mesh-sharded params (models/transformer.py param_shardings);
  on CPU test platforms the same code runs over
  ``jax_num_cpu_devices`` virtual devices and the compiled-vs-single-
  device tokens are pinned identical (tests/test_servingmesh.py).

Routing (gateway/balancer.py): replica endpoints carry a ``role``
attribute (``+role:prefill`` endpoint-spec suffix); client generation
traffic routes prefill-first — decode replicas never see a client
request, they only import handoffs.  A generation request at a
decode-only replica, a handoff at a non-decode replica, and a prefill
replica with no reachable decode peer all answer a typed retryable 503
(:class:`RoleMismatchError` / :class:`HandoffError`)."""

from __future__ import annotations

import logging
import os
import random
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from seldon_core_tpu.messages import SeldonMessageError
from seldon_core_tpu.runtime import kvstream
from seldon_core_tpu.utils.telemetry import RECORDER, Reservoir

__all__ = [
    "GEN_ROLES",
    "RoleMismatchError",
    "HandoffError",
    "disagg_enabled",
    "resolve_gen_role",
    "parse_decode_peers",
    "DisaggCoordinator",
    "resolve_gen_mesh",
    "shard_gen_pool",
]

logger = logging.getLogger(__name__)

GEN_ROLES = ("unified", "prefill", "decode")


class RoleMismatchError(SeldonMessageError):
    """A request landed on a replica whose generation role cannot serve
    it (generation at a decode-only replica, a KV handoff at a
    non-decode replica).  503: retryable — the right replica exists,
    routing just has to find it."""

    http_code = 503


class HandoffError(SeldonMessageError):
    """A prefill->decode handoff could not complete (no reachable peer,
    peer pool full on every candidate, stream torn).  503: retryable —
    another prefill replica, or the same one a moment later, may have a
    healthy peer."""

    http_code = 503


def disagg_enabled() -> bool:
    """Kill switch: ``SELDON_TPU_DISAGG=0`` forces every engine to the
    unified single-replica generation path, bit-for-bit PR 7."""
    return os.environ.get("SELDON_TPU_DISAGG", "1") != "0"


def resolve_gen_role(requested: Optional[str]) -> str:
    role = (requested or os.environ.get("ENGINE_GEN_ROLE", "")
            ).strip().lower() or "unified"
    if role not in GEN_ROLES:
        raise ValueError(
            f"unknown generation role {role!r} (expected one of "
            f"{GEN_ROLES})")
    if not disagg_enabled():
        return "unified"
    return role


def parse_decode_peers(raw: Optional[str] = None) -> List[str]:
    """``ENGINE_DECODE_PEERS`` — comma-separated relay specs
    (``uds:/path`` or ``tcp:host:port``) of the decode replicas a
    prefill replica may hand off to."""
    raw = raw if raw is not None else os.environ.get(
        "ENGINE_DECODE_PEERS", "")
    return [p.strip() for p in raw.split(",") if p.strip()]


# -- tensor-parallel dispatch -------------------------------------------

def resolve_gen_mesh(mesh_axes: Optional[Dict[str, int]] = None):
    """Build a device mesh for the generation lane: explicit axes, the
    ``SELDON_TPU_GEN_MESH`` env (``tp=2`` syntax), or None (single
    device — today's path)."""
    if mesh_axes is None:
        raw = os.environ.get("SELDON_TPU_GEN_MESH", "").strip()
        if not raw:
            return None
        mesh_axes = {}
        for part in raw.split(","):
            name, _, val = part.partition("=")
            mesh_axes[name.strip()] = int(val)
    from seldon_core_tpu.parallel.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec(dict(mesh_axes)))


def shard_gen_pool(mesh, pool):
    """Lay the paged KV pool out over the mesh: KV heads shard over the
    ``tp`` axis when divisible (each device holds its heads' blocks —
    attention per head stays device-local, so the compiled program's
    per-element math is unchanged and collectives are pure data
    movement), everything else replicates.  Composes with mesh-sharded
    params: GSPMD partitions the whole prefill/decode executable."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = "tp" if "tp" in mesh.axis_names else None
    tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1) \
        if axis else 1
    out = {}
    for li, layer in pool.items():
        new = {}
        for name, arr in layer.items():
            kv = arr.shape[2] if arr.ndim >= 3 else 0
            if axis and arr.ndim >= 3 and kv % tp == 0 and tp > 1:
                spec = P(*([None, None, axis] + [None] * (arr.ndim - 3)))
            else:
                spec = P()
            new[name] = jax.device_put(arr, NamedSharding(mesh, spec))
        out[li] = new
    return out


# -- the prefill-side coordinator ---------------------------------------

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class DisaggCoordinator:
    """Drives prefill->decode handoffs for one prefill-role GenServer.

    Owns a private asyncio loop on a daemon thread (the scheduler thread
    must never block on a peer); the scheduler submits finished-prefill
    exports and gets the decoded tokens back through a completion
    callback.  Peer choice is power-of-two-choices over the decode
    replicas' FREE-KV-BLOCK score (KV_STATS over the relay, cached
    ``SELDON_TPU_KV_STATS_TTL_S``) — the decode-side analogue of the
    gateway's p2c, with pool headroom as the load signal because KV
    residency, not CPU, is what a decode replica runs out of.

    A peer that refuses a BEGIN (pool full / role misconfig) costs one
    round trip and the next candidate is tried; a stream torn mid-flight
    sends a best-effort ABORT (the decode side's TTL reaper is the
    backstop) and the request fails typed + retryable."""

    def __init__(self, peers: List[str], *,
                 chunk_blocks: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 event_sink: Optional[Callable[..., None]] = None):
        if not peers:
            raise ValueError("DisaggCoordinator needs at least one peer")
        self.peers = list(peers)
        self.chunk_blocks = chunk_blocks or kvstream.chunk_blocks_default()
        self.timeout_s = timeout_s or _env_float(
            "SELDON_TPU_KV_HANDOFF_TIMEOUT_S", 120.0)
        self.stats_ttl_s = _env_float("SELDON_TPU_KV_STATS_TTL_S", 1.0)
        self._event_sink = event_sink
        self._rng = random.Random(0xD15A66)
        self._clients: Dict[str, Any] = {}
        self._free: Dict[str, "tuple[int, float]"] = {}  # peer -> (free, ts)
        self._lock = threading.Lock()
        self.handoffs: Dict[str, int] = {}
        self.inflight = 0
        self.bytes_total = 0
        self.tokens_total = 0
        self.latency_ms = Reservoir(512)
        #: rolling full-chain estimate (export+stream+remote decode) the
        #: engine's deadline-aware admission prices requests with
        self.chain_ewma_s = 0.0
        import asyncio

        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="disagg-coordinator",
            daemon=True)
        self._thread.start()

    # -- client surface (scheduler thread) ------------------------------

    def submit(self, export: kvstream.KvExport,
               done_cb: Callable[[Any], None]) -> None:
        """Fire one handoff; ``done_cb`` receives the decoded token
        array (np int32 [max_new]) or an exception, from the coordinator
        thread."""
        import asyncio

        asyncio.run_coroutine_threadsafe(
            self._handoff(export, done_cb), self._loop)

    def chain_estimate_s(self) -> Optional[float]:
        return self.chain_ewma_s or None

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            lat = self.latency_ms.snapshot()
            return {
                "peers": list(self.peers),
                "peer_free_blocks": {
                    p: f for p, (f, _) in self._free.items()
                },
                "handoffs": dict(self.handoffs),
                "inflight": self.inflight,
                "bytes_total": self.bytes_total,
                "tokens_total": self.tokens_total,
                "handoff_ms_p50": lat.get("p50"),
                "handoff_ms_p99": lat.get("p99"),
                "bytes_per_tok": (
                    round(self.bytes_total / self.tokens_total, 1)
                    if self.tokens_total else None
                ),
                "chain_ewma_ms": round(self.chain_ewma_s * 1e3, 3),
            }

    def close(self) -> None:
        import asyncio

        async def _shutdown():
            for c in self._clients.values():
                try:
                    await c.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            self._loop.stop()

        if self._loop.is_running():
            asyncio.run_coroutine_threadsafe(_shutdown(), self._loop)
            self._thread.join(timeout=5)
        if not self._loop.is_running():
            self._loop.close()

    # -- coordinator loop -------------------------------------------------

    def _client(self, peer: str):
        client = self._clients.get(peer)
        if client is None or client.closed:
            from seldon_core_tpu.runtime.udsrelay import make_relay_client

            client = make_relay_client(peer)
            self._clients[peer] = client
        return client

    def _account(self, outcome: str) -> None:
        with self._lock:
            self.handoffs[outcome] = self.handoffs.get(outcome, 0) + 1
        RECORDER.record_kv_handoff(outcome)

    async def _refresh_free(self, peer: str) -> int:
        """Cached free-KV-block score for one peer; a dead scrape scores
        it 0 (it still serves when every candidate is dead — the pick
        never fails on stale health alone)."""
        import asyncio

        now = time.monotonic()
        cached = self._free.get(peer)
        if cached is not None and now - cached[1] < self.stats_ttl_s:
            return cached[0]
        free = 0
        try:
            body, status = await asyncio.wait_for(
                self._client(peer).call(
                    _OP_KVSTREAM(), kvstream.stats_frame(),
                ), timeout=2.0,
            )
            if status == 200:
                free = kvstream.unpack_stats(body)["free"]
        except Exception:  # noqa: BLE001 - degraded peer scores 0
            free = 0
        with self._lock:
            self._free[peer] = (free, now)
        return free

    async def _pick_order(self) -> List[str]:
        """Peers in try-order: p2c by free-block score, remaining peers
        appended as fallbacks (a refused BEGIN walks down the list)."""
        if len(self.peers) == 1:
            return list(self.peers)
        i, j = self._rng.sample(range(len(self.peers)), 2)
        a, b = self.peers[i], self.peers[j]
        fa = await self._refresh_free(a)
        fb = await self._refresh_free(b)
        first, second = (a, b) if fa >= fb else (b, a)
        rest = [p for p in self.peers if p not in (first, second)]
        return [first, second] + rest

    @staticmethod
    def _handoff_meta(export: kvstream.KvExport) -> "bytes | None":
        """The relay metadata sidecar every frame of this handoff ships:
        the kv_handoff span's traceparent (so decode-side spans parent
        under it), plus tenant/tier for decode-side accounting.  None
        when there is nothing to carry — the wire bytes then match the
        sidecar-less PR-12 frames exactly."""
        from seldon_core_tpu.runtime.udsrelay import pack_relay_meta

        ctx = export.trace_ctx
        traceparent = None
        if ctx is not None and ctx.trace_id and ctx.span_id:
            traceparent = "00-%s-%s-01" % (ctx.trace_id, ctx.span_id)
        tenant = export.tenant or None
        tier = export.meta.tier or None
        if traceparent is None and tenant is None and \
                (tier in (None, "interactive")):
            return None
        return pack_relay_meta(
            traceparent=traceparent, tenant=tenant, tier=tier)

    def _record_handoff_span(self, export: kvstream.KvExport, peer: str,
                             nbytes: int, tokens: int, start_s: float,
                             wall_s: float, outcome: str) -> None:
        """The prefill-side ``kind="kv_handoff"`` span — recorded with
        the PRE-MINTED span id the sidecar already announced, so the
        decode replica's import/decode spans (recorded before this one
        finishes) land under it in the assembled federated tree."""
        from seldon_core_tpu.utils.tracing import TRACER, Span

        ctx = export.trace_ctx
        if ctx is None or not TRACER.enabled:
            return
        attrs = {
            "peer": peer or "", "bytes": int(nbytes),
            "tokens": int(tokens), "outcome": outcome,
        }
        TRACER.add(Span(
            puid=export.puid, name="kv_handoff", kind="kv_handoff",
            method="kv_handoff", start_s=start_s,
            duration_ms=wall_s * 1e3, attrs=attrs,
            trace_id=ctx.trace_id, span_id=ctx.span_id,
            parent_span_id=export.parent_span_id,
        ))

    async def _handoff(self, export: kvstream.KvExport, done_cb) -> None:
        t0 = time.perf_counter()
        start_epoch = time.time()
        with self._lock:
            self.inflight += 1
        RECORDER.set_kv_handoff_inflight(self.inflight)
        hid = uuid.uuid4().bytes
        trace_id = (export.trace_ctx.trace_id
                    if export.trace_ctx is not None else "")
        try:
            tokens, peer, nbytes = await self._stream(export, hid)
            wall = time.perf_counter() - t0
            with self._lock:
                self.inflight -= 1
                self.bytes_total += nbytes
                self.tokens_total += int(tokens.size)
                self.latency_ms.observe(wall * 1e3)
                a = 0.2
                self.chain_ewma_s = (
                    wall if self.chain_ewma_s == 0.0
                    else (1 - a) * self.chain_ewma_s + a * wall
                )
            self._account("ok")
            RECORDER.observe_kv_handoff(wall, nbytes)
            RECORDER.set_kv_handoff_inflight(self.inflight)
            self._record_handoff_span(
                export, peer, nbytes, int(tokens.size), start_epoch,
                wall, "ok")
            if self._event_sink is not None:
                try:
                    self._event_sink(
                        event="kv_handoff", peer=peer,
                        tokens=int(tokens.size), bytes=nbytes,
                        latency_ms=round(wall * 1e3, 3),
                        # join keys for firehose consumers: the trace the
                        # handoff belongs to + the request's identity
                        trace_id=trace_id, puid=export.puid,
                        tenant=export.tenant, tier=export.meta.tier,
                    )
                except Exception:  # noqa: BLE001 - sink must not fail the hop
                    pass
            done_cb(tokens)
        except Exception as e:  # noqa: BLE001 - surfaced typed per request
            wall = time.perf_counter() - t0
            with self._lock:
                self.inflight -= 1
            outcome = "torn" if isinstance(e, ConnectionError) else "error"
            self._account(outcome)
            RECORDER.set_kv_handoff_inflight(self.inflight)
            self._record_handoff_span(
                export, "", 0, 0, start_epoch, wall, outcome)
            if isinstance(e, SeldonMessageError):
                done_cb(e)
            else:
                done_cb(HandoffError(
                    f"prefill->decode handoff failed: {e}"))

    async def _stream(self, export: kvstream.KvExport, hid: bytes):
        """BEGIN on the best peer (walking the p2c order on refusals),
        then the chunked block stream and the COMMIT that answers with
        the decoded tokens."""
        import asyncio

        order = await self._pick_order()
        begin = kvstream.begin_frame(export, hid)
        # deadline/trace/tenant sidecar: the BEGIN frame announces the
        # kv_handoff span's traceparent so the decode side's spans join
        # the federated tree; the COMMIT repeats it (the decode round
        # runs inside that call).  BLOCKS frames skip it — pure payload.
        meta = self._handoff_meta(export)
        client = None
        peer = None
        last_refusal = "no decode peers configured"
        for candidate in order:
            try:
                c = self._client(candidate)
                body, status = await asyncio.wait_for(
                    c.call(_OP_KVSTREAM(), begin, meta=meta),
                    timeout=10.0,
                )
            except Exception as e:  # noqa: BLE001 - dead peer: next one
                last_refusal = f"{candidate}: {e}"
                continue
            if status == 200:
                client, peer = c, candidate
                break
            last_refusal = (
                f"{candidate}: {body.decode('utf-8', 'replace')[:200]}")
            self._account("refused")
        if client is None:
            raise HandoffError(
                f"no decode peer accepted the handoff ({last_refusal})")
        nbytes = len(begin)
        try:
            for frame in kvstream.block_frames(
                    export, hid, self.chunk_blocks):
                nbytes += len(frame)
                body, status = await asyncio.wait_for(
                    client.call(_OP_KVSTREAM(), frame),
                    timeout=self.timeout_s,
                )
                if status != 200:
                    raise HandoffError(
                        f"decode peer {peer} rejected a block frame: "
                        f"{body.decode('utf-8', 'replace')[:200]}")
            body, status = await asyncio.wait_for(
                client.call(_OP_KVSTREAM(), kvstream.commit_frame(hid),
                            meta=meta),
                timeout=self.timeout_s,
            )
            if status != 200:
                raise HandoffError(
                    f"decode peer {peer} failed the commit: "
                    f"{body.decode('utf-8', 'replace')[:200]}")
            return kvstream.unpack_tokens(body), peer, nbytes
        except (Exception, asyncio.CancelledError):
            # torn mid-stream: best-effort abort frees the reservation
            # now; the decode side's TTL reaper is the backstop
            try:
                await asyncio.wait_for(
                    client.call(
                        _OP_KVSTREAM(), kvstream.abort_frame(hid)),
                    timeout=2.0,
                )
            except Exception:  # noqa: BLE001 - the reaper covers this
                pass
            raise


def _OP_KVSTREAM() -> int:
    from seldon_core_tpu.runtime.udsrelay import OP_KVSTREAM

    return OP_KVSTREAM
