"""Engine process entrypoint — the reference's engine pod boot re-designed.

Config resolution order mirrors ``EnginePredictor.init()`` (engine
EnginePredictor.java:56-150):

  1. ``ENGINE_PREDICTOR``          base64(JSON PredictorSpec)
  2. ``ENGINE_SELDON_DEPLOYMENT``  base64(JSON SeldonDeployment) [+ name]
  3. ``./deploymentdef.json``      file fallback
  4. default SIMPLE_MODEL stub graph (the reference's in-engine test stub)

Ports: ``ENGINE_SERVER_PORT`` (default 8000) REST,
``ENGINE_SERVER_GRPC_PORT`` (default 5001) gRPC — the ports the reference
operator wires into every engine container
(cluster-manager SeldonDeploymentOperatorImpl.java:98-144).

Serving-mesh extensions:

* ``ENGINE_GRAPH_NODE`` / ``--node NAME`` — serve ONE node of the loaded
  deployment's graph as a standalone engine (graph/sharding.py
  node_subspec): the pod-per-node topology; the root engine dispatches
  to it over ``POST /predict``.
* ``ENGINE_UDS_PATH`` / ``--uds-path`` — additionally bind the zero-copy
  length-prefixed relay lane on a unix socket (runtime/udsrelay.py) for
  a co-located gateway.  ``SELDON_TPU_UDS=0`` skips the bind.
* ``ENGINE_HTTP_UDS_PATH`` / ``--http-uds-path`` — additionally serve
  the FULL HTTP route table on a unix socket (httpfast.py fast lane) so
  a co-located root engine can dial this node engine with a ``unix:``
  binding (runtime/client.py UnixConnector).  Distinct from the framed
  relay above: this one speaks HTTP.

    python -m seldon_core_tpu.runtime.engine_main [--file deployment.json]
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import os
from typing import Optional

from seldon_core_tpu.graph.defaulting import default_and_validate
from seldon_core_tpu.graph.spec import (
    PredictorSpec,
    SeldonDeploymentSpec,
)

__all__ = ["load_deployment_from_env", "main"]

DEFAULT_GRAPH = {
    "spec": {
        "name": "default",
        "predictors": [
            {
                "name": "default",
                "graph": {
                    "name": "simple-model",
                    "implementation": "SIMPLE_MODEL",
                    "type": "MODEL",
                },
            }
        ],
    }
}


def load_deployment_from_env(
    file_path: Optional[str] = None,
) -> SeldonDeploymentSpec:
    raw = os.environ.get("ENGINE_PREDICTOR")
    if raw:
        predictor = json.loads(base64.b64decode(raw))
        spec = SeldonDeploymentSpec(
            name=os.environ.get("SELDON_DEPLOYMENT_ID", "engine"),
            predictors=[PredictorSpec.from_json_dict(predictor)],
        )
        return default_and_validate(spec)
    raw = os.environ.get("ENGINE_SELDON_DEPLOYMENT")
    if raw:
        spec = SeldonDeploymentSpec.from_json(base64.b64decode(raw))
        return default_and_validate(spec)
    path = file_path or "./deploymentdef.json"
    if os.path.exists(path):
        with open(path) as f:
            return default_and_validate(SeldonDeploymentSpec.from_json(f.read()))
    return default_and_validate(SeldonDeploymentSpec.from_json_dict(DEFAULT_GRAPH))


async def serve(deployment: SeldonDeploymentSpec, predictor_name=None,
                host="0.0.0.0", rest_port=None, grpc_port=None,
                uds_path=None, http_uds_path=None, gen_role=None,
                decode_peers=None, relay_tcp_port=None) -> None:
    from seldon_core_tpu.runtime.engine import EngineService
    from seldon_core_tpu.runtime.grpc_server import make_engine_grpc_server
    from seldon_core_tpu.runtime.rest import make_engine_app, serve_app

    rest_port = rest_port or int(os.environ.get("ENGINE_SERVER_PORT", "8000"))
    grpc_port = grpc_port or int(os.environ.get("ENGINE_SERVER_GRPC_PORT", "5001"))
    # batching knobs, part of the engine env contract the operator renders
    # (the reference's engine JVM opts role, SeldonDeploymentOperatorImpl)
    engine = EngineService(
        deployment,
        predictor_name,
        max_batch=int(os.environ.get("ENGINE_MAX_BATCH", "1024")),
        max_wait_ms=float(os.environ.get("ENGINE_BATCH_WAIT_MS", "2.0")),
        pipeline_depth=int(os.environ.get("ENGINE_PIPELINE_DEPTH", "8")),
        # large models (100M+-param generators) compile for minutes on a
        # cold cache; the per-dispatch 504 budget must cover that first
        # trace when prewarm is skipped
        dispatch_timeout_s=float(
            os.environ.get("ENGINE_DISPATCH_TIMEOUT_S", "30")
        ),
        # disaggregated serving mesh (runtime/servingmesh.py): this
        # replica's generation role and, for prefill replicas, the
        # decode peers it streams finished KV blocks to
        gen_role=gen_role,
        decode_peers=decode_peers,
    )
    # boot-time shape compilation: ENGINE_PREWARM_WIDTHS="784,16" compiles
    # every batch bucket of those feature widths before the server binds,
    # so live traffic never waits on an XLA compile (engine.prewarm)
    prewarm_raw = os.environ.get("ENGINE_PREWARM_WIDTHS", "")
    if prewarm_raw.strip():
        widths = [int(w) for w in prewarm_raw.split(",") if w.strip()]
        t0 = asyncio.get_event_loop().time()
        n = engine.prewarm(widths)
        print(
            f"prewarmed {n} batch shapes for widths {widths} "
            f"in {asyncio.get_event_loop().time() - t0:.1f}s",
            flush=True,
        )
    # data plane, fastest eligible lane first:
    #   native (C++ HTTP termination + batching, runtime/nativeplane.py)
    #   fast   (asyncio.Protocol, runtime/httpfast.py)
    #   aiohttp (full framework app, runtime/rest.py)
    # ENGINE_HTTP_IMPL picks explicitly; the default tries native and falls
    # back per-lane (ineligible graph, missing toolchain)
    http_impl = os.environ.get("ENGINE_HTTP_IMPL", "native").strip().lower()
    if http_impl not in ("native", "fast", "aiohttp"):
        # never boot with NO data plane: unknown names get the most
        # compatible lane plus a loud line in the pod log
        print(f"unknown ENGINE_HTTP_IMPL={http_impl!r}; serving aiohttp",
              flush=True)
        http_impl = "aiohttp"
    # gRPC lane selection: native (C++ HTTP/2 in the same plane), fast
    # (runtime/grpcfast.py asyncio lane), aio (stock grpc.aio server).
    # Default rides the native plane when the HTTP lane does.
    grpc_impl = os.environ.get(
        "ENGINE_GRPC_IMPL", "native" if http_impl == "native" else "fast"
    ).strip().lower()
    if grpc_impl not in ("native", "fast", "aio"):
        print(f"unknown ENGINE_GRPC_IMPL={grpc_impl!r}; serving fast lane",
              flush=True)
        grpc_impl = "fast"
    native_plane = None
    fast_server = None
    runner = None
    if http_impl == "native":
        try:
            from seldon_core_tpu.runtime.nativeplane import serve_native

            # the C++ listener binds a single address; 0.0.0.0 maps to ANY
            native_plane = await serve_native(
                engine, host if host != "0.0.0.0" else "", rest_port,
                grpc_port=grpc_port if grpc_impl == "native" else None,
            )
        except (RuntimeError, OSError) as e:
            print(f"native data plane unavailable ({e}); "
                  f"serving the Python fast lane", flush=True)
            http_impl = "fast"
    if http_impl == "fast":
        from seldon_core_tpu.runtime.httpfast import serve_fast

        fast_server = await serve_fast(engine, host, rest_port)
    elif http_impl == "aiohttp":
        runner = await serve_app(make_engine_app(engine), host, rest_port)
    if grpc_impl == "native" and (
        native_plane is None or native_plane.grpc_port is None
    ):
        print("native gRPC lane unavailable (no native plane); "
              "serving the Python fast lane", flush=True)
        grpc_impl = "fast"
    if grpc_impl == "native":
        async def grpc_stop():
            pass  # stopped with the shared native plane below
    elif grpc_impl == "fast":
        from seldon_core_tpu.runtime.grpcfast import serve_grpc_fast

        grpc_server = await serve_grpc_fast(engine, host, grpc_port)
        grpc_stop = grpc_server.stop
    else:
        grpc_server = make_engine_grpc_server(engine, host, grpc_port)
        await grpc_server.start()

        async def grpc_stop():
            await grpc_server.stop(grace=5.0)
    # zero-copy relay lane for a co-located gateway (runtime/udsrelay.py);
    # rides ALONGSIDE the TCP lanes — /stats scrape + SSE stay on TCP
    uds_server = None
    uds_path = uds_path or os.environ.get("ENGINE_UDS_PATH", "").strip()
    if uds_path and os.environ.get("SELDON_TPU_UDS", "1") != "0":
        from seldon_core_tpu.runtime.udsrelay import serve_uds

        uds_server = await serve_uds(engine, uds_path)
    # the framed relay on a TCP port: the cross-host lane decode
    # replicas receive KV-block handoffs on (runtime/kvstream.py)
    relay_tcp_server = None
    relay_tcp_port = relay_tcp_port if relay_tcp_port is not None else int(
        os.environ.get("ENGINE_RELAY_TCP_PORT", "0") or 0)
    if relay_tcp_port:
        from seldon_core_tpu.runtime.udsrelay import serve_relay_tcp

        relay_tcp_server = await serve_relay_tcp(
            engine, host if host != "0.0.0.0" else "0.0.0.0",
            relay_tcp_port,
        )
    # HTTP face on a unix socket: the node-mesh lane a sharded root's
    # `unix:` binding dials (runtime/client.py).  Bound regardless of the
    # main HTTP lane's impl — the native plane can't listen on a UDS
    http_uds_server = None
    http_uds_path = http_uds_path or \
        os.environ.get("ENGINE_HTTP_UDS_PATH", "").strip()
    if http_uds_path and os.environ.get("SELDON_TPU_UDS", "1") != "0":
        from seldon_core_tpu.runtime.httpfast import FastHttpServer

        http_uds_server = FastHttpServer(engine)
        await http_uds_server.start_uds(http_uds_path)
    print(
        f"engine up: predictor={engine.predictor.name} mode={engine.mode} "
        f"rest=:{rest_port} grpc=:{grpc_port}"
        + (f" uds={uds_path}" if uds_server is not None else "")
        + (f" http-uds={http_uds_path}"
           if http_uds_server is not None else "")
        + (f" relay-tcp=:{relay_tcp_server.port}"
           if relay_tcp_server is not None else "")
        + (f" gen-role={engine.gen_role}"
           if engine.gen_role != "unified" else ""),
        flush=True,
    )

    # engine liveness lease: when a shared gateway state file and an
    # advertise URL are configured, heartbeat this replica's row (with
    # its boot_id epoch) so gateway balancers learn about a dead or
    # restarted engine within one lease TTL instead of waiting out
    # 3 failed scrapes (gateway/balancer.py ReplicaSet.apply_leases)
    lease_store = None
    advertise_url = os.environ.get("ENGINE_ADVERTISE_URL", "").strip()
    state_path = os.environ.get("GATEWAY_STATE_PATH", "").strip()
    heartbeat_task = None
    if advertise_url and state_path:
        from seldon_core_tpu.gateway.federation import lease_ttl_s
        from seldon_core_tpu.gateway.state import SqliteDeploymentStore

        lease_store = SqliteDeploymentStore(state_path)
        lease_ttl = lease_ttl_s()

        async def _heartbeat_loop():
            while True:
                try:
                    lease_store.heartbeat_engine(
                        advertise_url, engine.boot_id, lease_ttl)
                except Exception as e:  # noqa: BLE001 — a wedged store
                    # must not kill the engine; the lease just lapses
                    print(f"engine lease heartbeat failed: {e}", flush=True)
                await asyncio.sleep(max(lease_ttl / 3.0, 0.05))

        heartbeat_task = asyncio.get_running_loop().create_task(
            _heartbeat_loop())
        print(f"engine lease: heartbeating {advertise_url} "
              f"(ttl {lease_ttl:.1f}s) into {state_path}", flush=True)

    # graceful shutdown: SIGTERM/SIGINT flips readiness and drains before
    # exit — the reference's Tomcat drain (App.java:85-95, 20 s) + pre-stop
    # pause contract, built into the process itself
    import signal

    stop = asyncio.Event()
    hurry = asyncio.Event()  # second signal: skip the drain
    loop = asyncio.get_running_loop()

    def _on_signal():
        if stop.is_set():
            hurry.set()
        else:
            stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, _on_signal)
        except (NotImplementedError, RuntimeError):
            pass  # platforms without signal support: external kill only
    await stop.wait()
    drain_s = float(os.environ.get("ENGINE_SHUTDOWN_DRAIN_S", "20"))
    print(
        f"engine draining: up to {drain_s:.0f}s (readiness now 503; "
        f"signal again to skip)",
        flush=True,
    )
    engine.pause()  # /ready -> 503; the LB stops routing here
    if lease_store is not None:
        # deregister FIRST: balancers mark this replica dead (lease row
        # gone while it previously had one) before the drain even starts,
        # so no new work is routed at a draining engine
        if heartbeat_task is not None:
            heartbeat_task.cancel()
        try:
            lease_store.drop_engine(advertise_url)
        except Exception:  # noqa: BLE001 — best effort on the way out
            pass
    # poll-drain: exit the moment the last inflight request/sequence
    # finishes instead of always sleeping out the full window (a 20 s
    # fixed sleep was the old behavior — rolling restarts paid it even
    # on an idle engine)
    deadline = loop.time() + drain_s
    while loop.time() < deadline and not hurry.is_set():
        if engine.drained():
            print("engine drained early "
                  f"({drain_s - (deadline - loop.time()):.1f}s)", flush=True)
            break
        try:
            await asyncio.wait_for(
                hurry.wait(), min(0.1, max(deadline - loop.time(), 0.01)))
        except asyncio.TimeoutError:
            pass
    if hurry.is_set():
        print("drain skipped by second signal", flush=True)
    await grpc_stop()
    if runner is not None:
        await runner.cleanup()
    if fast_server is not None:
        await fast_server.stop()
    if uds_server is not None:
        await uds_server.stop()
    if relay_tcp_server is not None:
        await relay_tcp_server.stop()
    if http_uds_server is not None:
        await http_uds_server.stop()
    if native_plane is not None:
        await native_plane.stop()
    print("engine stopped", flush=True)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="seldon_core_tpu engine")
    parser.add_argument("--file", default=None, help="deployment JSON path")
    parser.add_argument("--predictor", default=None)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--rest-port", type=int, default=None)
    parser.add_argument("--grpc-port", type=int, default=None)
    parser.add_argument(
        "--node", default=None,
        help="serve ONE graph node of the deployment as a standalone "
             "node engine (graph sharding; env ENGINE_GRAPH_NODE)",
    )
    parser.add_argument(
        "--uds-path", default=None,
        help="also bind the zero-copy UDS relay lane on this socket path "
             "(env ENGINE_UDS_PATH)",
    )
    parser.add_argument(
        "--http-uds-path", default=None,
        help="also serve the HTTP route table on this unix socket — the "
             "node-mesh lane a sharded root's unix: binding dials "
             "(env ENGINE_HTTP_UDS_PATH)",
    )
    parser.add_argument(
        "--gen-role", default=None,
        choices=["unified", "prefill", "decode"],
        help="generation role in a disaggregated serving mesh (env "
             "ENGINE_GEN_ROLE; SELDON_TPU_DISAGG=0 forces unified)",
    )
    parser.add_argument(
        "--decode-peers", default=None,
        help="comma-separated relay specs (uds:/path or tcp:host:port) "
             "of decode replicas a prefill replica hands KV blocks to "
             "(env ENGINE_DECODE_PEERS)",
    )
    parser.add_argument(
        "--relay-tcp-port", type=int, default=None,
        help="also bind the framed relay lane on this TCP port — the "
             "cross-host KV-handoff receiver (env ENGINE_RELAY_TCP_PORT)",
    )
    args = parser.parse_args(argv)
    if os.environ.get("SELDON_FORCE_CPU") == "1":
        # host-CPU serving for control-plane demos/tests: several engines
        # can then coexist on a box whose accelerator admits one process
        # (JAX_PLATFORMS env is not honored by every plugin backend; the
        # config call before first backend use is)
        import jax

        jax.config.update("jax_platforms", "cpu")
    from seldon_core_tpu.runtime.compilecache import enable_compile_cache

    enable_compile_cache()
    deployment = load_deployment_from_env(args.file)
    node = args.node or os.environ.get("ENGINE_GRAPH_NODE", "").strip()
    if node:
        # pod-per-node topology: this process serves ONE leaf of the graph
        # (the operator ships the FULL deployment to every shard; the node
        # name selects the slice — graph/sharding.py)
        from seldon_core_tpu.graph.sharding import node_subspec

        deployment = default_and_validate(
            node_subspec(deployment, node, args.predictor)
        )
    decode_peers = None
    if args.decode_peers is not None:
        from seldon_core_tpu.runtime.servingmesh import parse_decode_peers

        decode_peers = parse_decode_peers(args.decode_peers)
    asyncio.run(
        serve(deployment, args.predictor, args.host, args.rest_port,
              args.grpc_port, uds_path=args.uds_path,
              http_uds_path=args.http_uds_path, gen_role=args.gen_role,
              decode_peers=decode_peers,
              relay_tcp_port=args.relay_tcp_port)
    )


if __name__ == "__main__":
    main()
