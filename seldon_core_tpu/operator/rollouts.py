"""Canary rollouts with automatic rollback — the controller that makes
the observability stack load-bearing.

The reference platform ships the canary *pattern* (two predictors behind a
replica-weighted split, ``examples/canary_deployment.json``) but leaves
promotion and rollback to a human watching dashboards.  This module closes
the loop: a :class:`RolloutController` walks a candidate predictor through
staged traffic shifts (default 1 → 5 → 25 → 100 %) by reassigning the
gateway's weighted predictor split (``DeploymentStore.set_weights`` — the
same lever the reference's replica weighting is), and gates every stage on
the live signals the platform already measures:

  * **drift** — the candidate's PSI/KS drift score (``GET /quality``,
    utils/quality.py),
  * **SLO burn rate** — the 5-minute fast-burn window (``GET /quality``),
  * **error rate** — the candidate's share of FAILURE answers at the
    gateway (per-predictor traffic accounting, ``GET /stats``),
  * **shadow/replay disagreement** — live-vs-candidate divergence from
    the shadow mirror (``GET /shadow``) or a pre-rollout firehose replay
    verdict (runtime/replay.py) supplied as the plan's prior.

Any breach **snaps the split back to the baseline in one step**, stamps a
rollback event into the audit firehose and
``seldon_tpu_rollbacks_total{reason}``, and **quarantines** the deployment:
the same spec (identified by its config hash) is never promoted again —
only a changed spec clears the quarantine.  Every stage decision rides a
tracer span (kind ``rollout``) so the promotion history is auditable next
to the request trees it governed.

``SELDON_TPU_ROLLOUTS=0`` freezes the controller (no weight changes — a
kill switch that restores today's manual behavior).

Signal sources are pluggable: :class:`GatewaySignals` reads the in-process
gateway + the process-global quality observatory (the common co-located
topology); :class:`HttpSignals` scrapes the same surfaces over HTTP for a
split-process control plane.  ``operator/reconciler.py`` drives the
controller from CR annotations (``seldon.io/canary`` et al.) and writes
the rollout state back onto the CR status.
"""

from __future__ import annotations

import json
import os
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from seldon_core_tpu.utils.telemetry import RECORDER

__all__ = [
    "RolloutGates",
    "RolloutPlan",
    "RolloutController",
    "GatewaySignals",
    "HttpSignals",
    "rollouts_enabled",
    "plan_from_annotations",
    "CANARY_ANNOTATION",
]

CANARY_ANNOTATION = "seldon.io/canary"

DEFAULT_STAGES = (1, 5, 25, 100)


def rollouts_enabled() -> bool:
    """``SELDON_TPU_ROLLOUTS=0`` freezes every controller — no weight
    changes, no promotions, no rollbacks (checked per tick)."""
    return os.environ.get("SELDON_TPU_ROLLOUTS", "1").strip() != "0"


@dataclass
class RolloutGates:
    """Per-stage promotion gates.  ``None`` disables a gate; a stage is
    judged only after ``min_requests`` candidate requests AND
    ``hold_s`` seconds at its weight — deciding on no evidence is how
    a 1% stage with zero traffic gets promoted to 100%."""

    max_drift: Optional[float] = 0.25          # PSI — 0.25 is "major shift"
    max_burn_rate: Optional[float] = 14.4      # classic 5m fast-burn page
    max_error_rate: Optional[float] = 0.05
    max_shadow_disagreement: Optional[float] = 0.1
    min_requests: int = 20

    def to_json_dict(self) -> dict:
        return {
            "max_drift": self.max_drift,
            "max_burn_rate": self.max_burn_rate,
            "max_error_rate": self.max_error_rate,
            "max_shadow_disagreement": self.max_shadow_disagreement,
            "min_requests": self.min_requests,
        }


@dataclass
class RolloutPlan:
    """Desired rollout for one deployment: shift ``candidate`` from 0 to
    100 % of the live split through ``stages``, holding each stage
    ``hold_s`` seconds, gated by ``gates``.  ``config_hash`` is the
    quarantine identity — a rolled-back hash is never retried."""

    deployment: str
    candidate: str
    baseline: str
    stages: Tuple[int, ...] = DEFAULT_STAGES
    hold_s: float = 30.0
    gates: RolloutGates = field(default_factory=RolloutGates)
    config_hash: str = ""

    def __post_init__(self):
        stages = tuple(int(s) for s in self.stages)
        if not stages or any(
            not 0 < s <= 100 for s in stages
        ) or list(stages) != sorted(set(stages)):
            raise ValueError(
                f"stages must be strictly increasing percents in (0, 100], "
                f"got {self.stages!r}"
            )
        if stages[-1] != 100:
            stages = stages + (100,)  # a rollout that never finishes isn't one
        self.stages = stages
        if self.candidate == self.baseline:
            raise ValueError("candidate and baseline must differ")


class _Rollout:
    """State machine for one deployment's active rollout."""

    def __init__(self, plan: RolloutPlan, now: float):
        self.plan = plan
        self.state = "pending"           # pending|running|promoted|rolled_back
        self.stage_idx = -1              # -1 = not yet shifted
        self.stage_entered_at = now
        self.stage_requests_at_entry = 0
        self.stage_errors_at_entry = 0
        self.rollback_reason: Optional[str] = None
        self.history: List[dict] = []

    @property
    def current_percent(self) -> int:
        if self.state == "promoted":
            return 100
        if self.state == "rolled_back" or self.stage_idx < 0:
            return 0
        return self.plan.stages[self.stage_idx]

    def note(self, decision: str, now_wall: float, **fields) -> dict:
        event = {"ts": now_wall, "decision": decision,
                 "stage_percent": self.current_percent, **fields}
        self.history.append(event)
        if len(self.history) > 64:
            del self.history[:-64]
        return event

    def snapshot(self) -> dict:
        return {
            "deployment": self.plan.deployment,
            "candidate": self.plan.candidate,
            "baseline": self.plan.baseline,
            "state": self.state,
            "stage_percent": self.current_percent,
            "stages": list(self.plan.stages),
            "config_hash": self.plan.config_hash,
            "rollback_reason": self.rollback_reason,
        }

    def document(self) -> dict:
        return {
            **self.snapshot(),
            "hold_s": self.plan.hold_s,
            "gates": self.plan.gates.to_json_dict(),
            "history": list(self.history),
        }


class RolloutController:
    """Drives every active rollout against one deployment store.

    ``signals`` is a callable ``(plan) -> dict`` returning whatever
    subset of ``{"requests", "errors", "drift", "burn_rate",
    "shadow_disagreement"}`` the topology can measure — missing keys
    simply disable the matching gate for that tick (the gates that CAN
    be evaluated still roll back).  ``firehose`` (optional) receives
    stage/rollback events next to the request stream
    (gateway/firehose.py ``publish_event``)."""

    def __init__(self, store, signals: Callable[[RolloutPlan], dict],
                 firehose=None, clock: Callable[[], float] = time.monotonic,
                 federation=None):
        self.store = store
        self.signals = signals
        self.firehose = firehose
        self.clock = clock
        #: optional GatewayFederation (gateway/federation.py).  A rollout
        #: controller is a SINGLETON duty — with N gateway replicas over
        #: one store, only the coordinator's controller may tick, and its
        #: traffic-split writes go through the fenced path so a paused
        #: ex-coordinator that wakes up mid-write is rejected by the
        #: store itself (fencing token), not by luck
        self.federation = federation
        self._rollouts: Dict[str, _Rollout] = {}
        #: deployment -> EVERY config_hash that rolled back (bounded to
        #: the most recent 64) — the quarantine survives the _Rollout
        #: object being superseded, and a flip-flopping operator can't
        #: re-run a known-bad revision by shipping something else in
        #: between (only CR deletion clears the history)
        self._quarantined: Dict[str, List[str]] = {}

    # -- plan intake -----------------------------------------------------

    def apply(self, plan: RolloutPlan) -> _Rollout:
        """Idempotent desired-state intake (the reconciler calls this
        every tick).  Same config_hash -> the existing rollout (or the
        standing quarantine); a NEW hash supersedes both — the operator
        shipped a changed spec, which is the one sanctioned quarantine
        exit."""
        ro = self._rollouts.get(plan.deployment)
        if ro is not None and ro.plan.config_hash == plan.config_hash:
            return ro
        if plan.config_hash in self._quarantined.get(plan.deployment, ()):
            # rebuild the quarantined terminal state for status surfaces
            if ro is None or ro.plan.config_hash != plan.config_hash:
                ro = _Rollout(plan, self.clock())
                ro.state = "rolled_back"
                ro.rollback_reason = "quarantined"
                self._rollouts[plan.deployment] = ro
            return ro
        ro = _Rollout(plan, self.clock())
        self._rollouts[plan.deployment] = ro
        RECORDER.set_rollout_stage(plan.deployment, 0)
        return ro

    def forget(self, deployment: str) -> None:
        """Deployment deleted: drop its rollout AND its quarantine."""
        self._rollouts.pop(deployment, None)
        self._quarantined.pop(deployment, None)

    # -- the control loop ------------------------------------------------

    def tick(self) -> List[dict]:
        """One pass over every active rollout; returns the decisions
        taken (promote / hold / rollback), one dict per deployment."""
        if not rollouts_enabled():
            return []
        if self.federation is not None and not self.federation.is_coordinator:
            return []  # singleton duty: only the coordinator replica ticks
        decisions = []
        for ro in list(self._rollouts.values()):
            if ro.state in ("promoted", "rolled_back"):
                continue
            decisions.append(self._tick_one(ro))
        return decisions

    def tick_deployment(self, deployment: str) -> Optional[dict]:
        """Tick just one deployment (the reconciler's per-CR path)."""
        if not rollouts_enabled():
            return None
        if self.federation is not None and not self.federation.is_coordinator:
            return None
        ro = self._rollouts.get(deployment)
        if ro is None or ro.state in ("promoted", "rolled_back"):
            return None
        return self._tick_one(ro)

    def _tick_one(self, ro: _Rollout) -> dict:
        from seldon_core_tpu.utils.tracing import TRACER

        plan = ro.plan
        now = self.clock()
        with TRACER.span(
            f"rollout-{plan.deployment}", "rollout", kind="rollout",
            deployment=plan.deployment, candidate=plan.candidate,
            stage_percent=str(ro.current_percent), state=ro.state,
        ) as span:
            try:
                decision = self._decide(ro, now)
            except Exception as e:  # noqa: BLE001 — narrow re-raise below
                from seldon_core_tpu.gateway.state import StaleFenceError

                if not isinstance(e, StaleFenceError):
                    raise
                # this replica lost the coordinator lease mid-decision and
                # the store rejected the split write (stale fencing token).
                # Abandon the transition — the successor's controller owns
                # the rollout now, re-derived from the shared store
                RECORDER.record_lease_transition("fenced_write_rejected")
                decision = ro.note("fenced", time.time(), error=str(e))
            if span is not None:
                span["decision"] = decision["decision"]
                if decision.get("reason"):
                    span["reason"] = decision["reason"]
        return decision

    def _decide(self, ro: _Rollout, now: float) -> dict:
        plan = ro.plan
        if ro.state == "pending":
            resumed = self._maybe_resume(ro, now)
            if resumed is not None:
                return resumed
            # first shift: candidate enters at stage 0's percent
            return self._advance(ro, now)
        sig = self._signals_safe(plan)
        if "_scrape_error" not in sig and ro.stage_requests_at_entry is None:
            # the stage entered during a scrape outage: this is the first
            # good read — it becomes the entry baseline, and the stage
            # clock restarts so the candidate is judged on traffic it
            # actually served AT this weight
            ro.stage_requests_at_entry = int(sig.get("requests", 0) or 0)
            ro.stage_errors_at_entry = int(sig.get("errors", 0) or 0)
            ro.stage_entered_at = now
        breach = self._breach(ro, sig)
        if breach is not None:
            return self._rollback(ro, now, breach, sig)
        held_s = now - ro.stage_entered_at
        stage_requests = max(
            int(sig.get("requests", 0)) - (ro.stage_requests_at_entry or 0), 0
        )
        if held_s < plan.hold_s or stage_requests < plan.gates.min_requests:
            return ro.note(
                "hold", time.time(), held_s=round(held_s, 3),
                stage_requests=stage_requests,
            )
        if ro.stage_idx >= len(plan.stages) - 1:
            return self._promote(ro, now, sig)
        return self._advance(ro, now)

    def _maybe_resume(self, ro: _Rollout, now: float) -> Optional[dict]:
        """Continue a predecessor's rollout instead of restarting it.

        With N federated gateway replicas, the rollout's only durable
        state is the traffic split in the shared store — the _Rollout
        object dies with the coordinator that held it.  A fresh
        controller whose pending plan finds the candidate ALREADY at one
        of its stage percents (the dead coordinator got that far)
        fast-forwards to that stage and holds it, rather than snapping
        live traffic back to stage 0.

        Only armed under federation: a lone controller owns its rollout
        for the rollout's whole life, and a fresh canary whose candidate
        REGISTRATION weight happens to equal a stage percent must not
        read as a predecessor's progress."""
        if self.federation is None:
            return None
        plan = ro.plan
        try:
            current = self.store.weights(plan.deployment)
        except Exception:  # noqa: BLE001 — a store that can't answer (no
            # weights API, partitioned) degrades to the stage-0 start
            return None
        pct = current.get(plan.candidate)
        if pct is None or pct not in plan.stages:
            return None
        ro.state = "running"
        ro.stage_idx = plan.stages.index(pct)
        ro.stage_entered_at = now
        sig = self._signals_safe(plan)
        if "_scrape_error" in sig:
            ro.stage_requests_at_entry = None
            ro.stage_errors_at_entry = None
        else:
            ro.stage_requests_at_entry = int(sig.get("requests", 0) or 0)
            ro.stage_errors_at_entry = int(sig.get("errors", 0) or 0)
        RECORDER.set_rollout_stage(plan.deployment, pct)
        event = ro.note("resume", time.time(),
                        stage=ro.stage_idx, percent=pct)
        self._publish("rollout_resumed", plan, stage=ro.stage_idx,
                      percent=pct)
        return event

    # -- signal plumbing --------------------------------------------------

    def _signals_safe(self, plan: RolloutPlan) -> dict:
        try:
            return dict(self.signals(plan) or {})
        except Exception as e:  # noqa: BLE001 — a broken scrape must not
            # crash the loop, but it must not read as "all healthy"
            # either: fail the stage closed via a sentinel the breach
            # check treats as a scrape failure
            return {"_scrape_error": f"{type(e).__name__}: {e}"}

    def _breach(self, ro: _Rollout, sig: dict) -> Optional[Tuple[str, Any]]:
        """First breached gate as (reason, observed), else None."""
        gates = ro.plan.gates
        if "_scrape_error" in sig:
            # no signals at all while the candidate takes live traffic is
            # itself unsafe — roll back rather than fly blind
            return ("signals_unavailable", sig["_scrape_error"])
        checks = [
            ("drift", gates.max_drift, sig.get("drift")),
            ("burn_rate", gates.max_burn_rate, sig.get("burn_rate")),
            ("shadow", gates.max_shadow_disagreement,
             sig.get("shadow_disagreement")),
        ]
        for reason, limit, observed in checks:
            if limit is not None and observed is not None \
                    and float(observed) > float(limit):
                return (reason, round(float(observed), 6))
        if gates.max_error_rate is not None and \
                ro.stage_requests_at_entry is not None:
            # judged on THIS stage's delta (counts since stage entry) and
            # on a minimum sample — one failed request out of three must
            # not read as a 33% error rate.  Entry-None (stage entered
            # during a scrape outage, not yet backfilled) skips the gate
            # for the tick rather than judging against all-time counts
            requests = int(sig.get("requests", 0)) - ro.stage_requests_at_entry
            errors = int(sig.get("errors", 0)) - (ro.stage_errors_at_entry or 0)
            if requests >= max(gates.min_requests, 1):
                rate = max(errors, 0) / requests
                if rate > gates.max_error_rate:
                    return ("error_rate", round(rate, 6))
        return None

    # -- transitions -------------------------------------------------------

    def _write_split(self, deployment: str, weights: Dict[str, int]) -> None:
        """The controller's only store write, fenced when federated: a
        stale fencing token (this replica lost the coordinator lease to
        a successor while deciding) surfaces as StaleFenceError — the
        caller's transition is abandoned, the NEW coordinator's
        controller re-derives it from the shared store."""
        if self.federation is not None:
            self.federation.set_weights(deployment, weights)
        else:
            self.store.set_weights(deployment, weights)

    def _set_split(self, plan: RolloutPlan, candidate_percent: int) -> None:
        self._write_split(plan.deployment, {
            plan.candidate: candidate_percent,
            plan.baseline: 100 - candidate_percent,
        })
        RECORDER.set_rollout_stage(plan.deployment, candidate_percent)

    def _advance(self, ro: _Rollout, now: float) -> dict:
        plan = ro.plan
        if ro.state == "pending":
            ro.state = "running"
        ro.stage_idx += 1
        percent = plan.stages[ro.stage_idx]
        self._set_split(plan, percent)
        ro.stage_entered_at = now
        sig = self._signals_safe(plan)
        if "_scrape_error" in sig:
            # entry counters unknown: leave them None so the FIRST
            # successful read after the shift backfills them — zeroing
            # here would judge the stage against all-time cumulative
            # counts (min_requests trivially satisfied with zero actual
            # stage traffic, error deltas diluted by history)
            ro.stage_requests_at_entry = None
            ro.stage_errors_at_entry = None
        else:
            ro.stage_requests_at_entry = int(sig.get("requests", 0) or 0)
            ro.stage_errors_at_entry = int(sig.get("errors", 0) or 0)
        event = ro.note(
            "advance", time.time(),
            stage=ro.stage_idx, percent=percent,
        )
        self._publish("rollout_stage", plan, stage=ro.stage_idx,
                      percent=percent)
        return event

    def _promote(self, ro: _Rollout, now: float, sig: dict) -> dict:
        ro.state = "promoted"
        self._set_split(ro.plan, 100)
        event = ro.note("promote", time.time())
        self._publish("rollout_promoted", ro.plan)
        return event

    def _rollback(self, ro: _Rollout, now: float,
                  breach: Tuple[str, Any], sig: dict) -> dict:
        """The one-step snap-back: baseline takes 100% in a single
        set_weights call, the breach is stamped everywhere an operator
        looks (firehose, /stats counter mirror, Prometheus), and the
        config hash is quarantined until the spec changes."""
        plan = ro.plan
        reason, observed = breach
        ro.state = "rolled_back"
        ro.rollback_reason = reason
        self._write_split(plan.deployment, {
            plan.candidate: 0,
            plan.baseline: 100,
        })
        RECORDER.set_rollout_stage(plan.deployment, 0)
        RECORDER.record_rollback(reason)
        hashes = self._quarantined.setdefault(plan.deployment, [])
        if plan.config_hash not in hashes:
            hashes.append(plan.config_hash)
            del hashes[:-64]
        # cite the postmortem exemplars that witnessed the breach: the
        # operator lands on GET /postmortems?puid=<one of these> instead
        # of re-deriving which requests the gate actually saw
        evidence: list = []
        try:
            from seldon_core_tpu.utils.postmortem import POSTMORTEM
            evidence = POSTMORTEM.exemplar_puids(
                deployment=plan.deployment, limit=4)
        except Exception:  # noqa: BLE001 - evidence is best-effort
            evidence = []
        event = ro.note(
            "rollback", time.time(), reason=reason, observed=observed,
            signals={k: v for k, v in sig.items() if not k.startswith("_")},
            evidence_puids=evidence,
        )
        self._publish(
            "rollback", plan, reason=reason, observed=observed,
            config_hash=plan.config_hash, evidence_puids=evidence,
        )
        return event

    def _publish(self, kind: str, plan: RolloutPlan, **fields) -> None:
        if self.firehose is not None:
            self.firehose.publish_event(
                plan.deployment, kind,
                candidate=plan.candidate, baseline=plan.baseline, **fields,
            )

    # -- surfaces ----------------------------------------------------------

    def status_block(self, deployment: str) -> Optional[dict]:
        ro = self._rollouts.get(deployment)
        return None if ro is None else ro.snapshot()

    def snapshot(self) -> dict:
        return {
            "enabled": rollouts_enabled(),
            "rollouts": {
                dep: ro.snapshot()
                for dep, ro in sorted(self._rollouts.items())
            },
        }

    def document(self) -> dict:
        """The ``GET /rollouts`` body: full per-deployment state with
        gates and decision history."""
        return {
            "enabled": rollouts_enabled(),
            "rollouts": {
                dep: ro.document()
                for dep, ro in sorted(self._rollouts.items())
            },
            "quarantined": {
                dep: list(hashes)
                for dep, hashes in sorted(self._quarantined.items())
            },
        }


# ---------------------------------------------------------------------------
# signal sources
# ---------------------------------------------------------------------------


class GatewaySignals:
    """Candidate health read straight off the co-located gateway + the
    process-global quality observatory — the in-process topology every
    demo/test runs and the single-host production default.

    ``nodes``: graph node names whose drift scores describe the
    candidate (None = max over all nodes — correct when baseline and
    candidate share node names and therefore one drift window)."""

    def __init__(self, gateway, nodes: Optional[List[str]] = None):
        self.gateway = gateway
        self.nodes = nodes

    def __call__(self, plan: RolloutPlan) -> dict:
        from seldon_core_tpu.utils.quality import QUALITY

        requests, errors = self.gateway.predictor_traffic(
            plan.deployment, plan.candidate
        )
        out: dict = {"requests": requests, "errors": errors}
        # force-fresh drift: a stage decision must judge the batches the
        # candidate just served, not the last throttle window's scores
        QUALITY.refresh_gauges()
        snap = QUALITY.snapshot()
        drifts = []
        for name, ent in (snap.get("nodes") or {}).items():
            if self.nodes is not None and name not in self.nodes:
                continue
            for key, val in ent.items():
                if key.endswith("psi_max") or key == "prediction_psi":
                    try:
                        drifts.append(float(val))
                    except (TypeError, ValueError):
                        pass
        if drifts:
            out["drift"] = max(drifts)
        # burn gates judge the fleet-truth aggregate when federation
        # publishes one, the local ring otherwise — the SAME
        # effective_burn_rate the brownout ladder reads, so a canary
        # cannot pass on a 1/N slice of the fleet's burn
        from seldon_core_tpu.utils.quality import effective_burn_rate

        burn = effective_burn_rate("5m")
        if burn is not None:
            out["burn_rate"] = burn
        dis = self.gateway.shadow.disagreement_rate(plan.deployment)
        if dis is not None:
            out["shadow_disagreement"] = dis
        return out


class HttpSignals:
    """The same signals scraped over HTTP: the gateway's ``/stats`` +
    ``/shadow`` and an engine's ``/quality`` — for a control plane that
    does not share a process with the data plane."""

    def __init__(self, gateway_url: str, quality_url: Optional[str] = None,
                 timeout_s: float = 5.0):
        self.gateway_url = gateway_url.rstrip("/")
        self.quality_url = (quality_url or gateway_url).rstrip("/")
        self.timeout_s = timeout_s

    def _get(self, url: str) -> dict:
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return json.loads(r.read().decode("utf-8", "replace"))

    def __call__(self, plan: RolloutPlan) -> dict:
        stats = self._get(self.gateway_url + "/stats")
        out: dict = {}
        traffic = (stats.get("traffic") or {}).get(
            f"{plan.deployment}/{plan.candidate}"
        )
        if traffic:
            out["requests"] = int(traffic.get("count", 0))
            out["errors"] = int(traffic.get("errors", 0))
        else:
            out["requests"] = 0
            out["errors"] = 0
        shadow = (stats.get("shadow") or {}).get("deployments", {}).get(
            plan.deployment
        )
        if shadow and shadow.get("mean_disagreement") is not None:
            out["shadow_disagreement"] = shadow["mean_disagreement"]
        try:
            quality = self._get(self.quality_url + "/quality")
        except Exception:
            quality = None
        if quality:
            drifts = []
            for row in quality.get("nodes", []):
                drift = row.get("drift") or {}
                for key in ("psi_max", "prediction_psi"):
                    if key in drift:
                        try:
                            drifts.append(float(drift[key]))
                        except (TypeError, ValueError):
                            pass
            if drifts:
                out["drift"] = max(drifts)
            slo = (quality.get("slo") or {}).get("windows") or {}
            if "5m" in slo and (quality.get("slo") or {}).get("configured"):
                out["burn_rate"] = slo["5m"].get("burn_rate")
        return out


# ---------------------------------------------------------------------------
# CR annotation contract (operator/reconciler.py)
# ---------------------------------------------------------------------------


def _ann(annotations: dict, key: str, default: Optional[str] = None):
    v = annotations.get(f"seldon.io/canary-{key}")
    return default if v is None else str(v)


def plan_from_annotations(spec, config_hash: str) -> Optional[RolloutPlan]:
    """Build a RolloutPlan from deployment annotations, or None when the
    CR doesn't opt in.  Contract:

      ``seldon.io/canary``                      candidate predictor name
      ``seldon.io/canary-baseline``             baseline (default: the
                                                other predictor)
      ``seldon.io/canary-stages``               "1,5,25,100"
      ``seldon.io/canary-hold-s``               per-stage hold seconds
      ``seldon.io/canary-max-drift``            gate knobs ("none"
      ``seldon.io/canary-max-burn-rate``         disables a gate)
      ``seldon.io/canary-max-error-rate``
      ``seldon.io/canary-max-shadow-disagreement``
      ``seldon.io/canary-min-requests``

    Raises ValueError on a malformed contract (unknown predictor names,
    bad stage lists) — the reconciler surfaces that on the CR status the
    same way it surfaces an invalid graph."""
    ann = spec.annotations
    candidate = str(ann.get(CANARY_ANNOTATION, "") or "").strip()
    if not candidate:
        return None
    names = [p.name for p in spec.predictors]
    if candidate not in names:
        raise ValueError(
            f"canary annotation names unknown predictor {candidate!r} "
            f"(have {names})"
        )
    baseline = _ann(ann, "baseline")
    if baseline is None:
        others = [n for n in names if n != candidate]
        if len(others) != 1:
            raise ValueError(
                "canary-baseline annotation required when the deployment "
                f"doesn't have exactly one other predictor (have {names})"
            )
        baseline = others[0]
    elif baseline not in names:
        raise ValueError(
            f"canary-baseline names unknown predictor {baseline!r}"
        )

    def _gate(key: str, default: Optional[float]) -> Optional[float]:
        raw = _ann(ann, key)
        if raw is None:
            return default
        if raw.strip().lower() in ("none", "off", ""):
            return None
        return float(raw)

    stages_raw = _ann(ann, "stages")
    stages = (
        DEFAULT_STAGES if stages_raw is None
        else tuple(int(s) for s in stages_raw.split(",") if s.strip())
    )
    defaults = RolloutGates()
    gates = RolloutGates(
        max_drift=_gate("max-drift", defaults.max_drift),
        max_burn_rate=_gate("max-burn-rate", defaults.max_burn_rate),
        max_error_rate=_gate("max-error-rate", defaults.max_error_rate),
        max_shadow_disagreement=_gate(
            "max-shadow-disagreement", defaults.max_shadow_disagreement
        ),
        min_requests=int(float(_ann(ann, "min-requests",
                                    str(defaults.min_requests)))),
    )
    return RolloutPlan(
        deployment=spec.name,
        candidate=candidate,
        baseline=baseline,
        stages=stages,
        hold_s=float(_ann(ann, "hold-s", "30")),
        gates=gates,
        config_hash=config_hash,
    )
