"""Deployment materializer — the reference operator without Kubernetes.

The reference's cluster-manager watches the SeldonDeployment CRD, rewrites
the resource (defaulting), validates it, and materializes k8s Deployments +
Services with an injected engine container; a second watcher feeds pod
availability back into the CR status (SURVEY.md §2.4, §3.1).

Here the same control loop materializes a deployment spec into this host's
runtime:

  * ``apply``   defaulting + validation, then per predictor: spawn unit
    microservice subprocesses for remote (rest/grpc) bindings with the
    reference env contract injected (PREDICTIVE_UNIT_SERVICE_PORT,
    PREDICTIVE_UNIT_PARAMETERS, ids — graph/defaulting.py), build an
    ``EngineService`` (the engine "container", config via the same
    ``ENGINE_PREDICTOR`` b64 contract when subprocessed), and register the
    deployment with the gateway's DeploymentStore.
  * ``delete``  stop processes, unregister (the reference's ownerReference GC).
  * ``watch_dir``  poll a directory of ``*.json`` specs every interval;
    ADDED/MODIFIED (mtime dedup, like resourceVersion) -> apply, file gone ->
    delete (SeldonDeploymentWatcher.java:89-171's 5 s scheduled loop).
  * ``status``  per-predictor {replicas, replicasAvailable} where available =
    live engine + live unit subprocesses
    (SeldonDeploymentStatusUpdateImpl.java:49-104).
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from seldon_core_tpu.gateway.apife import DeploymentStore
from seldon_core_tpu.graph.defaulting import default_and_validate
from seldon_core_tpu.graph.spec import (
    GraphSpecError,
    SeldonDeploymentSpec,
)

__all__ = ["Materializer", "MaterializedDeployment"]


@dataclass
class _UnitProc:
    name: str
    popen: subprocess.Popen
    port: int
    binding: object = None
    predictor_id: str = ""
    deployment_id: str = ""
    restarts: int = 0
    last_restart: float = 0.0


@dataclass
class MaterializedDeployment:
    spec: SeldonDeploymentSpec
    engines: Dict[str, object] = field(default_factory=dict)  # predictor -> engine
    unit_procs: List[_UnitProc] = field(default_factory=list)
    applied_at: float = 0.0


class Materializer:
    def __init__(
        self,
        store: Optional[DeploymentStore] = None,
        spawn_units: bool = True,
        python: str = sys.executable,
    ):
        self.store = store or DeploymentStore()
        self.spawn_units = spawn_units
        self.python = python
        self.deployments: Dict[str, MaterializedDeployment] = {}

    # ------------------------------------------------------------------

    def apply(self, spec: SeldonDeploymentSpec) -> MaterializedDeployment:
        """Defaulting -> validation -> materialize -> register."""
        default_and_validate(spec)
        existing = self.deployments.get(spec.name)
        if existing is not None:
            self._teardown(existing)

        md = MaterializedDeployment(spec=spec, applied_at=time.time())
        try:
            for predictor in spec.predictors:
                # 1. unit subprocesses for remote bindings (the reference's
                #    per-componentSpec Deployments)
                for binding in predictor.components:
                    if binding.runtime in ("rest", "grpc") and self.spawn_units:
                        md.unit_procs.append(
                            self._spawn_unit(binding, predictor.name, spec.name)
                        )
                # 2. the engine for this predictor (reference: injected
                #    engine container per predictor)
                from seldon_core_tpu.runtime.engine import EngineService

                md.engines[predictor.name] = EngineService(spec, predictor.name)
        except Exception:
            self._teardown(md)
            raise
        self.deployments[spec.name] = md
        self.store.register(spec, md.engines)
        return md

    def delete(self, name: str) -> None:
        md = self.deployments.pop(name, None)
        if md is None:
            return
        self._teardown(md)
        self.store.unregister(md.spec.oauth_key or md.spec.name)

    def _teardown(self, md: MaterializedDeployment) -> None:
        for proc in md.unit_procs:
            if proc.popen.poll() is None:
                proc.popen.terminate()
                try:
                    proc.popen.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.popen.kill()
        md.unit_procs.clear()

    def _spawn_unit(self, binding, predictor_id: str, deployment_id: str) -> _UnitProc:
        """Launch ``microservice.py``-equivalent with the reference env
        contract (SeldonDeploymentOperatorImpl.updateContainer:195-292)."""
        if not binding.class_path:
            raise GraphSpecError(
                f"remote binding {binding.name!r} needs class_path to run "
                f"locally (no container images here)"
            )
        env = dict(os.environ)
        env.update(binding.env)
        env["PREDICTIVE_UNIT_SERVICE_PORT"] = str(binding.port)
        env["PREDICTIVE_UNIT_ID"] = binding.name
        env["PREDICTOR_ID"] = predictor_id
        env["SELDON_DEPLOYMENT_ID"] = deployment_id
        api = "GRPC" if binding.runtime == "grpc" else "REST"
        popen = subprocess.Popen(
            [
                self.python,
                "-m",
                "seldon_core_tpu.runtime.microservice",
                binding.class_path,
                api,
                "--port",
                str(binding.port),
            ],
            env=env,
        )
        return _UnitProc(
            name=binding.name,
            popen=popen,
            port=binding.port,
            binding=binding,
            predictor_id=predictor_id,
            deployment_id=deployment_id,
        )

    # ------------------------------------------------------------------

    def supervise(self) -> int:
        """Restart dead unit subprocesses with exponential backoff — the
        reference delegates this to the kubelet (k8s Deployment restart
        policy, SURVEY.md §2.7 elasticity row); a local materializer must
        supervise its own children.  Returns the number of restarts made."""
        restarted = 0
        now = time.time()
        for md in self.deployments.values():
            for proc in md.unit_procs:
                if proc.popen.poll() is None or proc.binding is None:
                    continue
                backoff = min(2.0 ** min(proc.restarts, 5), 30.0)
                if now - proc.last_restart < backoff:
                    continue
                fresh = self._spawn_unit(
                    proc.binding, proc.predictor_id, proc.deployment_id
                )
                proc.popen = fresh.popen
                proc.restarts += 1
                proc.last_restart = now
                restarted += 1
        return restarted

    # ------------------------------------------------------------------

    def status(self, name: str) -> dict:
        """Per-predictor availability — the reference CR status block
        (seldon_deployment.proto PredictorStatus)."""
        md = self.deployments.get(name)
        if md is None:
            return {"state": "absent"}
        predictors = []
        units_alive = all(p.popen.poll() is None for p in md.unit_procs)
        for predictor in md.spec.predictors:
            available = 1 if (predictor.name in md.engines and units_alive) else 0
            predictors.append(
                {
                    "name": predictor.name,
                    "replicas": predictor.replicas,
                    "replicasAvailable": available * predictor.replicas,
                }
            )
        return {
            "state": "Available" if units_alive else "Degraded",
            "predictorStatus": predictors,
            "unitRestarts": sum(p.restarts for p in md.unit_procs),
        }

    # ------------------------------------------------------------------

    async def watch_dir(self, path: str, interval_s: float = 5.0, once: bool = False):
        """Reference watch loop: 5 s schedule, mtime dedup (resourceVersion
        bookkeeping, SeldonDeploymentWatcher.java:89-171); a file removed
        from the directory deletes its deployment (ownerReference GC)."""
        seen_mtime: Dict[str, float] = {}
        file_to_name: Dict[str, str] = {}
        while True:
            self.supervise()  # restart any dead unit subprocess (backoff)
            files: Dict[str, float] = {}
            if os.path.isdir(path):
                for fn in sorted(os.listdir(path)):
                    if fn.endswith(".json"):
                        full = os.path.join(path, fn)
                        try:
                            files[full] = os.path.getmtime(full)
                        except OSError:
                            continue
            # ADDED / MODIFIED
            for full, mtime in files.items():
                if seen_mtime.get(full) == mtime:
                    continue
                seen_mtime[full] = mtime  # never retry an unchanged bad file
                try:
                    with open(full) as f:
                        spec = SeldonDeploymentSpec.from_json(f.read())
                    self.apply(spec)
                    file_to_name[full] = spec.name
                except (GraphSpecError, json.JSONDecodeError, OSError) as e:
                    import logging

                    logging.getLogger(__name__).error("apply %s failed: %s", full, e)
            # DELETED
            for full in [f for f in seen_mtime if f not in files]:
                del seen_mtime[full]
                name = file_to_name.pop(full, None)
                if name is not None:
                    self.delete(name)
                    try:
                        os.remove(full + ".status")
                    except OSError:
                        pass
            # status write-back: the reference patches the CR status
            # (SeldonDeploymentStatusUpdateImpl.java:49-104); a sibling
            # ``<spec>.json.status`` file is this materializer's CR
            for full, name in file_to_name.items():
                try:
                    with open(full + ".status", "w") as f:
                        json.dump(self.status(name), f)
                except OSError:
                    pass
            if once:
                return
            await asyncio.sleep(interval_s)

    def shutdown(self) -> None:
        for name in list(self.deployments):
            self.delete(name)


def main(argv=None) -> None:
    """``python -m seldon_core_tpu.operator.materializer <spec-dir>`` — run
    the watch/supervise/status loop over a directory of deployment specs
    (the reference's cluster-manager as a local process)."""
    import argparse

    parser = argparse.ArgumentParser(description=main.__doc__)
    parser.add_argument("spec_dir")
    parser.add_argument("--interval", type=float, default=5.0)
    parser.add_argument("--no-spawn", action="store_true",
                        help="do not launch unit subprocesses (engines only)")
    args = parser.parse_args(argv)
    m = Materializer(spawn_units=not args.no_spawn)
    try:
        asyncio.run(m.watch_dir(args.spec_dir, interval_s=args.interval))
    except KeyboardInterrupt:
        pass
    finally:
        m.shutdown()


if __name__ == "__main__":
    main()
