"""Parameterized platform bundle — the helm-values / ksonnet-prototype
packaging layer.

The reference ships its platform as templated charts: ``seldon-core``
(apife + cluster-manager + engine image + redis + RBAC,
helm-charts/seldon-core/values.yaml), ``seldon-core-crd``,
``seldon-core-analytics`` (prometheus + grafana),
``seldon-core-loadtesting``, ``seldon-core-kafka``, with the same knobs
mirrored in ksonnet (seldon-core/seldon-core/core.libsonnet:35-141).

``render_bundle(values)`` is that layer for this framework: one values
dict (or YAML file) parameterizes images, replicas, ports, RBAC, OAuth,
TPU resources/topology, analytics on/off, a loadtest job, and the firehose
consumer (this framework's Kafka-role component); the output is a list of
Kubernetes manifests ready for ``kubectl apply -f -`` via
``manifests.to_yaml_stream``.  Per-model resources stay with
``manifests.generate_manifests`` — this module renders the PLATFORM, the
same split the reference kept between its charts and the operator's
per-deployment resources.
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Dict, List, Mapping, Optional

from seldon_core_tpu.operator.reconciler import SELDON_CRD

__all__ = ["default_values", "merge_values", "render_bundle", "main"]

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def default_values() -> Dict[str, Any]:
    """The chart's tunable surface, reference values.yaml roles mapped to
    this framework's components."""
    return {
        "namespace": "seldon",
        "rbac": {"enabled": True, "service_account": "seldon"},
        "crd": {"create": True},
        "operator": {  # cluster-manager role
            "image": "seldon-core-tpu/operator:latest",
            "replicas": 1,
            "reconcile_interval_s": 10,
        },
        "gateway": {  # apife role
            "enabled": True,
            "image": "seldon-core-tpu/gateway:latest",
            "replicas": 1,
            "service_type": "NodePort",
            "rest_port": 8080,
            "grpc_port": 5000,
            "oauth": {"enabled": True},
            # shared token/deployment state (the reference's redis role):
            # a PVC (ReadWriteMany) makes the sqlite file replica-shared;
            # without it replicas>1 is refused at render time, because
            # per-pod token stores would 401 cross-replica traffic
            "state_path": "/var/run/seldon/gateway.db",
            "state_pvc": {"enabled": False, "size": "1Gi",
                          "storage_class": ""},
        },
        "engine": {  # image + env every engine pod gets
            "image": "seldon-core-tpu/engine:latest",
            "http_impl": "native",
            "grpc_impl": "native",
            "max_batch": 1024,
            "batch_wait_ms": 2.0,
            "pipeline_depth": 8,
        },
        "tpu": {  # TPU scheduling defaults for engine pods
            "resource": "google.com/tpu",
            "default_chips": 1,
            "topology_selector": "cloud.google.com/gke-tpu-topology",
        },
        "analytics": {  # seldon-core-analytics chart role
            "enabled": False,
            "prometheus_image": "prom/prometheus:v2.45.0",
            "grafana_image": "grafana/grafana:10.0.0",
            "grafana_service_type": "NodePort",
        },
        "loadtest": {  # seldon-core-loadtesting chart role
            "enabled": False,
            "image": "seldon-core-tpu/loadtest:latest",
            "target_host": "",
            "target_port": 8000,
            "contract": "/contracts/contract.json",
            "clients": 256,
            "duration_s": 60,
            "api": "rest",
        },
        "firehose": {  # seldon-core-kafka chart role (JSONL firehose)
            "consumer_enabled": False,
            "image": "seldon-core-tpu/gateway:latest",
            "base_dir": "/var/run/seldon/firehose",
            "deployment": "",  # deployment id (topic) the consumer follows
        },
    }


def merge_values(overrides: Optional[Mapping[str, Any]]) -> Dict[str, Any]:
    """Defaults deep-merged with user overrides (helm's values semantics:
    scalars replace, maps merge)."""
    def deep(base: Dict[str, Any], over: Mapping[str, Any]):
        for k, v in over.items():
            if isinstance(v, Mapping) and isinstance(base.get(k), dict):
                deep(base[k], v)
            else:
                base[k] = copy.deepcopy(v)

    values = default_values()
    if overrides:
        deep(values, overrides)
    return values


def _metadata(name: str, values: Dict[str, Any],
              labels: Optional[Dict[str, str]] = None) -> dict:
    return {
        "name": name,
        "namespace": values["namespace"],
        "labels": {"app": "seldon", "seldon-platform": name, **(labels or {})},
    }


def _deployment(name: str, values: Dict[str, Any], image: str, replicas: int,
                container: dict) -> dict:
    container = {"name": name, "image": image, **container}
    spec_pod: Dict[str, Any] = {"containers": [container]}
    if values["rbac"]["enabled"]:
        spec_pod["serviceAccountName"] = values["rbac"]["service_account"]
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _metadata(name, values),
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"seldon-platform": name}},
            "template": {
                "metadata": {
                    "labels": {"app": "seldon", "seldon-platform": name}
                },
                "spec": spec_pod,
            },
        },
    }


def _service(name: str, values: Dict[str, Any], ports: List[dict],
             service_type: str = "ClusterIP") -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _metadata(name, values),
        "spec": {
            "type": service_type,
            "selector": {"seldon-platform": name},
            "ports": ports,
        },
    }


def _rbac(values: Dict[str, Any]) -> List[dict]:
    sa = values["rbac"]["service_account"]
    return [
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": _metadata(sa, values),
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "Role",
            "metadata": _metadata("seldon-operator", values),
            "rules": [
                {
                    "apiGroups": ["machinelearning.seldon.io"],
                    "resources": ["seldondeployments",
                                  "seldondeployments/status"],
                    "verbs": ["get", "list", "watch", "create", "update",
                              "patch", "delete"],
                },
                {
                    "apiGroups": ["apps", ""],
                    "resources": ["deployments", "services"],
                    "verbs": ["get", "list", "watch", "create", "update",
                              "patch", "delete"],
                },
                {
                    "apiGroups": ["apiextensions.k8s.io"],
                    "resources": ["customresourcedefinitions"],
                    "verbs": ["get", "create"],
                },
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "RoleBinding",
            "metadata": _metadata("seldon-operator", values),
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "Role",
                "name": "seldon-operator",
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": sa,
                    "namespace": values["namespace"],
                }
            ],
        },
    ]


def _operator(values: Dict[str, Any]) -> dict:
    v = values["operator"]
    e = values["engine"]
    # the engine knobs ride the operator pod's env into every rendered
    # engine Deployment (reconciler.main reads these two variables)
    engine_env = {
        "ENGINE_HTTP_IMPL": e["http_impl"],
        "ENGINE_GRPC_IMPL": e["grpc_impl"],
        "ENGINE_MAX_BATCH": str(e["max_batch"]),
        "ENGINE_BATCH_WAIT_MS": str(e["batch_wait_ms"]),
        "ENGINE_PIPELINE_DEPTH": str(e["pipeline_depth"]),
    }
    return _deployment(
        "seldon-operator", values, v["image"], v["replicas"],
        {
            "command": ["python", "-m",
                        "seldon_core_tpu.operator.reconciler",
                        "--namespace", values["namespace"],
                        "--interval", str(v["reconcile_interval_s"])],
            "env": [
                {"name": "SELDON_ENGINE_IMAGE", "value": e["image"]},
                {"name": "SELDON_ENGINE_ENV",
                 "value": json.dumps(engine_env, sort_keys=True)},
            ],
        },
    )


def _gateway(values: Dict[str, Any]) -> List[dict]:
    v = values["gateway"]
    env = [
        {"name": "GATEWAY_OAUTH_ENABLED",
         "value": "1" if v["oauth"]["enabled"] else "0"},
        {"name": "GATEWAY_STATE_PATH", "value": v["state_path"]},
        {"name": "GATEWAY_REST_PORT", "value": str(v["rest_port"])},
        {"name": "GATEWAY_GRPC_PORT", "value": str(v["grpc_port"])},
    ]
    pvc_on = v["state_pvc"]["enabled"]
    if v["replicas"] > 1 and not pvc_on:
        raise ValueError(
            "gateway.replicas > 1 requires gateway.state_pvc.enabled: "
            "per-pod sqlite stores would reject tokens issued by other "
            "replicas (see gateway/state.py)"
        )
    state_dir = os.path.dirname(v["state_path"]) or "/var/run/seldon"
    dep = _deployment(
        "seldon-gateway", values, v["image"], v["replicas"],
        {
            "command": ["python", "-m",
                        "seldon_core_tpu.gateway.gateway_main"],
            "env": env,
            "ports": [
                {"containerPort": v["rest_port"], "name": "http"},
                {"containerPort": v["grpc_port"], "name": "grpc"},
            ],
            # /ready is 503 until a deployment registers; gateway_main
            # registers file specs BEFORE binding the server, so a probe
            # can only stay red while the spec source is genuinely empty.
            # Pin period/threshold explicitly: unready (no restart) for as
            # long as that lasts, green within ~5 s of the first register.
            "readinessProbe": {
                "httpGet": {"path": "/ready", "port": v["rest_port"]},
                "initialDelaySeconds": 5,
                "periodSeconds": 5,
                "failureThreshold": 3,
            },
            "volumeMounts": [{"name": "gateway-state",
                              "mountPath": state_dir}],
        },
    )
    dep["spec"]["template"]["spec"]["volumes"] = [
        {"name": "gateway-state",
         **({"persistentVolumeClaim": {"claimName": "seldon-gateway-state"}}
            if pvc_on else {"emptyDir": {}})}
    ]
    out: List[dict] = []
    if pvc_on:
        pvc_spec: Dict[str, Any] = {
            "accessModes": ["ReadWriteMany"],
            "resources": {"requests": {"storage": v["state_pvc"]["size"]}},
        }
        if v["state_pvc"]["storage_class"]:
            pvc_spec["storageClassName"] = v["state_pvc"]["storage_class"]
        out.append({
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": _metadata("seldon-gateway-state", values),
            "spec": pvc_spec,
        })
    svc = _service(
        "seldon-gateway", values,
        [
            {"port": v["rest_port"], "targetPort": v["rest_port"],
             "name": "http"},
            {"port": v["grpc_port"], "targetPort": v["grpc_port"],
             "name": "grpc"},
        ],
        v["service_type"],
    )
    return [*out, dep, svc]


def _analytics(values: Dict[str, Any]) -> List[dict]:
    v = values["analytics"]

    def read(rel: str) -> str:
        with open(os.path.join(_REPO, "monitoring", rel)) as f:
            return f.read()

    prom_cm = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": _metadata("seldon-prometheus-config", values),
        "data": {
            "prometheus.yml": read("prometheus.yml"),
            "alerts.yml": read("alerts.yml"),
        },
    }
    graf_cm = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": _metadata("seldon-grafana-dashboards", values),
        "data": {
            "predictions-analytics-dashboard.json": read(
                os.path.join("grafana",
                             "predictions-analytics-dashboard.json")
            ),
        },
    }
    prom = _deployment(
        "seldon-prometheus", values, v["prometheus_image"], 1,
        {
            "args": ["--config.file=/etc/prometheus/prometheus.yml"],
            "ports": [{"containerPort": 9090}],
            "volumeMounts": [
                {"name": "config", "mountPath": "/etc/prometheus"}
            ],
        },
    )
    prom["spec"]["template"]["spec"]["volumes"] = [
        {"name": "config",
         "configMap": {"name": "seldon-prometheus-config"}}
    ]
    graf = _deployment(
        "seldon-grafana", values, v["grafana_image"], 1,
        {
            "ports": [{"containerPort": 3000}],
            "volumeMounts": [
                {"name": "dashboards",
                 "mountPath": "/var/lib/grafana/dashboards"}
            ],
        },
    )
    graf["spec"]["template"]["spec"]["volumes"] = [
        {"name": "dashboards",
         "configMap": {"name": "seldon-grafana-dashboards"}}
    ]
    return [
        prom_cm, graf_cm, prom,
        _service("seldon-prometheus", values,
                 [{"port": 9090, "targetPort": 9090}]),
        graf,
        _service("seldon-grafana", values,
                 [{"port": 3000, "targetPort": 3000}],
                 v["grafana_service_type"]),
    ]


def _loadtest_job(values: Dict[str, Any]) -> dict:
    v = values["loadtest"]
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": _metadata("seldon-loadtest", values),
        "spec": {
            "backoffLimit": 0,
            "template": {
                "metadata": {"labels": {"app": "seldon",
                                        "seldon-platform": "loadtest"}},
                "spec": {
                    "restartPolicy": "Never",
                    "containers": [
                        {
                            "name": "loadtest",
                            "image": v["image"],
                            "command": [
                                "python", "-m",
                                "seldon_core_tpu.testing.loadtest",
                                v["contract"], v["target_host"],
                                str(v["target_port"]),
                                "--native", "--api", v["api"],
                                "--clients", str(v["clients"]),
                                "--duration", str(v["duration_s"]),
                            ],
                        }
                    ],
                },
            },
        },
    }


def _firehose_consumer(values: Dict[str, Any]) -> dict:
    v = values["firehose"]
    return _deployment(
        "seldon-firehose-consumer", values, v["image"], 1,
        {
            "command": ["python", "-m", "seldon_core_tpu.gateway.firehose",
                        v["deployment"], "--dir", v["base_dir"], "--follow"],
        },
    )


def render_bundle(overrides: Optional[Mapping[str, Any]] = None) -> List[dict]:
    """Values -> full platform manifest list (reference chart-set parity:
    crd, core, analytics, loadtesting, kafka-role firehose)."""
    values = merge_values(overrides)
    out: List[dict] = []
    if values["crd"]["create"]:
        crd = copy.deepcopy(SELDON_CRD)
        out.append(crd)
    if values["rbac"]["enabled"]:
        out.extend(_rbac(values))
    out.append(_operator(values))
    if values["gateway"]["enabled"]:
        out.extend(_gateway(values))
    if values["analytics"]["enabled"]:
        out.extend(_analytics(values))
    if values["loadtest"]["enabled"]:
        out.append(_loadtest_job(values))
    if values["firehose"]["consumer_enabled"]:
        out.append(_firehose_consumer(values))
    return out


def main(argv=None) -> None:
    """Render the platform bundle to YAML.

        python -m seldon_core_tpu.operator.bundle \
            [--values values.yaml] [--set analytics.enabled=true ...]
    """
    import argparse

    from seldon_core_tpu.operator.manifests import to_yaml_stream

    parser = argparse.ArgumentParser(description="platform bundle renderer")
    parser.add_argument("--values", default=None, help="values YAML/JSON file")
    parser.add_argument(
        "--set", action="append", default=[],
        help="dotted override, e.g. analytics.enabled=true",
    )
    args = parser.parse_args(argv)
    overrides: Dict[str, Any] = {}
    if args.values:
        with open(args.values) as f:
            text = f.read()
        try:
            overrides = json.loads(text)
        except json.JSONDecodeError:
            import yaml

            overrides = yaml.safe_load(text) or {}
    for item in args.set:
        key, _, raw = item.partition("=")
        value: Any = raw
        if raw.lower() in ("true", "false"):
            value = raw.lower() == "true"
        else:
            try:
                value = int(raw)
            except ValueError:
                try:
                    value = float(raw)
                except ValueError:
                    pass
        node = overrides
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    print(to_yaml_stream(render_bundle(overrides)), end="")


if __name__ == "__main__":
    main()
