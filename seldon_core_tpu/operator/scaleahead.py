"""Predictive scale-ahead — replicas move BEFORE the burn window fires.

``operator/reconciler.py`` has always copied ``spec.replicas`` verbatim:
capacity only ever changed by hand, after the SLO had already burned.
This module closes ROADMAP item 3's autoscaling half: a
:class:`ScaleAheadPlanner` accumulates per-deployment load samples
(queue depth + gateway-side inflight, scraped from the gateway / fed by
the autopilot's surfaces), fits the queue-growth trend, and forecasts
the load ``horizon_s`` ahead — the 5-minute fast-burn window by
default, so the replica write lands before the page would.  The
reconciler consults it per tick and overrides the rendered engine
Deployments' ``spec.replicas``:

  * **Scale-out** is eager: the forecast (or the live load, whichever
    is larger) divided by the per-replica target decides the count —
    a growing queue buys capacity on the trend, not on the damage.
  * **Scale-in** is deliberate: hysteresis (the forecast must clear the
    smaller fleet's capacity with margin) and HARD-GATED on the rollout
    controller — a canary in flight holds the floor, because shrinking
    the fleet mid-rollout would let a capacity cut masquerade as (or
    mask) a candidate regression.  Same fail-closed polarity as the
    rollout gates: when in doubt, keep the capacity.

Opt-in per CR via annotations (docs/operations.md "Surviving
overload")::

    seldon.io/autoscale: "true"
    seldon.io/autoscale-min: "1"            # floor (default 1)
    seldon.io/autoscale-max: "8"            # ceiling (default 8)
    seldon.io/autoscale-target-inflight: "4"   # per-replica load target
    seldon.io/autoscale-horizon-s: "300"    # forecast horizon

Malformed annotations fail the reconcile with a clear CR status (the
same contract as the canary annotations), never a crash loop.  Every
decision is a typed record on :meth:`ScaleAheadPlanner.snapshot` and
the CR's ``status.autoscale`` block, so "why did the fleet grow at
14:02" is one status read."""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

__all__ = [
    "AUTOSCALE_ANNOTATION",
    "AutoscalePolicy",
    "ScaleAheadPlanner",
    "gateway_load_sample",
]

AUTOSCALE_ANNOTATION = "seldon.io/autoscale"
ANN_MIN = "seldon.io/autoscale-min"
ANN_MAX = "seldon.io/autoscale-max"
ANN_TARGET = "seldon.io/autoscale-target-inflight"
ANN_HORIZON = "seldon.io/autoscale-horizon-s"


@dataclass
class AutoscalePolicy:
    """Per-CR scale-ahead contract, parsed from annotations."""

    min_replicas: int = 1
    max_replicas: int = 8
    target_inflight: float = 4.0
    horizon_s: float = 300.0
    #: scale-in headroom: the forecast must fit the SMALLER fleet at
    #: this utilization or better before a replica is taken away
    scale_in_margin: float = 0.85

    @classmethod
    def from_spec(cls, spec) -> Optional["AutoscalePolicy"]:
        """None unless the CR opts in; ValueError on malformed values
        (the reconciler surfaces it as a Failed/invalid status)."""
        ann = getattr(spec, "annotations", None) or {}
        if str(ann.get(AUTOSCALE_ANNOTATION, "")).lower() != "true":
            return None
        try:
            policy = cls(
                min_replicas=int(ann.get(ANN_MIN, 1)),
                max_replicas=int(ann.get(ANN_MAX, 8)),
                target_inflight=float(ann.get(ANN_TARGET, 4.0)),
                horizon_s=float(ann.get(ANN_HORIZON, 300.0)),
            )
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"malformed seldon.io/autoscale-* annotation: {e}"
            ) from e
        if policy.min_replicas < 1 or policy.max_replicas < policy.min_replicas:
            raise ValueError(
                f"autoscale bounds invalid: min={policy.min_replicas} "
                f"max={policy.max_replicas}"
            )
        if policy.target_inflight <= 0 or policy.horizon_s <= 0:
            raise ValueError(
                "autoscale-target-inflight and autoscale-horizon-s must "
                "be positive"
            )
        return policy


class ScaleAheadPlanner:
    """Per-deployment load series -> forecast -> desired replica count."""

    MAX_SAMPLES = 128
    MAX_DECISIONS = 64

    def __init__(self, now_fn: Callable[[], float] = time.monotonic):
        self._now = now_fn
        self._series: Dict[str, deque] = {}
        self.decisions: deque = deque(maxlen=self.MAX_DECISIONS)

    # -- signal intake ----------------------------------------------------

    def observe(self, deployment: str, *, queue_depth: float = 0.0,
                inflight: float = 0.0, burn_5m: float = 0.0,
                now: Optional[float] = None) -> None:
        """One load sample.  ``queue_depth + inflight`` is the load the
        fleet must absorb; ``burn_5m`` rides along for the decision
        record (the planner acts BEFORE burn, it doesn't wait for it)."""
        now = now if now is not None else self._now()
        q = self._series.setdefault(
            deployment, deque(maxlen=self.MAX_SAMPLES))
        q.append((float(now), float(queue_depth) + float(inflight),
                  float(burn_5m)))

    # -- forecast ---------------------------------------------------------

    def forecast(self, deployment: str, horizon_s: float,
                 now: Optional[float] = None) -> Dict[str, float]:
        """Least-squares trend over the retained samples, extrapolated
        ``horizon_s`` ahead (clamped at zero).  With < 2 samples the
        forecast is the last observation — no trend, no extrapolation."""
        now = now if now is not None else self._now()
        q = self._series.get(deployment)
        if not q:
            return {"current": 0.0, "predicted": 0.0, "slope_per_s": 0.0,
                    "samples": 0}
        ts = [s[0] for s in q]
        loads = [s[1] for s in q]
        current = loads[-1]
        n = len(q)
        if n < 2 or ts[-1] == ts[0]:
            return {"current": current, "predicted": current,
                    "slope_per_s": 0.0, "samples": n}
        tbar = sum(ts) / n
        lbar = sum(loads) / n
        denom = sum((t - tbar) ** 2 for t in ts)
        slope = (
            sum((t - tbar) * (l - lbar) for t, l in zip(ts, loads)) / denom
            if denom > 0 else 0.0
        )
        predicted = max(0.0, current + slope * horizon_s)
        return {"current": current, "predicted": predicted,
                "slope_per_s": slope, "samples": n}

    # -- the decision -----------------------------------------------------

    def desired_replicas(
        self,
        deployment: str,
        current_replicas: int,
        policy: AutoscalePolicy,
        rollout_active: bool = False,
        now: Optional[float] = None,
    ) -> Dict[str, Any]:
        """The replica count the reconciler should write, with the full
        reasoning as a typed record (also appended to ``decisions``)."""
        fc = self.forecast(deployment, policy.horizon_s, now=now)
        # no samples = no signal, NOT "idle": an operator restart (the
        # planner is in-memory) or a dead scrape feed must hold the
        # fleet, never cut capacity mid-overload — the same
        # keep-capacity-when-in-doubt polarity as the rollout gate
        if fc["samples"] == 0:
            decision = {
                "deployment": deployment,
                "ts": time.time(),
                "current_replicas": int(current_replicas),
                "desired_replicas": int(current_replicas),
                "reason": "no load signal (hold)",
                "rollout_active": bool(rollout_active),
                "load_now": 0.0, "load_forecast": 0.0,
                "slope_per_s": 0.0,
                "horizon_s": policy.horizon_s,
                "target_inflight": policy.target_inflight,
            }
            return decision
        # plan for the WORSE of live load and forecast: a spike that
        # already arrived must not be scaled for "later"
        load = max(fc["current"], fc["predicted"])
        want = max(1, math.ceil(load / policy.target_inflight))
        want = min(max(want, policy.min_replicas), policy.max_replicas)
        reason = "steady"
        if want > current_replicas:
            reason = "queue-growth forecast"
        elif want < current_replicas:
            if rollout_active:
                # a canary never masks a capacity cut: hold the fleet
                want, reason = current_replicas, "scale-in rollout-gated"
            else:
                # hysteresis: the smaller fleet must absorb the forecast
                # with margin, or we'd flap at the boundary
                cap = (want * policy.target_inflight
                       * policy.scale_in_margin)
                if load > cap:
                    want, reason = current_replicas, "scale-in hysteresis"
                else:
                    reason = "load receded"
        decision = {
            "deployment": deployment,
            "ts": time.time(),
            "current_replicas": int(current_replicas),
            "desired_replicas": int(want),
            "reason": reason,
            "rollout_active": bool(rollout_active),
            "load_now": round(fc["current"], 3),
            "load_forecast": round(fc["predicted"], 3),
            "slope_per_s": round(fc["slope_per_s"], 6),
            "horizon_s": policy.horizon_s,
            "target_inflight": policy.target_inflight,
        }
        if want != current_replicas:
            self.decisions.append(decision)
        return decision

    def snapshot(self) -> Dict[str, Any]:
        return {
            "deployments": {
                dep: {
                    "samples": len(q),
                    "last_load": q[-1][1] if q else 0.0,
                }
                for dep, q in self._series.items()
            },
            "decisions": list(self.decisions)[-16:],
        }

    def reset(self) -> None:
        self._series = {}
        self.decisions.clear()


def gateway_load_sample(gateway, deployment: str) -> Dict[str, float]:
    """Scrape one load sample for ``deployment`` from an in-process
    gateway: gateway-side inflight summed over the deployment's replica
    sets, plus the fair-queue backlog, plus the global 5m burn — the
    co-located-control-plane analogue of the rollout controller's
    GatewaySignals.  Feed the result to :meth:`ScaleAheadPlanner
    .observe`."""
    inflight = 0
    for (dep, _pred), (_fp, rs) in getattr(
            gateway, "_replica_sets", {}).items():
        if dep != deployment:
            continue
        for ep in rs.endpoints:
            inflight += max(int(getattr(ep, "inflight", 0)), 0)
    queue_depth = 0
    tenants = getattr(gateway, "tenants", None)
    if tenants is not None:
        queue_depth = tenants.queue_depth()
    burn = 0.0
    try:
        from seldon_core_tpu.utils.quality import QUALITY

        if QUALITY.slo.configured:
            burn = float(QUALITY.slo.burn_rates()["5m"]["burn_rate"])
    except Exception:  # noqa: BLE001 - a dead feed is a zero, not a crash
        pass
    return {"queue_depth": float(queue_depth),
            "inflight": float(inflight), "burn_5m": burn}
