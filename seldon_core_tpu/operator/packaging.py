"""Model-image packaging — the s2i/docker-wrapper equivalent.

The reference ships source-to-image builders whose contract is four env
vars + a requirements.txt (wrappers/s2i/python/s2i/bin/run:11-21:
``MODEL_NAME``, ``API_TYPE``, ``SERVICE_TYPE``, ``PERSISTENCE``) and a
legacy jinja2 docker wrapper (wrappers/python/wrap_model.py:12-54) that
copies the microservice next to the user model.  Same contract here: point
``package_model`` at a directory containing the user class; it writes a
Dockerfile, a ``.s2i/environment`` file, and a ``run.sh`` that exec's the
wrapper CLI (runtime/microservice.py) — buildable with any container tool,
no s2i binary needed.
"""

from __future__ import annotations

import os
import shutil
import stat
from dataclasses import dataclass
from typing import Optional

__all__ = ["ImageSpec", "package_model"]

_BASE_IMAGE = "seldon-core-tpu/base:latest"

_DOCKERFILE = """\
FROM {base_image}

WORKDIR /microservice
COPY . /microservice
RUN if [ -f requirements.txt ]; then pip install --no-cache-dir -r requirements.txt; fi

ENV MODEL_NAME={model_name}
ENV API_TYPE={api_type}
ENV SERVICE_TYPE={service_type}
ENV PERSISTENCE={persistence}
EXPOSE 5000

CMD ["/bin/sh", "/microservice/run.sh"]
"""

_RUN_SH = """\
#!/bin/sh
# s2i run contract (reference wrappers/s2i/python/s2i/bin/run:11-21)
exec python -m seldon_core_tpu.runtime.microservice \\
    "$MODEL_NAME" "$API_TYPE" \\
    --service-type "$SERVICE_TYPE" \\
    --persistence "$PERSISTENCE"
"""

_S2I_ENV = """\
MODEL_NAME={model_name}
API_TYPE={api_type}
SERVICE_TYPE={service_type}
PERSISTENCE={persistence}
"""


@dataclass
class ImageSpec:
    model_name: str                 # module:Class or registered unit name
    api_type: str = "REST"          # REST | GRPC
    service_type: str = "MODEL"     # MODEL|ROUTER|TRANSFORMER|COMBINER|OUTLIER_DETECTOR
    persistence: int = 0
    base_image: str = _BASE_IMAGE

    def validate(self) -> None:
        from seldon_core_tpu.runtime.microservice import SERVICE_TYPES

        if self.api_type not in ("REST", "GRPC"):
            raise ValueError(f"api_type must be REST or GRPC, got {self.api_type!r}")
        if self.service_type not in SERVICE_TYPES:
            raise ValueError(f"unknown service_type {self.service_type!r}")
        if not self.model_name:
            raise ValueError("model_name is required")


def package_model(model_dir: str, spec: ImageSpec,
                  out_dir: Optional[str] = None) -> dict:
    """Write Dockerfile / run.sh / .s2i/environment into ``out_dir``
    (default: the model dir).  Returns {filename: path} for what was written.
    """
    spec.validate()
    out_dir = out_dir or model_dir
    os.makedirs(out_dir, exist_ok=True)
    if os.path.realpath(out_dir) != os.path.realpath(model_dir):
        # out_dir becomes the docker build context ("COPY . /microservice"),
        # so the model sources must be staged into it
        shutil.copytree(model_dir, out_dir, dirs_exist_ok=True)
    os.makedirs(os.path.join(out_dir, ".s2i"), exist_ok=True)
    fields = dict(
        base_image=spec.base_image,
        model_name=spec.model_name,
        api_type=spec.api_type,
        service_type=spec.service_type,
        persistence=int(spec.persistence),
    )
    written = {}

    def emit(rel: str, content: str, executable: bool = False):
        path = os.path.join(out_dir, rel)
        with open(path, "w") as f:
            f.write(content)
        if executable:
            os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR | stat.S_IXGRP)
        written[rel] = path

    emit("Dockerfile", _DOCKERFILE.format(**fields))
    emit("run.sh", _RUN_SH, executable=True)
    emit(os.path.join(".s2i", "environment"), _S2I_ENV.format(**fields))
    return written
