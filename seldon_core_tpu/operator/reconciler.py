"""Live reconciliation loop — the operator's cluster-facing half.

The reference operator is a control loop against the Kubernetes API:
``CRDCreator`` registers the SeldonDeployment CRD at boot (cluster-manager
k8s/CRDCreator.java:33-60), ``SeldonDeploymentControllerImpl`` LISTs owned
resources and issues create/update/delete to converge them on the CR's
desired state (k8s/SeldonDeploymentControllerImpl.java:69-111), and
``SeldonDeploymentStatusUpdateImpl`` writes progress back onto the CR's
``status`` (k8s/SeldonDeploymentStatusUpdateImpl.java:49-104).

This module is that loop with the API server behind a small pluggable
client interface:

  * :class:`KubeClient` — the five verbs the loop needs (list / get /
    create / replace / delete + status patch).  :class:`FakeKubeApi` is an
    in-memory implementation for tests and local runs;
    :class:`KubectlClient` shells out to ``kubectl`` for a real cluster.
  * :class:`Reconciler` — desired state comes from
    ``manifests.generate_manifests`` (the same rendering ``kubectl apply``
    consumers use); convergence is hash-driven: every rendered resource
    carries a ``seldon.io/config-hash`` annotation, and an observed
    resource is replaced only when its hash differs, so a steady-state
    reconcile is zero API writes (the reference compares resource
    versions the same way).  Resources owned by the CR but no longer
    rendered — a removed predictor or component — are pruned.
  * Status write-back: ``Creating`` until every owned Deployment reports
    ``readyReplicas >= replicas``, then ``Available``; per-predictor
    replica counts mirror the reference's ``PredictorStatus`` list.
"""

from __future__ import annotations

import copy
import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from seldon_core_tpu.graph.spec import GraphSpecError, SeldonDeploymentSpec
from seldon_core_tpu.operator.manifests import generate_manifests

__all__ = [
    "KubeClient",
    "KubeConflict",
    "FakeKubeApi",
    "HostileKubeApi",
    "KubectlClient",
    "Reconciler",
    "SELDON_CRD",
    "HASH_ANNOTATION",
    "OWNER_LABEL",
]

HASH_ANNOTATION = "seldon.io/config-hash"
OWNER_LABEL = "seldon-deployment-id"

GROUP = "machinelearning.seldon.io"
CRD_NAME = f"seldondeployments.{GROUP}"

#: CustomResourceDefinition for SeldonDeployment — the resource
#: CRDCreator.java registers at operator boot.  Schema kept permissive the
#: way the reference's was (validation happens in graph/defaulting.py, the
#: same split the reference used between the CRD and ClusterManager).
SELDON_CRD = {
    "apiVersion": "apiextensions.k8s.io/v1",
    "kind": "CustomResourceDefinition",
    "metadata": {"name": CRD_NAME},
    "spec": {
        "group": GROUP,
        "names": {
            "kind": "SeldonDeployment",
            "listKind": "SeldonDeploymentList",
            "plural": "seldondeployments",
            "singular": "seldondeployment",
            "shortNames": ["sdep"],
        },
        "scope": "Namespaced",
        "versions": [
            {
                "name": "v1alpha2",
                "served": True,
                "storage": True,
                "subresources": {"status": {}},
                "schema": {
                    "openAPIV3Schema": {
                        "type": "object",
                        "properties": {
                            "spec": {
                                "type": "object",
                                "x-kubernetes-preserve-unknown-fields": True,
                            },
                            "status": {
                                "type": "object",
                                "x-kubernetes-preserve-unknown-fields": True,
                            },
                        },
                    }
                },
            }
        ],
    },
}


class KubeConflict(Exception):
    """HTTP 409 — optimistic-concurrency conflict (stale resourceVersion)
    or a write colliding with another actor's.  The real API server
    returns these routinely under controller races; the reconcile loop
    resolves them by re-reading and retrying
    (SeldonDeploymentControllerImpl.java:69-111 takes the same
    LIST -> CREATE(404)/UPDATE shape for the same reason)."""


class KubeClient:
    """The API-server verbs the reconcile loop needs.  Implementations must
    be idempotent-friendly: create on an existing object raises KeyError,
    replace/delete on a missing one raises KeyError; optimistic-concurrency
    failures raise KubeConflict."""

    def list(self, kind: str, namespace: str,
             label_selector: Optional[Dict[str, str]] = None) -> List[dict]:
        raise NotImplementedError

    def get(self, kind: str, namespace: str, name: str) -> Optional[dict]:
        raise NotImplementedError

    def create(self, obj: dict) -> None:
        raise NotImplementedError

    def replace(self, obj: dict) -> None:
        raise NotImplementedError

    def delete(self, kind: str, namespace: str, name: str) -> None:
        raise NotImplementedError

    def patch_status(self, kind: str, namespace: str, name: str,
                     status: dict) -> None:
        raise NotImplementedError


def _meta(obj: dict) -> Tuple[str, str, str]:
    md = obj.get("metadata", {})
    return obj.get("kind", ""), md.get("namespace", "default"), md.get("name", "")


@dataclass
class FakeKubeApi(KubeClient):
    """In-memory API server for tests and local dry-runs — the role minikube
    played in the reference's E2E notebooks
    (notebooks/kubectl_demo_minikube_rbac.ipynb), without a cluster.

    Records every mutating verb in ``ops`` so tests can assert convergence
    properties (e.g. steady-state reconciles issue zero writes)."""

    objects: Dict[Tuple[str, str, str], dict] = field(default_factory=dict)
    ops: List[Tuple[str, str]] = field(default_factory=list)
    _rv: int = 0

    def _bump_rv(self, obj: dict) -> None:
        self._rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)

    def list(self, kind, namespace, label_selector=None):
        out = []
        for (k, ns, _), obj in sorted(self.objects.items()):
            if k != kind or ns != namespace:
                continue
            if label_selector:
                labels = obj.get("metadata", {}).get("labels", {})
                if any(labels.get(lk) != lv
                       for lk, lv in label_selector.items()):
                    continue
            out.append(copy.deepcopy(obj))
        return out

    def get(self, kind, namespace, name):
        obj = self.objects.get((kind, namespace, name))
        return copy.deepcopy(obj) if obj is not None else None

    def create(self, obj):
        key = _meta(obj)
        if key in self.objects:
            raise KeyError(f"already exists: {key}")
        stored = copy.deepcopy(obj)
        self._bump_rv(stored)
        self.objects[key] = stored
        self.ops.append(("create", f"{key[0]}/{key[2]}"))

    def replace(self, obj):
        key = _meta(obj)
        if key not in self.objects:
            raise KeyError(f"not found: {key}")
        live = self.objects[key]
        # optimistic concurrency, real-API-server semantics: a caller that
        # echoes a resourceVersion must echo the CURRENT one; objects
        # rendered fresh (no resourceVersion) behave like server-side
        # apply and win (KubectlClient.replace uses exactly that)
        sent_rv = obj.get("metadata", {}).get("resourceVersion")
        live_rv = live.get("metadata", {}).get("resourceVersion")
        if sent_rv is not None and live_rv is not None and sent_rv != live_rv:
            raise KubeConflict(
                f"conflict: {key} resourceVersion {sent_rv} != {live_rv}"
            )
        prior_status = live.get("status")
        stored = copy.deepcopy(obj)
        self._bump_rv(stored)
        self.objects[key] = stored
        if prior_status is not None and "status" not in obj:
            self.objects[key]["status"] = prior_status  # replace keeps status
        self.ops.append(("replace", f"{key[0]}/{key[2]}"))

    def delete(self, kind, namespace, name):
        key = (kind, namespace, name)
        if key not in self.objects:
            raise KeyError(f"not found: {key}")
        del self.objects[key]
        self.ops.append(("delete", f"{kind}/{name}"))

    def patch_status(self, kind, namespace, name, status):
        key = (kind, namespace, name)
        if key not in self.objects:
            raise KeyError(f"not found: {key}")
        self.objects[key].setdefault("status", {}).update(
            copy.deepcopy(status)
        )
        self._bump_rv(self.objects[key])
        self.ops.append(("patch_status", f"{kind}/{name}"))

    # -- test conveniences ---------------------------------------------

    def mark_deployments_ready(self, namespace: str = "default") -> None:
        """Simulate kubelet convergence: every Deployment reports its
        desired replica count ready."""
        for (kind, ns, _), obj in self.objects.items():
            if kind == "Deployment" and ns == namespace:
                want = obj.get("spec", {}).get("replicas", 1)
                obj["status"] = {"replicas": want, "readyReplicas": want}

    def clear_ops(self) -> None:
        self.ops.clear()


@dataclass
class HostileKubeApi(FakeKubeApi):
    """FakeKubeApi with the real API server's failure modes, injectable —
    the semantics the reference controller hardens against
    (SeldonDeploymentControllerImpl.java:69-111 create-vs-update races,
    SeldonDeploymentWatcher.java:89-153 stale resourceVersions).

    Knobs:
      * ``fail_queue`` — list of (verb, kind_or_name_substring, exception);
        the next matching call consumes the entry and raises.  Use for
        transient 500s (RuntimeError) and injected 409s (KubeConflict).
      * ``race_on_get_miss`` — when get() misses for a (kind, name) listed
        here, a phantom controller creates the object BEFORE returning, so
        the caller's get->create window always loses the race.
      * ``delete_crs_after_writes`` — once this many mutating verbs have
        landed, every SeldonDeployment CR vanishes (mid-reconcile CR
        deletion)."""

    fail_queue: List[Tuple[str, str, Exception]] = field(default_factory=list)
    race_on_get_miss: List[Tuple[str, str]] = field(default_factory=list)
    delete_crs_after_writes: Optional[int] = None
    _writes: int = 0

    def _maybe_fail(self, verb: str, ident: str) -> None:
        for i, (v, frag, exc) in enumerate(self.fail_queue):
            if v == verb and frag in ident:
                del self.fail_queue[i]
                raise exc

    def _count_write(self) -> None:
        self._writes += 1
        if (self.delete_crs_after_writes is not None
                and self._writes >= self.delete_crs_after_writes):
            self.delete_crs_after_writes = None
            for key in [k for k in self.objects
                        if k[0] == "SeldonDeployment"]:
                del self.objects[key]
                self.ops.append(("hostile_delete", f"{key[0]}/{key[2]}"))

    def list(self, kind, namespace, label_selector=None):
        self._maybe_fail("list", kind)
        return super().list(kind, namespace, label_selector)

    def get(self, kind, namespace, name):
        self._maybe_fail("get", f"{kind}/{name}")
        obj = super().get(kind, namespace, name)
        if obj is None and (kind, name) in self.race_on_get_miss:
            self.race_on_get_miss.remove((kind, name))
            phantom = {
                "kind": kind,
                "metadata": {"namespace": namespace, "name": name,
                             "annotations": {HASH_ANNOTATION: "phantom"},
                             "labels": {}},
            }
            super().create(phantom)
            self.ops.append(("hostile_create", f"{kind}/{name}"))
        return obj

    def create(self, obj):
        key = _meta(obj)
        self._maybe_fail("create", f"{key[0]}/{key[2]}")
        super().create(obj)
        self._count_write()

    def replace(self, obj):
        key = _meta(obj)
        self._maybe_fail("replace", f"{key[0]}/{key[2]}")
        super().replace(obj)
        self._count_write()

    def delete(self, kind, namespace, name):
        self._maybe_fail("delete", f"{kind}/{name}")
        super().delete(kind, namespace, name)
        self._count_write()

    def patch_status(self, kind, namespace, name, status):
        self._maybe_fail("patch_status", f"{kind}/{name}")
        super().patch_status(kind, namespace, name, status)


class KubectlClient(KubeClient):
    """Real-cluster client: each verb shells to ``kubectl`` with JSON IO.
    Used when the operator runs against an actual API server; everything
    the Reconciler needs from a cluster rides these five subcommands."""

    def __init__(self, kubectl: str = "kubectl"):
        self.kubectl = kubectl

    def _run(self, args: List[str], stdin: Optional[str] = None) -> str:
        import subprocess

        proc = subprocess.run(
            [self.kubectl, *args], input=stdin, capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            if "NotFound" in proc.stderr or "AlreadyExists" in proc.stderr:
                raise KeyError(proc.stderr.strip())
            if "Conflict" in proc.stderr or "conflict" in proc.stderr:
                raise KubeConflict(proc.stderr.strip())
            raise RuntimeError(proc.stderr.strip())
        return proc.stdout

    def list(self, kind, namespace, label_selector=None):
        args = ["get", kind, "-n", namespace, "-o", "json"]
        if label_selector:
            args += ["-l", ",".join(f"{k}={v}"
                                    for k, v in label_selector.items())]
        return json.loads(self._run(args)).get("items", [])

    def get(self, kind, namespace, name):
        try:
            return json.loads(
                self._run(["get", kind, name, "-n", namespace, "-o", "json"])
            )
        except KeyError:
            return None

    def create(self, obj):
        self._run(["create", "-f", "-"], stdin=json.dumps(obj))

    def replace(self, obj):
        # server-side apply, not PUT: a freshly rendered Service carries no
        # clusterIP/resourceVersion and a bare replace would be rejected
        # ("field is immutable"); apply merges onto the live object
        self._run(
            ["apply", "--server-side", "--force-conflicts", "-f", "-"],
            stdin=json.dumps(obj),
        )

    def delete(self, kind, namespace, name):
        self._run(["delete", kind, name, "-n", namespace, "--wait=false"])

    def patch_status(self, kind, namespace, name, status):
        self._run(
            ["patch", kind, name, "-n", namespace, "--subresource=status",
             "--type=merge", "-p", json.dumps({"status": status})]
        )


def _config_hash(obj: dict) -> str:
    """Content hash over everything but status/annotations-hash — the
    convergence test (the reference compared generated vs live specs
    field-by-field; a hash of our own rendering is equivalent and cheap)."""
    trimmed = copy.deepcopy(obj)
    trimmed.pop("status", None)
    md = trimmed.get("metadata", {})
    md.get("annotations", {}).pop(HASH_ANNOTATION, None)
    return hashlib.sha256(
        json.dumps(trimmed, sort_keys=True).encode()
    ).hexdigest()[:16]


class Reconciler:
    """Converge owned resources on each SeldonDeployment CR."""

    def __init__(self, client: KubeClient, namespace: str = "default",
                 engine_image: str = "",
                 engine_env: Optional[Dict[str, str]] = None,
                 rollouts=None, autoscaler=None):
        # engine_image/engine_env: the chart-level engine knobs
        # (bundle.py values.engine) flowing into every rendered engine pod,
        # the reference's ENGINE_CONTAINER_IMAGE_AND_VERSION property role
        self.client = client
        self.namespace = namespace
        self.engine_image = engine_image
        self.engine_env = dict(engine_env or {})
        #: optional RolloutController (operator/rollouts.py): CRs
        #: annotated ``seldon.io/canary`` get staged traffic shifts with
        #: gate-checked auto-rollback, driven one tick per reconcile and
        #: written back onto the CR status as ``status.rollout``
        self.rollouts = rollouts
        #: optional ScaleAheadPlanner (operator/scaleahead.py): CRs
        #: annotated ``seldon.io/autoscale`` get their rendered engine
        #: Deployments' spec.replicas written from the planner's
        #: queue-growth forecast — scale-out lands ahead of the 5m burn
        #: window, scale-in is gated on the rollout controller
        self.autoscaler = autoscaler
        self._autoscale_status: Dict[str, dict] = {}

    # -- CRD bootstrap ---------------------------------------------------

    def ensure_crd(self) -> bool:
        """Register the SeldonDeployment CRD if absent (CRDCreator.java's
        boot path).  Returns True when it had to be created.

        CRDs are cluster-scoped: the lookup must use the same namespace
        key the (namespace-less) SELDON_CRD manifest stores under, NOT
        this reconciler's working namespace — kubectl ignores -n for
        cluster-scoped kinds, and the fake API defaults them to
        'default'."""
        existing = self.client.get(
            "CustomResourceDefinition", "default", CRD_NAME
        )
        if existing is not None:
            return False
        self.client.create(copy.deepcopy(SELDON_CRD))
        return True

    # -- one CR ------------------------------------------------------------

    def _desired(self, cr: dict) -> List[dict]:
        spec = SeldonDeploymentSpec.from_json_dict(cr)
        manifests = generate_manifests(
            spec, engine_image=self.engine_image, engine_env=self.engine_env
        )
        name = cr.get("metadata", {}).get("name", spec.name)
        uid = cr.get("metadata", {}).get("uid", "")
        # predictive scale-ahead BEFORE hashing: the replica override is
        # part of the desired state, so convergence sees it like any
        # other spec change (steady forecast = steady hash = zero writes)
        self._apply_autoscale(spec, name, manifests)
        for m in manifests:
            md = m.setdefault("metadata", {})
            md["namespace"] = self.namespace
            md.setdefault("labels", {})[OWNER_LABEL] = name
            # ownerReferences: the cluster GC's prune contract; our own
            # prune pass below covers API servers without GC (fake, tests)
            md["ownerReferences"] = [
                {
                    "apiVersion": f"{GROUP}/v1alpha2",
                    "kind": "SeldonDeployment",
                    "name": name,
                    "uid": uid,
                    "controller": True,
                }
            ]
            md.setdefault("annotations", {})[HASH_ANNOTATION] = \
                _config_hash(m)
        return manifests

    def reconcile(self, cr: dict) -> Dict[str, int]:
        """One convergence pass for one CR.  Returns the verb counts
        (creates/updates/deletes) so callers and tests can see the work."""
        name = cr.get("metadata", {}).get("name", "")
        try:
            desired = self._desired(cr)
        except Exception as e:
            # invalid spec: surface on the CR like the reference's FAILED
            # state (SeldonDeploymentStatusUpdateImpl failure path).  The
            # permissive CRD schema admits arbitrary JSON, so ANY parse/
            # render error must land here — one malformed CR must never
            # take down reconciliation for the rest of the cluster
            self._patch_cr_status(name, {
                "state": "Failed",
                "description": f"{type(e).__name__}: {e}",
            })
            return {"creates": 0, "updates": 0, "deletes": 0, "failed": 1}
        counts = {"creates": 0, "updates": 0, "deletes": 0}
        desired_keys = set()
        for m in desired:
            kind, _, res_name = _meta(m)
            desired_keys.add((kind, res_name))
            live = self.client.get(kind, self.namespace, res_name)
            if live is None:
                try:
                    self.client.create(m)
                    counts["creates"] += 1
                except KeyError:
                    # lost a create race (another controller/kubelet actor
                    # landed it between our GET miss and the POST) —
                    # converge onto the racer's object in the same pass
                    # (the reference's CREATE(404)-vs-UPDATE split,
                    # SeldonDeploymentControllerImpl.java:69-111)
                    try:
                        self._replace_converged(m)
                        counts["updates"] += 1
                    except KeyError:
                        # racer's object vanished again before our replace
                        # (create-then-delete churn): take the create path
                        self.client.create(m)
                        counts["creates"] += 1
                continue
            live_hash = (
                live.get("metadata", {}).get("annotations", {})
                .get(HASH_ANNOTATION)
            )
            if live_hash != m["metadata"]["annotations"][HASH_ANNOTATION]:
                try:
                    self._replace_converged(m)
                    counts["updates"] += 1
                except KeyError:
                    # deleted under us mid-pass: recreate
                    self.client.create(m)
                    counts["creates"] += 1
        # prune: owned resources no longer rendered (removed predictors /
        # components) — SeldonDeploymentControllerImpl's removeDeployments
        for kind in ("Deployment", "Service"):
            for live in self.client.list(
                kind, self.namespace, {OWNER_LABEL: name}
            ):
                _, _, res_name = _meta(live)
                if (kind, res_name) not in desired_keys:
                    self.client.delete(kind, self.namespace, res_name)
                    counts["deletes"] += 1
        self._update_status(
            name, rollout=self._reconcile_rollout(cr),
            autoscale=self._autoscale_status.get(name),
        )
        return counts

    def _apply_autoscale(self, spec, name: str,
                         manifests: List[dict]) -> None:
        """Override rendered engine Deployments' ``spec.replicas`` with
        the scale-ahead planner's decision (operator/scaleahead.py).
        No-op without a planner or the ``seldon.io/autoscale``
        annotation; a malformed annotation raises (the caller surfaces
        it as a Failed CR, same contract as a malformed graph)."""
        self._autoscale_status.pop(name, None)
        if self.autoscaler is None:
            return
        from seldon_core_tpu.operator.scaleahead import AutoscalePolicy

        policy = AutoscalePolicy.from_spec(spec)  # raises on malformed
        if policy is None:
            return
        # a live canary gates scale-IN: shrinking the fleet mid-rollout
        # would let a capacity cut mask (or masquerade as) a candidate
        # regression.  Scale-out stays allowed — a rollout under load
        # needs capacity more, not less.
        rollout_active = False
        if self.rollouts is not None:
            block = self.rollouts.status_block(name)
            rollout_active = bool(
                block and block.get("state") in ("pending", "running")
            )
        decisions = []
        for m in manifests:
            if m.get("kind") != "Deployment":
                continue
            if m.get("metadata", {}).get("labels", {}).get(
                    "seldon-type") != "engine":
                continue  # component pods scale with their own story
            # "current" is the LIVE Deployment's count — the previous
            # autoscale decision — not the freshly rendered CR baseline:
            # judging scale-in against the baseline would reset an 8-
            # replica fleet to the CR's 1 in a single tick with neither
            # the hysteresis nor the rollout gate ever seeing a
            # want < current transition
            current = int(m.get("spec", {}).get("replicas", 1))
            live = self.client.get(
                "Deployment", self.namespace,
                m.get("metadata", {}).get("name", ""),
            )
            if live is not None:
                current = int(
                    live.get("spec", {}).get("replicas", current))
            decision = self.autoscaler.desired_replicas(
                name, current, policy, rollout_active=rollout_active,
            )
            m["spec"]["replicas"] = decision["desired_replicas"]
            decisions.append({
                "deployment": m["metadata"].get("name", ""),
                "current_replicas": decision["current_replicas"],
                "desired_replicas": decision["desired_replicas"],
                "reason": decision["reason"],
                # integer-rounded so a steady load reads as an unchanged
                # status (the write-suppression gate compares values)
                "load_now": int(round(decision["load_now"])),
                "load_forecast": int(round(decision["load_forecast"])),
            })
        if decisions:
            self._autoscale_status[name] = {
                "enabled": True,
                "rollout_gated": rollout_active,
                "decisions": decisions,
            }

    def _reconcile_rollout(self, cr: dict) -> Optional[dict]:
        """One rollout-controller tick for an annotated CR: desired-state
        intake (idempotent; the CR's config hash is the quarantine
        identity) then a stage decision.  Returns the status block to
        write back, None when no controller is wired or the CR doesn't
        opt in."""
        if self.rollouts is None:
            return None
        from seldon_core_tpu.operator.rollouts import plan_from_annotations

        try:
            spec = SeldonDeploymentSpec.from_json_dict(cr)
            # hash over the CR spec only — status/annotation churn (our
            # own write-backs included) must not read as "spec changed"
            # and reopen a quarantine
            plan = plan_from_annotations(
                spec, config_hash=_config_hash({"spec": cr.get("spec")})
            )
        except Exception as e:
            return {"state": "invalid",
                    "error": f"{type(e).__name__}: {e}"}
        if plan is None:
            return None
        self.rollouts.apply(plan)
        self.rollouts.tick_deployment(plan.deployment)
        return self.rollouts.status_block(plan.deployment)

    def _replace_converged(self, m: dict, retries: int = 2) -> None:
        """Replace with 409 resolution: our rendering is authoritative for
        owned resources, so a conflict just means the live resourceVersion
        moved — re-issue the (version-less, server-side-apply-like) write.
        Bounded retries: a persistently conflicting object surfaces as an
        error rather than a livelock."""
        for attempt in range(retries + 1):
            try:
                self.client.replace(m)
                return
            except KubeConflict:
                if attempt == retries:
                    raise
                # refresh our view; the next write supersedes the racer's
                kind, _, res_name = _meta(m)
                if self.client.get(kind, self.namespace, res_name) is None:
                    raise KeyError(f"not found: {res_name}")

    def reconcile_deleted(self, name: str) -> int:
        """CR removed: prune everything it owned."""
        if self.rollouts is not None:
            # the quarantine dies with the CR — a re-created deployment
            # is a new spec by definition
            self.rollouts.forget(name)
        deleted = 0
        for kind in ("Deployment", "Service"):
            for live in self.client.list(
                kind, self.namespace, {OWNER_LABEL: name}
            ):
                _, _, res_name = _meta(live)
                self.client.delete(kind, self.namespace, res_name)
                deleted += 1
        return deleted

    # -- status ------------------------------------------------------------

    def _update_status(self, name: str,
                       rollout: Optional[dict] = None,
                       autoscale: Optional[dict] = None) -> None:
        """CR status from observed Deployment readiness — the write-back
        half (SeldonDeploymentStatusUpdateImpl.java:49-104) — plus the
        rollout controller's state for canary-annotated CRs."""
        deployments = self.client.list(
            "Deployment", self.namespace, {OWNER_LABEL: name}
        )
        predictor_status = []
        available = bool(deployments)
        for d in deployments:
            want = d.get("spec", {}).get("replicas", 1)
            ready = d.get("status", {}).get("readyReplicas", 0)
            predictor_status.append({
                "name": d["metadata"]["name"],
                "replicas": want,
                "replicasAvailable": ready,
            })
            if ready < want:
                available = False
        status = {
            "state": "Available" if available else "Creating",
            "predictorStatus": sorted(
                predictor_status, key=lambda p: p["name"]
            ),
        }
        if rollout is not None:
            status["rollout"] = rollout
        if autoscale is not None:
            # decision timestamps are stripped for write-suppression: a
            # steady decision must read as an unchanged status
            status["autoscale"] = autoscale
        self._patch_cr_status(name, status)

    def _patch_cr_status(self, name: str, status: dict) -> None:
        # write-suppression: a status patch bumps the CR's resourceVersion,
        # so patching an unchanged status every tick turns the steady state
        # into a write loop (and retriggers level-based watchers cluster-
        # wide).  Compare against the live status first.
        live = self.client.get("SeldonDeployment", self.namespace, name)
        if live is None:
            return  # CR deleted mid-reconcile: nothing to write back to
        live_status = live.get("status", {})
        if all(live_status.get(k) == v for k, v in status.items()):
            return
        try:
            self.client.patch_status(
                "SeldonDeployment", self.namespace, name, status
            )
        except KeyError:
            pass  # CR deleted between the read and the patch
        except KubeConflict:
            # another writer bumped the CR between read and patch; one
            # retry — status is derived state, next tick rewrites it anyway
            try:
                self.client.patch_status(
                    "SeldonDeployment", self.namespace, name, status
                )
            except (KeyError, KubeConflict):
                pass

    # -- control loop --------------------------------------------------------

    def run_once(self) -> Dict[str, Dict[str, int]]:
        """LIST all CRs, reconcile each, prune orphans of deleted CRs —
        one tick of the reference's watch-driven controller, poll-driven
        the way materializer.watch_dir already is."""
        crs = self.client.list("SeldonDeployment", self.namespace)
        seen = set()
        results = {}
        for cr in crs:
            name = cr.get("metadata", {}).get("name", "")
            seen.add(name)
            try:
                results[name] = self.reconcile(cr)
            except Exception as e:  # API flake mid-reconcile: isolate the CR
                results[name] = {
                    "creates": 0, "updates": 0, "deletes": 0, "failed": 1,
                    "error": f"{type(e).__name__}: {e}",
                }
        # resources whose owning CR is gone
        owners = set()
        for kind in ("Deployment", "Service"):
            for live in self.client.list(kind, self.namespace):
                owner = (
                    live.get("metadata", {}).get("labels", {})
                    .get(OWNER_LABEL)
                )
                if owner:
                    owners.add(owner)
        for orphan in owners - seen:
            results[orphan] = {
                "creates": 0, "updates": 0,
                "deletes": self.reconcile_deleted(orphan),
            }
        return results


def main(argv=None) -> None:
    """Operator process: CRD bootstrap then the poll-reconcile loop.

        python -m seldon_core_tpu.operator.reconciler \
            [--namespace default] [--interval 10] [--once]
    """
    import argparse
    import time

    parser = argparse.ArgumentParser(description="seldon_core_tpu operator")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--interval", type=float, default=10.0)
    parser.add_argument("--once", action="store_true")
    parser.add_argument("--kubectl", default="kubectl",
                        help="kubectl binary for the cluster client")
    args = parser.parse_args(argv)
    import os

    engine_env = {}
    raw = os.environ.get("SELDON_ENGINE_ENV", "")
    if raw.strip():
        engine_env = {str(k): str(v) for k, v in json.loads(raw).items()}
    rec = Reconciler(
        KubectlClient(args.kubectl), namespace=args.namespace,
        engine_image=os.environ.get("SELDON_ENGINE_IMAGE", ""),
        engine_env=engine_env,
    )
    if rec.ensure_crd():
        print(f"registered CRD {CRD_NAME}", flush=True)
    while True:
        results = rec.run_once()
        work = {k: v for k, v in results.items()
                if any(v.get(x) for x in ("creates", "updates", "deletes",
                                          "failed"))}
        if work:
            print(json.dumps(work), flush=True)
        if args.once:
            return
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
