"""Deployment operator: materializes SeldonDeployment specs into running
engines/units, watches a spec directory, tracks status; reconciles CRs
against a (pluggable) Kubernetes API server with CRD bootstrap and status
write-back; renders k8s manifests (helm-equivalent) and packages model
images (s2i-equivalent)."""

from seldon_core_tpu.operator.materializer import Materializer  # noqa: F401
from seldon_core_tpu.operator.manifests import (  # noqa: F401
    generate_manifests,
    to_yaml_stream,
)
from seldon_core_tpu.operator.packaging import ImageSpec, package_model  # noqa: F401
from seldon_core_tpu.operator.reconciler import (  # noqa: F401
    FakeKubeApi,
    KubeClient,
    KubectlClient,
    Reconciler,
)
