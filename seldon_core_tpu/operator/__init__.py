"""Deployment operator: materializes SeldonDeployment specs into running
engines/units, watches a spec directory, tracks status."""

from seldon_core_tpu.operator.materializer import Materializer  # noqa: F401
