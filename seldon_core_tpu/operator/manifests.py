"""Kubernetes manifest generation — the operator's resource-creation pass,
emitted as data instead of API calls.

Mirrors the reference operator's ``createResources`` (cluster-manager
SeldonDeploymentOperatorImpl.java:520-666) and the helm/ksonnet packaging
(helm-charts/, seldon-core/ core.libsonnet:35-141): per predictor an engine
Deployment (graph shipped as ``ENGINE_PREDICTOR`` base64 JSON env —
SeldonDeploymentOperatorImpl.java:105 — prometheus scrape annotations,
``/ready`` readiness probe, pre-stop ``/pause`` drain, rolling update
maxUnavailable 10%), one Deployment + ClusterIP Service per remote component
binding (TCP readiness probe on the assigned port, ``seldon-app-<name>``
selector labels), and one per-deployment Service fronting the engine with
Ambassador-style route annotations.

TPU-native additions: engine pods for predictors with ``device: tpu``
inprocess bindings request ``google.com/tpu`` resources and carry a
``tpu-topology`` node-selector derived from the binding's ``mesh_axes``
(the graph compiles INTO the engine, so the engine pod — not the model
pods — owns the chips; remote bindings keep the reference's CPU layout).

Everything returns plain dicts; ``to_yaml_stream`` renders the multi-doc
YAML that ``kubectl apply -f -`` consumes.
"""

from __future__ import annotations

import base64
import json
from typing import Dict, List

from seldon_core_tpu.graph.defaulting import default_and_validate
from seldon_core_tpu.graph.spec import PredictorSpec, SeldonDeploymentSpec

__all__ = ["generate_manifests", "engine_deployment", "to_yaml_stream",
           "SHARD_ANNOTATION"]

ENGINE_IMAGE = "seldon-core-tpu/engine:latest"
ENGINE_REST_PORT = 8000   # cluster-manager application.properties:5
ENGINE_GRPC_PORT = 5001   # cluster-manager application.properties:6
ENGINE_METRICS_PATH = "/prometheus"

#: ``seldon.io/shard-graph: "true"`` materializes one engine
#: Deployment+Service per shardable MODEL leaf (graph/sharding.py) — the
#: reference's pod-per-node topology (PAPER.md §1) won back at scale-out
SHARD_ANNOTATION = "seldon.io/shard-graph"


def _labels(spec: SeldonDeploymentSpec, predictor: PredictorSpec,
            component: str = "") -> Dict[str, str]:
    lab = {
        "app": "seldon",
        "seldon-deployment-id": spec.name,
        "seldon-predictor": predictor.name,
    }
    if component:
        # the reference labels model pods seldon-app-<container> so the
        # per-container Service can select them
        # (SeldonDeploymentOperatorImpl.java:254-258)
        lab[f"seldon-app-{component}"] = "true"
    else:
        lab["seldon-type"] = "engine"
    return lab


def _tpu_request(predictor: PredictorSpec) -> Dict[str, str]:
    """Chips the engine pod needs: max mesh size over inprocess tpu bindings."""
    chips = 0
    for b in predictor.components:
        if b.runtime == "inprocess" and b.device == "tpu":
            n = 1
            for v in (b.mesh_axes or {}).values():
                n *= int(v)
            chips = max(chips, n)
    return {"google.com/tpu": str(chips)} if chips else {}


def _topology(chips: int) -> str:
    """GKE tpu-topology label value for a chip count (v5e slice shapes)."""
    return {1: "1x1", 2: "1x2", 4: "2x2", 8: "2x4", 16: "4x4",
            32: "4x8"}.get(chips, f"1x{chips}")


def engine_deployment(spec: SeldonDeploymentSpec,
                      predictor: PredictorSpec,
                      engine_image: str = "",
                      engine_env: "Dict[str, str] | None" = None) -> dict:
    """``engine_image`` / ``engine_env`` are the chart-level knobs the
    reference wires through its operator properties
    (ENGINE_CONTAINER_IMAGE_AND_VERSION, cluster-manager
    application.properties) — rendered values flow operator -> here."""
    pred_b64 = base64.b64encode(
        json.dumps(predictor.to_json_dict(), separators=(",", ":")).encode()
    ).decode()
    # validated here so a malformed annotation fails the RECONCILE (CR goes
    # Failed with a clear message) instead of crash-looping engine pods
    prewarm = spec.annotations.get("seldon.io/prewarm-widths")
    if prewarm is not None:
        prewarm = str(prewarm)
        parts = [w.strip() for w in prewarm.split(",") if w.strip()]
        if not parts or any(not w.isdigit() or int(w) <= 0 for w in parts):
            raise ValueError(
                f"annotation seldon.io/prewarm-widths must be "
                f"comma-separated positive integers, got {prewarm!r}"
            )
    labels = _labels(spec, predictor)
    resources: dict = {"requests": {"cpu": "0.1"}}
    tpu = _tpu_request(predictor)
    if tpu:
        resources["limits"] = dict(tpu)
        resources["requests"].update(tpu)
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": f"{spec.name}-{predictor.name}-engine",
            "labels": labels,
            "annotations": dict(spec.annotations),
        },
        "spec": {
            "replicas": predictor.replicas,
            "selector": {"matchLabels": labels},
            # reference rolling policy (SeldonDeploymentOperatorImpl.java:564)
            "strategy": {
                "type": "RollingUpdate",
                "rollingUpdate": {"maxUnavailable": "10%"},
            },
            "template": {
                "metadata": {
                    "labels": labels,
                    "annotations": {
                        # scrape annotations the reference injects
                        # (SeldonDeploymentOperatorImpl.java:542-544)
                        "prometheus.io/scrape": "true",
                        "prometheus.io/path": ENGINE_METRICS_PATH,
                        "prometheus.io/port": str(ENGINE_REST_PORT),
                    },
                },
                "spec": {
                    "containers": [
                        {
                            "name": "seldon-engine",
                            "image": engine_image or ENGINE_IMAGE,
                            "env": [
                                {"name": "ENGINE_PREDICTOR", "value": pred_b64},
                                {"name": "SELDON_DEPLOYMENT_ID",
                                 "value": spec.name},
                                {"name": "ENGINE_SERVER_PORT",
                                 "value": str(ENGINE_REST_PORT)},
                                {"name": "ENGINE_SERVER_GRPC_PORT",
                                 "value": str(ENGINE_GRPC_PORT)},
                                *(
                                    {"name": k, "value": str(v)}
                                    for k, v in sorted(
                                        (engine_env or {}).items()
                                    )
                                    # the per-CR annotation must beat a
                                    # chart-wide default; drop the dup
                                    if not (prewarm is not None
                                            and k == "ENGINE_PREWARM_WIDTHS")
                                ),
                                *(
                                    [{"name": "ENGINE_PREWARM_WIDTHS",
                                      "value": prewarm}]
                                    if prewarm is not None else []
                                ),
                            ],
                            "ports": [
                                {"containerPort": ENGINE_REST_PORT,
                                 "name": "rest"},
                                {"containerPort": ENGINE_GRPC_PORT,
                                 "name": "grpc"},
                            ],
                            "readinessProbe": {
                                "httpGet": {"path": "/ready",
                                            "port": ENGINE_REST_PORT},
                                "initialDelaySeconds": 5,
                                "periodSeconds": 5,
                            },
                            "lifecycle": {
                                # pre-stop drain: flip readiness then sleep
                                # (SeldonDeploymentOperatorImpl.java:130-134)
                                "preStop": {
                                    "exec": {
                                        "command": [
                                            "/bin/sh", "-c",
                                            f"curl -s localhost:"
                                            f"{ENGINE_REST_PORT}/pause "
                                            f"&& sleep 5",
                                        ]
                                    }
                                }
                            },
                            "resources": resources,
                        }
                    ],
                    **(
                        {"nodeSelector": {"cloud.google.com/gke-tpu-topology":
                                          _topology(int(tpu["google.com/tpu"]))}}
                        if tpu
                        else {}
                    ),
                },
            },
        },
    }


def component_deployment(spec: SeldonDeploymentSpec, predictor: PredictorSpec,
                         binding) -> dict:
    labels = _labels(spec, predictor, binding.name)
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": f"{spec.name}-{predictor.name}-{binding.name}",
            "labels": labels,
        },
        "spec": {
            "replicas": predictor.replicas,
            "selector": {"matchLabels": labels},
            "strategy": {
                "type": "RollingUpdate",
                "rollingUpdate": {"maxUnavailable": "10%"},
            },
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "containers": [
                        {
                            "name": binding.name,
                            "image": binding.image
                            or "seldon-core-tpu/microservice:latest",
                            "env": [
                                {"name": k, "value": str(v)}
                                for k, v in sorted(binding.env.items())
                            ],
                            "ports": [
                                {"containerPort": binding.port,
                                 "name": "http"
                                 if binding.runtime == "rest" else "grpc"}
                            ],
                            # TCP probe on the assigned unit port
                            # (SeldonDeploymentOperatorImpl.java:210-250)
                            "readinessProbe": {
                                "tcpSocket": {"port": binding.port},
                                "initialDelaySeconds": 10,
                                "periodSeconds": 5,
                            },
                            "livenessProbe": {
                                "tcpSocket": {"port": binding.port},
                                "initialDelaySeconds": 60,
                                "periodSeconds": 5,
                            },
                            "lifecycle": {
                                "preStop": {
                                    "exec": {"command": ["/bin/sh", "-c",
                                                         "sleep 10"]}
                                }
                            },
                        }
                    ]
                },
            },
        },
    }


def component_service(spec: SeldonDeploymentSpec, predictor: PredictorSpec,
                      binding) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": f"{spec.name}-{predictor.name}-{binding.name}",
            "labels": {"seldon-deployment-id": spec.name},
        },
        "spec": {
            "type": "ClusterIP",
            # scope by deployment AND predictor: a bare seldon-app-<name>
            # selector would grab same-named components of other deployments
            "selector": {
                "seldon-deployment-id": spec.name,
                "seldon-predictor": predictor.name,
                f"seldon-app-{binding.name}": "true",
            },
            "ports": [
                {
                    "port": binding.port,
                    "targetPort": binding.port,
                    "protocol": "TCP",
                    "name": "http" if binding.runtime == "rest" else "grpc",
                }
            ],
        },
    }


def deployment_service(spec: SeldonDeploymentSpec) -> dict:
    """Per-deployment Service fronting the engines, with Ambassador-style
    route annotations (SeldonDeploymentOperatorImpl.java:465-484)."""
    import yaml  # deferred: pyyaml only needed when rendering manifests

    ambassador = {
        "apiVersion": "ambassador/v0",
        "kind": "Mapping",
        "name": f"seldon_{spec.name}_mapping",
        "prefix": f"/seldon/{spec.name}/",
        "service": f"{spec.name}:{ENGINE_REST_PORT}",
    }
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": spec.name,
            "labels": {"seldon-deployment-id": spec.name},
            "annotations": {
                "getambassador.io/config": yaml.safe_dump(ambassador,
                                                          sort_keys=False)
            },
        },
        "spec": {
            "type": "ClusterIP",
            "selector": {"seldon-deployment-id": spec.name,
                         "seldon-type": "engine"},
            "ports": [
                {"port": ENGINE_REST_PORT, "targetPort": ENGINE_REST_PORT,
                 "name": "rest"},
                {"port": ENGINE_GRPC_PORT, "targetPort": ENGINE_GRPC_PORT,
                 "name": "grpc"},
            ],
        },
    }


def node_engine_service(node_spec: SeldonDeploymentSpec,
                        predictor: PredictorSpec) -> dict:
    """ClusterIP Service fronting one node engine (graph sharding).  No
    Ambassador route: node engines are internal mesh hops, only the root
    engine's deployment Service is externally routable."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": node_spec.name,
            "labels": {"seldon-deployment-id": node_spec.name},
        },
        "spec": {
            "type": "ClusterIP",
            "selector": {"seldon-deployment-id": node_spec.name,
                         "seldon-predictor": predictor.name,
                         "seldon-type": "engine"},
            "ports": [
                {"port": ENGINE_REST_PORT, "targetPort": ENGINE_REST_PORT,
                 "name": "rest"},
                {"port": ENGINE_GRPC_PORT, "targetPort": ENGINE_GRPC_PORT,
                 "name": "grpc"},
            ],
        },
    }


def _shard_enabled(spec: SeldonDeploymentSpec) -> bool:
    return str(
        spec.annotations.get(SHARD_ANNOTATION, "")
    ).strip().lower() in ("1", "true", "yes")


def generate_manifests(spec: SeldonDeploymentSpec,
                       run_defaulting: bool = True,
                       engine_image: str = "",
                       engine_env: "Dict[str, str] | None" = None) -> List[dict]:
    """All resources for a deployment, reference createResources order:
    engine Deployments, component Deployments/Services, deployment Service.

    With ``seldon.io/shard-graph: "true"`` and >= 2 shardable MODEL
    leaves, each leaf becomes its OWN engine Deployment+Service (the
    reference's pod-per-node topology) and the root engine's graph is
    rewritten to dispatch to them over the resilient remote client —
    graph/sharding.py.  A single-leaf graph is served collapsed even when
    annotated: sharding it would only add a network hop."""
    if run_defaulting:
        default_and_validate(spec)
    out: List[dict] = []
    for predictor in spec.predictors:
        for binding in predictor.components:
            if binding.name == "engine" and binding.runtime in ("rest", "grpc"):
                # its Deployment name would collide with (and on kubectl
                # apply, overwrite) the predictor's engine Deployment
                raise ValueError(
                    f"component name 'engine' is reserved "
                    f"(predictor {predictor.name!r})"
                )
        sharded_names: set = set()
        engine_pred = predictor
        if _shard_enabled(spec):
            from seldon_core_tpu.graph.sharding import (
                node_subspec,
                shard_predictor,
                shardable_nodes,
            )

            nodes = shardable_nodes(predictor)
            if len(nodes) >= 2:
                endpoints = {}
                for unit in nodes:
                    nspec = node_subspec(spec, unit.name, predictor.name)
                    node_pred = nspec.predictors[0]
                    out.append(
                        engine_deployment(nspec, node_pred,
                                          engine_image=engine_image,
                                          engine_env=engine_env)
                    )
                    out.append(node_engine_service(nspec, node_pred))
                    # the node Service's DNS name is the nspec name
                    endpoints[unit.name] = (nspec.name, ENGINE_REST_PORT)
                engine_pred = shard_predictor(
                    spec, endpoints, predictor.name
                ).predictor(predictor.name)
                sharded_names = set(endpoints)
        out.append(
            engine_deployment(spec, engine_pred, engine_image=engine_image,
                              engine_env=engine_env)
        )
        for binding in engine_pred.components:
            if (
                binding.runtime in ("rest", "grpc")
                and binding.name not in sharded_names
            ):
                # genuinely-remote components keep their microservice
                # Deployment; sharded leaves are node ENGINES above, not
                # generic model pods
                out.append(component_deployment(spec, predictor, binding))
                out.append(component_service(spec, predictor, binding))
    out.append(deployment_service(spec))
    return out


def to_yaml_stream(manifests: List[dict]) -> str:
    """Multi-document YAML for ``kubectl apply -f -``."""
    import yaml  # deferred: pyyaml only needed when rendering manifests

    class NoAliasDumper(yaml.SafeDumper):
        # kubectl chokes on nothing, but humans choke on &id001 anchors
        # that appear when the same labels dict is referenced twice
        def ignore_aliases(self, data):
            return True

    return "---\n".join(
        yaml.dump(m, Dumper=NoAliasDumper, sort_keys=False)
        for m in manifests
    )


def main(argv=None) -> None:
    """CLI: render a deployment spec to k8s YAML (the helm-template
    equivalent): ``python -m seldon_core_tpu.operator.manifests spec.json``.
    """
    import argparse
    import sys

    parser = argparse.ArgumentParser(description="render deployment manifests")
    parser.add_argument("spec", help="SeldonDeployment JSON file")
    args = parser.parse_args(argv)
    with open(args.spec) as f:
        spec = SeldonDeploymentSpec.from_json(f.read())
    sys.stdout.write(to_yaml_stream(generate_manifests(spec)))


if __name__ == "__main__":
    main()
