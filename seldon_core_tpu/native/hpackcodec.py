"""HPACK (RFC 7541) header compression codec — pure Python, no deps.

Backs the wire-level gRPC data plane (runtime/grpcfast.py): the stock
Python gRPC runtime tops out around 2.6k unary calls/s/core on this class
of host, so the framework terminates HTTP/2 + HPACK itself the same way it
terminates HTTP/1.1 (runtime/httpfast.py).

Decode implements the full spec surface a real gRPC peer exercises:
indexed fields, all literal forms, dynamic-table inserts/evictions/size
updates, and Huffman-coded strings (nibble-FSM decoder built at import
from the spec table).  Encode stays deliberately simple — exact static
matches as indexed fields, everything else literal-without-indexing,
never Huffman — which any conformant peer must accept and which keeps the
encoder stateless (no dynamic entries referenced, so peers never need our
table state).

HUFFMAN_CODES / HUFFMAN_LENGTHS / STATIC_TABLE are the constants from RFC
7541 Appendix B and Appendix A verbatim (spec data, not creative code).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

__all__ = ["HpackDecoder", "encode_headers", "HpackError"]


class HpackError(Exception):
    """Malformed header block (connection-fatal per RFC 7541)."""


HUFFMAN_CODES = [8184, 8388568, 268435426, 268435427, 268435428, 268435429, 268435430, 268435431, 268435432, 16777194, 1073741820, 268435433, 268435434, 1073741821, 268435435, 268435436, 268435437, 268435438, 268435439, 268435440, 268435441, 268435442, 1073741822, 268435443, 268435444, 268435445, 268435446, 268435447, 268435448, 268435449, 268435450, 268435451, 20, 1016, 1017, 4090, 8185, 21, 248, 2042, 1018, 1019, 249, 2043, 250, 22, 23, 24, 0, 1, 2, 25, 26, 27, 28, 29, 30, 31, 92, 251, 32764, 32, 4091, 1020, 8186, 33, 93, 94, 95, 96, 97, 98, 99, 100, 101, 102, 103, 104, 105, 106, 107, 108, 109, 110, 111, 112, 113, 114, 252, 115, 253, 8187, 524272, 8188, 16380, 34, 32765, 3, 35, 4, 36, 5, 37, 38, 39, 6, 116, 117, 40, 41, 42, 7, 43, 118, 44, 8, 9, 45, 119, 120, 121, 122, 123, 32766, 2044, 16381, 8189, 268435452, 1048550, 4194258, 1048551, 1048552, 4194259, 4194260, 4194261, 8388569, 4194262, 8388570, 8388571, 8388572, 8388573, 8388574, 16777195, 8388575, 16777196, 16777197, 4194263, 8388576, 16777198, 8388577, 8388578, 8388579, 8388580, 2097116, 4194264, 8388581, 4194265, 8388582, 8388583, 16777199, 4194266, 2097117, 1048553, 4194267, 4194268, 8388584, 8388585, 2097118, 8388586, 4194269, 4194270, 16777200, 2097119, 4194271, 8388587, 8388588, 2097120, 2097121, 4194272, 2097122, 8388589, 4194273, 8388590, 8388591, 1048554, 4194274, 4194275, 4194276, 8388592, 4194277, 4194278, 8388593, 67108832, 67108833, 1048555, 524273, 4194279, 8388594, 4194280, 33554412, 67108834, 67108835, 67108836, 134217694, 134217695, 67108837, 16777201, 33554413, 524274, 2097123, 67108838, 134217696, 134217697, 67108839, 134217698, 16777202, 2097124, 2097125, 67108840, 67108841, 268435453, 134217699, 134217700, 134217701, 1048556, 16777203, 1048557, 2097126, 4194281, 2097127, 2097128, 8388595, 4194282, 4194283, 33554414, 33554415, 16777204, 16777205, 67108842, 8388596, 67108843, 134217702, 67108844, 67108845, 134217703, 134217704, 134217705, 134217706, 134217707, 268435454, 134217708, 134217709, 134217710, 134217711, 134217712, 67108846, 1073741823]
HUFFMAN_LENGTHS = [13, 23, 28, 28, 28, 28, 28, 28, 28, 24, 30, 28, 28, 30, 28, 28, 28, 28, 28, 28, 28, 28, 30, 28, 28, 28, 28, 28, 28, 28, 28, 28, 6, 10, 10, 12, 13, 6, 8, 11, 10, 10, 8, 11, 8, 6, 6, 6, 5, 5, 5, 6, 6, 6, 6, 6, 6, 6, 7, 8, 15, 6, 12, 10, 13, 6, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 8, 7, 8, 13, 19, 13, 14, 6, 15, 5, 6, 5, 6, 5, 6, 6, 6, 5, 7, 7, 6, 6, 6, 5, 6, 7, 6, 5, 5, 6, 7, 7, 7, 7, 7, 15, 11, 14, 13, 28, 20, 22, 20, 20, 22, 22, 22, 23, 22, 23, 23, 23, 23, 23, 24, 23, 24, 24, 22, 23, 24, 23, 23, 23, 23, 21, 22, 23, 22, 23, 23, 24, 22, 21, 20, 22, 22, 23, 23, 21, 23, 22, 22, 24, 21, 22, 23, 23, 21, 21, 22, 21, 23, 22, 23, 23, 20, 22, 22, 22, 23, 22, 22, 23, 26, 26, 20, 19, 22, 23, 22, 25, 26, 26, 26, 27, 27, 26, 24, 25, 19, 21, 26, 27, 27, 26, 27, 24, 21, 21, 26, 26, 28, 27, 27, 27, 20, 24, 20, 21, 22, 21, 21, 23, 22, 22, 25, 25, 24, 24, 26, 23, 26, 27, 26, 26, 27, 27, 27, 27, 27, 28, 27, 27, 27, 27, 27, 26, 30]
STATIC_TABLE = [(b':authority', b''), (b':method', b'GET'), (b':method', b'POST'), (b':path', b'/'), (b':path', b'/index.html'), (b':scheme', b'http'), (b':scheme', b'https'), (b':status', b'200'), (b':status', b'204'), (b':status', b'206'), (b':status', b'304'), (b':status', b'400'), (b':status', b'404'), (b':status', b'500'), (b'accept-charset', b''), (b'accept-encoding', b'gzip, deflate'), (b'accept-language', b''), (b'accept-ranges', b''), (b'accept', b''), (b'access-control-allow-origin', b''), (b'age', b''), (b'allow', b''), (b'authorization', b''), (b'cache-control', b''), (b'content-disposition', b''), (b'content-encoding', b''), (b'content-language', b''), (b'content-length', b''), (b'content-location', b''), (b'content-range', b''), (b'content-type', b''), (b'cookie', b''), (b'date', b''), (b'etag', b''), (b'expect', b''), (b'expires', b''), (b'from', b''), (b'host', b''), (b'if-match', b''), (b'if-modified-since', b''), (b'if-none-match', b''), (b'if-range', b''), (b'if-unmodified-since', b''), (b'last-modified', b''), (b'link', b''), (b'location', b''), (b'max-forwards', b''), (b'proxy-authenticate', b''), (b'proxy-authorization', b''), (b'range', b''), (b'referer', b''), (b'refresh', b''), (b'retry-after', b''), (b'server', b''), (b'set-cookie', b''), (b'strict-transport-security', b''), (b'transfer-encoding', b''), (b'user-agent', b''), (b'vary', b''), (b'via', b''), (b'www-authenticate', b'')]


_STATIC_MAP = {pair: i + 1 for i, pair in enumerate(STATIC_TABLE)}
_EOS = 256


def _build_fsm():
    """Nibble-stepped Huffman decode FSM.

    Trie nodes: [zero_child, one_child, symbol].  FSM state = trie node id;
    transitions[state * 16 + nibble] = (next_state, emitted, ok) where a
    symbol hit mid-walk emits and resets to the root.  A state is a valid
    END state iff its path from the root is all 1-bits (EOS prefix = legal
    padding).
    """
    nodes = [[None, None, None]]  # root

    def insert(code, length, sym):
        n = 0
        for i in range(length - 1, -1, -1):
            bit = (code >> i) & 1
            if nodes[n][bit] is None:
                nodes.append([None, None, None])
                nodes[n][bit] = len(nodes) - 1
            n = nodes[n][bit]
        nodes[n][2] = sym

    for sym, (code, length) in enumerate(zip(HUFFMAN_CODES, HUFFMAN_LENGTHS)):
        insert(code, length, sym)

    # all-ones path marking (valid padding end states)
    accept = [False] * len(nodes)
    n = 0
    accept[0] = True
    while True:
        n = nodes[n][1]
        if n is None or nodes[n][2] is not None:
            break
        accept[n] = True

    transitions = []
    for state in range(len(nodes)):
        for nibble in range(16):
            n, out, ok = state, [], True
            for i in (3, 2, 1, 0):
                bit = (nibble >> i) & 1
                nxt = nodes[n][bit]
                if nxt is None:
                    ok = False
                    break
                sym = nodes[nxt][2]
                if sym is not None:
                    if sym == _EOS:
                        ok = False
                        break
                    out.append(sym)
                    n = 0
                else:
                    n = nxt
            transitions.append((n, bytes(out), ok))
    return transitions, accept


_FSM, _FSM_ACCEPT = _build_fsm()


def huffman_decode(data: bytes) -> bytes:
    state = 0
    out = []
    fsm = _FSM
    for b in data:
        nxt, emitted, ok = fsm[state * 16 + (b >> 4)]
        if not ok:
            raise HpackError("bad huffman sequence")
        if emitted:
            out.append(emitted)
        nxt, emitted, ok = fsm[nxt * 16 + (b & 0x0F)]
        if not ok:
            raise HpackError("bad huffman sequence")
        if emitted:
            out.append(emitted)
        state = nxt
    if not _FSM_ACCEPT[state]:
        raise HpackError("bad huffman padding")
    return b"".join(out)


def _decode_int(data: bytes, pos: int, prefix_bits: int) -> Tuple[int, int]:
    mask = (1 << prefix_bits) - 1
    value = data[pos] & mask
    pos += 1
    if value < mask:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated integer")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if shift > 35:
            raise HpackError("integer overflow")
        if not b & 0x80:
            return value, pos


def _decode_string(data: bytes, pos: int) -> Tuple[bytes, int]:
    if pos >= len(data):
        raise HpackError("truncated string")
    huff = bool(data[pos] & 0x80)
    length, pos = _decode_int(data, pos, 7)
    if pos + length > len(data):
        raise HpackError("truncated string")
    raw = data[pos: pos + length]
    return (huffman_decode(raw) if huff else raw), pos + length


class HpackDecoder:
    """Stateful decoder: one per HTTP/2 connection (owns the peer-populated
    dynamic table)."""

    def __init__(self, max_table_size: int = 4096):
        self.dynamic: List[Tuple[bytes, bytes]] = []
        self.size = 0
        self.max_size = max_table_size
        self.protocol_max = max_table_size

    def _entry(self, index: int) -> Tuple[bytes, bytes]:
        if index <= 0:
            raise HpackError("index 0")
        if index <= len(STATIC_TABLE):
            return STATIC_TABLE[index - 1]
        d = index - len(STATIC_TABLE) - 1
        if d >= len(self.dynamic):
            raise HpackError(f"index {index} out of table")
        return self.dynamic[d]

    def _insert(self, name: bytes, value: bytes) -> None:
        self.dynamic.insert(0, (name, value))
        self.size += len(name) + len(value) + 32
        while self.size > self.max_size and self.dynamic:
            n, v = self.dynamic.pop()
            self.size -= len(n) + len(v) + 32

    def decode(self, block: bytes) -> List[Tuple[bytes, bytes]]:
        headers: List[Tuple[bytes, bytes]] = []
        pos = 0
        while pos < len(block):
            b = block[pos]
            if b & 0x80:  # indexed
                index, pos = _decode_int(block, pos, 7)
                headers.append(self._entry(index))
            elif b & 0x40:  # literal with incremental indexing
                index, pos = _decode_int(block, pos, 6)
                name = self._entry(index)[0] if index else None
                if name is None:
                    name, pos = _decode_string(block, pos)
                value, pos = _decode_string(block, pos)
                self._insert(name, value)
                headers.append((name, value))
            elif b & 0x20:  # dynamic table size update
                new_size, pos = _decode_int(block, pos, 5)
                if new_size > self.protocol_max:
                    raise HpackError("table size above protocol maximum")
                self.max_size = new_size
                while self.size > self.max_size and self.dynamic:
                    n, v = self.dynamic.pop()
                    self.size -= len(n) + len(v) + 32
            else:  # literal without indexing (0x00) / never indexed (0x10)
                index, pos = _decode_int(block, pos, 4)
                name = self._entry(index)[0] if index else None
                if name is None:
                    name, pos = _decode_string(block, pos)
                value, pos = _decode_string(block, pos)
                headers.append((name, value))
        return headers


def _encode_int(value: int, prefix_bits: int, pattern: int) -> bytes:
    mask = (1 << prefix_bits) - 1
    if value < mask:
        return bytes([pattern | value])
    out = bytearray([pattern | mask])
    value -= mask
    while value >= 0x80:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def encode_headers(headers: List[Tuple[bytes, bytes]]) -> bytes:
    """Stateless encode: exact static matches indexed, the rest literal
    without indexing, never Huffman."""
    out = bytearray()
    for name, value in headers:
        idx = _STATIC_MAP.get((name, value))
        if idx is not None:
            out += _encode_int(idx, 7, 0x80)
            continue
        out.append(0x00)  # literal w/o indexing, new name
        out += _encode_int(len(name), 7, 0x00)
        out += name
        out += _encode_int(len(value), 7, 0x00)
        out += value
    return bytes(out)
