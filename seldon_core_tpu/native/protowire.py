"""Wire-level protobuf codec for the gRPC hot path.

``np.asarray(upb_repeated_double)`` walks 784 Python float objects
(~58 us/request at MNIST shapes); but on the wire those values are a
single packed-doubles LEN field, so scanning the few enclosing tags by
hand and ``np.frombuffer``-ing the payload is ~10x cheaper and zero-copy.
This is the proto sibling of the native JSON codec
(native/fastcodec): a fast lane for the overwhelmingly common message
shape, with ``None`` returned for anything unusual so callers fall back
to real protobuf parsing — wire semantics never diverge, speed does.

Handled request shape: ``SeldonMessage{meta{puid?}, data{names*,
tensor{shape packed, values packed}}}``.  Any other field (binData,
strData, status, meta tags/routing/requestPath, ndarray) declines.

Layout constants come from proto/prediction.proto field numbers:
  SeldonMessage: status=1 meta=2 data=3 binData=4 strData=5
  Meta:          puid=1 tags=2 routing=3 requestPath=4
  DefaultData:   names=1 tensor=2 ndarray=3
  Tensor:        shape=1 (packed varint) values=2 (packed double)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["parse_tensor_request", "build_tensor_response"]


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _skip_field(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == 0:  # varint
        _, pos = _read_varint(buf, pos)
    elif wire_type == 1:  # fixed64
        pos += 8
    elif wire_type == 2:  # LEN
        n, pos = _read_varint(buf, pos)
        pos += n
    elif wire_type == 5:  # fixed32
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wire_type}")
    if pos > len(buf):
        # truncated message: real protobuf raises DecodeError here, so the
        # fast lane must decline rather than accept what upb would reject
        raise ValueError("field overruns buffer")
    return pos


def _read_len(buf: bytes, pos: int) -> Tuple[int, int]:
    """LEN prefix with overrun check (python slicing would silently
    truncate where real protobuf raises DecodeError)."""
    n, pos = _read_varint(buf, pos)
    if pos + n > len(buf):
        raise ValueError("length-delimited field overruns buffer")
    return n, pos


def _scan_meta(buf: bytes) -> Optional[str]:
    """Return puid if meta contains ONLY a puid (or nothing); None = decline."""
    pos = 0
    puid = ""
    end = len(buf)
    while pos < end:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if field == 1 and wt == 2:  # puid
            n, pos = _read_len(buf, pos)
            puid = buf[pos : pos + n].decode("utf-8")
            pos += n
        else:
            return None  # tags/routing/requestPath present -> object path
    return puid


def _scan_tensor(buf: bytes):
    """-> (shape tuple, values ndarray) or None."""
    pos = 0
    end = len(buf)
    shape: Tuple[int, ...] = ()
    values = None
    while pos < end:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if field == 1:  # shape: packed varints (or repeated varint)
            if wt == 2:
                n, pos = _read_len(buf, pos)
                sub_end = pos + n
                dims = []
                while pos < sub_end:
                    d, pos = _read_varint(buf, pos)
                    dims.append(d)
                shape = shape + tuple(dims)
            elif wt == 0:
                d, pos = _read_varint(buf, pos)
                shape = shape + (d,)
            else:
                return None
        elif field == 2:  # values
            if wt != 2 or values is not None:
                # unpacked (wt 1) elements or a split packed field: protobuf
                # merge semantics concatenate — decline so the full parser
                # (and its shape validation) handles the message
                return None
            n, pos = _read_len(buf, pos)
            if n % 8:
                return None
            values = np.frombuffer(buf, dtype="<f8", count=n // 8, offset=pos)
            pos += n
        else:
            pos = _skip_field(buf, pos, wt)
    if values is None:
        return None
    return shape, values


def parse_tensor_request(wire: bytes):
    """SeldonMessage wire bytes -> (puid, rows ndarray) or None (decline).

    rows is at least 2-D; the values array is a zero-copy view of ``wire``
    (read-only — callers must not mutate in place).
    """
    try:
        pos = 0
        end = len(wire)
        puid = ""
        tensor = None
        seen_meta = False
        while pos < end:
            key, pos = _read_varint(wire, pos)
            field, wt = key >> 3, key & 7
            if field == 2 and wt == 2:  # meta
                if seen_meta:
                    return None  # repeated field -> protobuf merges; decline
                seen_meta = True
                n, pos = _read_len(wire, pos)
                meta_puid = _scan_meta(wire[pos : pos + n])
                if meta_puid is None:
                    return None
                puid = meta_puid
                pos += n
            elif field == 3 and wt == 2:  # data
                if tensor is not None:
                    return None  # repeated data -> merge semantics; decline
                n, pos = _read_len(wire, pos)
                sub = wire[pos : pos + n]
                pos += n
                spos, send = 0, len(sub)
                while spos < send:
                    skey, spos = _read_varint(sub, spos)
                    sfield, swt = skey >> 3, skey & 7
                    if sfield == 2 and swt == 2:  # tensor
                        if tensor is not None:
                            return None  # repeated tensor: merge; decline
                        sn, spos = _read_len(sub, spos)
                        tensor = _scan_tensor(sub[spos : spos + sn])
                        if tensor is None:
                            return None
                        spos += sn
                    elif sfield == 1 and swt == 2:  # names: ignore on input
                        spos = _skip_field(sub, spos, swt)
                    else:
                        return None  # ndarray -> object path
            elif field in (1, 4, 5):  # status / binData / strData
                return None
            else:
                pos = _skip_field(wire, pos, wt)
        if tensor is None:
            return None
        shape, values = tensor
        shape = shape or (values.size,)
        if int(np.prod(shape)) != values.size:
            return None
        rows = values.reshape(shape)
        if rows.ndim < 2:
            rows = rows.reshape(1, -1)
        return puid, rows
    except (IndexError, ValueError):
        return None


def _len_field(field: int, payload: bytes) -> bytes:
    key = (field << 3) | 2
    return bytes([key]) + _varint(len(payload)) + payload


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def names_fragment(names: Sequence[str]) -> bytes:
    """Precomputable DefaultData.names fields (field 1, repeated string)."""
    out = b""
    for nm in names:
        out += _len_field(1, nm.encode("utf-8"))
    return out


# Status{code=200, status=SUCCESS(0)}: field1 varint 200 (SUCCESS is the
# zero enum — omitted on the wire, same bytes upb produces)
_STATUS_OK = _len_field(1, bytes([0x08]) + _varint(200))


def build_tensor_response(
    puid: str, y: np.ndarray, names_frag: bytes = b""
) -> bytes:
    """SUCCESS SeldonMessage with a tensor payload, as wire bytes."""
    y = np.ascontiguousarray(y, dtype="<f8")
    tensor = (
        _len_field(1, b"".join(_varint(int(s)) for s in y.shape))
        + _len_field(2, y.tobytes())
    )
    data = names_frag + _len_field(2, tensor)
    meta = _len_field(1, puid.encode("utf-8"))
    return _STATUS_OK + _len_field(2, meta) + _len_field(3, data)
