"""ctypes bindings for the native SeldonMessage wire codec (native/fastcodec.cpp).

Replaces the per-request cost the reference pays in its vendored protobuf
JsonFormat fork (engine/.../pb/JsonFormat.java, ~1.8k LoC per service) and
its Python wrappers' stock-json marshalling (wrappers/python/
microservice.py:35-120): the C++ side splits a message into a tiny verbatim
"envelope" (meta/status/names spans) and a contiguous float64 buffer, so
parsing a 784-feature request costs one memcpy instead of building ~800
Python objects.

Loading order: prebuilt ``native/libfastcodec.so`` next to the sources, else
build it once with g++ into the same place (first import pays ~1 s), else
``native_available() == False`` and callers use the pure-Python codec.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

__all__ = ["native_available", "parse_message_fast", "format_data_fragment"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "fastcodec.cpp")
_LIB_PATH = os.path.join(_REPO_ROOT, "native", "libfastcodec.so")
_PYMOD_SRC = os.path.join(_REPO_ROOT, "native", "fastcodec_pymod.cpp")
_PYMOD_PATH = os.path.join(_REPO_ROOT, "native", "_fastcodec.so")

_lock = threading.Lock()
_lib = None
_load_attempted = False
_ext = None
_ext_attempted = False

SM_OK = 0
KIND_NONE, KIND_TENSOR, KIND_NDARRAY = 0, 1, 2


class _SMView(ctypes.Structure):
    _fields_ = [
        ("status", ctypes.c_int32),
        ("kind", ctypes.c_int32),
        ("ndim", ctypes.c_int32),
        ("_pad", ctypes.c_int32),
        ("nvalues", ctypes.c_longlong),
        ("envelope_len", ctypes.c_longlong),
        ("envelope", ctypes.c_void_p),
        ("values", ctypes.POINTER(ctypes.c_double)),
        ("shape", ctypes.POINTER(ctypes.c_longlong)),
    ]


def _build() -> bool:
    if not os.path.exists(_SRC):
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-shared",
             "-o", _LIB_PATH, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def _load():
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        if not os.path.exists(_LIB_PATH) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)
        ):
            if not _build():
                if not os.path.exists(_LIB_PATH):
                    return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.sm_parse.restype = ctypes.c_void_p
        lib.sm_parse.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
        lib.sm_parse_view.restype = ctypes.c_void_p
        lib.sm_parse_view.argtypes = [
            ctypes.c_char_p,
            ctypes.c_longlong,
            ctypes.POINTER(_SMView),
        ]
        lib.sm_status.restype = ctypes.c_int
        lib.sm_status.argtypes = [ctypes.c_void_p]
        lib.sm_envelope.restype = ctypes.c_void_p  # raw ptr; length out-param
        lib.sm_envelope.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong)]
        lib.sm_kind.restype = ctypes.c_int
        lib.sm_kind.argtypes = [ctypes.c_void_p]
        lib.sm_values.restype = ctypes.POINTER(ctypes.c_double)
        lib.sm_values.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong)]
        lib.sm_shape.restype = ctypes.POINTER(ctypes.c_longlong)
        lib.sm_shape.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
        lib.sm_free.restype = None
        lib.sm_free.argtypes = [ctypes.c_void_p]
        lib.sm_format.restype = ctypes.c_void_p  # malloc'd; freed via sm_buf_free
        lib.sm_format.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_longlong),
        ]
        lib.sm_buf_free.restype = None
        lib.sm_buf_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def _build_ext() -> bool:
    """Compile the CPython extension binding (fastcodec_pymod.cpp) — ~1us
    per call vs ~15us of ctypes marshalling."""
    if not os.path.exists(_PYMOD_SRC) or not os.path.exists(_SRC):
        return False
    try:
        import sysconfig

        import numpy as _np

        subprocess.run(
            [
                "g++", "-O3", "-std=c++17", "-fPIC", "-shared",
                "-I", sysconfig.get_paths()["include"],
                "-I", _np.get_include(),
                "-I", os.path.join(_REPO_ROOT, "native"),
                "-o", _PYMOD_PATH, _PYMOD_SRC,
            ],
            check=True,
            capture_output=True,
            timeout=180,
        )
        return os.path.exists(_PYMOD_PATH)
    except (subprocess.SubprocessError, OSError, ImportError):
        return False


def _load_ext():
    """The CPython-extension binding, or None (ctypes/pure-Python fallback)."""
    global _ext, _ext_attempted
    with _lock:
        if _ext_attempted:
            return _ext
        _ext_attempted = True
        stale = os.path.exists(_PYMOD_PATH) and any(
            os.path.exists(src) and os.path.getmtime(src) > os.path.getmtime(_PYMOD_PATH)
            for src in (_PYMOD_SRC, _SRC)
        )
        if not os.path.exists(_PYMOD_PATH) or stale:
            if not _build_ext() and not os.path.exists(_PYMOD_PATH):
                return None
        try:
            import importlib.util

            spec = importlib.util.spec_from_file_location("_fastcodec", _PYMOD_PATH)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except (ImportError, OSError):  # pragma: no cover - bad/stale binary
            return None
        _ext = mod
        return _ext


def native_available() -> bool:
    return _load_ext() is not None or _load() is not None


def parse_message_fast(
    raw: bytes,
) -> Optional[Tuple[dict, Optional[str], Optional[np.ndarray]]]:
    """Fast-path parse.  Returns ``(envelope_dict, kind, array)`` where
    ``kind`` is "tensor" | "ndarray" | None and ``array`` the float64 payload,
    or ``None`` when the native codec is unavailable or declines the message
    (caller falls back to the pure-Python parser — including for genuinely
    invalid JSON, so error text stays identical either way)."""
    ext = _load_ext()
    if ext is not None:
        r = ext.parse(raw)
        if r is None:
            return None
        env_bytes, kind_code, arr = r
        if env_bytes == b"{}" or not env_bytes:
            envelope = {}  # bare-data message: skip the ~11us loads
        else:
            try:
                envelope = json.loads(env_bytes)
            except json.JSONDecodeError:
                return None  # envelope should always be valid; be safe
        if kind_code == KIND_NONE:
            return envelope, None, None
        return envelope, ("tensor" if kind_code == KIND_TENSOR else "ndarray"), arr
    lib = _load()
    if lib is None:
        return None
    if isinstance(raw, str):
        raw = raw.encode("utf-8")
    view = _SMView()
    h = lib.sm_parse_view(raw, len(raw), ctypes.byref(view))
    if not h:
        return None
    try:
        if view.status != SM_OK:
            return None
        env_bytes = (
            ctypes.string_at(view.envelope, view.envelope_len)
            if view.envelope
            else b"{}"
        )
        try:
            envelope = json.loads(env_bytes)
        except json.JSONDecodeError:
            return None  # envelope should always be valid; be safe
        if view.kind == KIND_NONE:
            return envelope, None, None
        shape = tuple(view.shape[i] for i in range(view.ndim))
        if view.nvalues:
            # one memmove into a fresh writable array — np.ctypeslib.as_array
            # costs ~10us building a ctypes array type per call
            arr = np.empty((view.nvalues,), dtype=np.float64)
            ctypes.memmove(arr.ctypes.data, view.values, view.nvalues * 8)
        else:
            arr = np.empty((0,), dtype=np.float64)
        arr = arr.reshape(shape)
        kind = "tensor" if view.kind == KIND_TENSOR else "ndarray"
        return envelope, kind, arr
    finally:
        lib.sm_free(h)


def format_data_fragment(arr: np.ndarray, kind: str) -> Optional[bytes]:
    """Format ``arr`` as the JSON fragment ``"tensor":{...}`` or
    ``"ndarray":[...]`` (no surrounding braces).  None => caller falls back."""
    a = np.ascontiguousarray(arr, dtype=np.float64)
    if a.ndim == 0:
        a = a.reshape(1)
    ext = _load_ext()
    if ext is not None:
        kind_code = KIND_TENSOR if kind == "tensor" else KIND_NDARRAY
        return ext.format(a, kind_code)
    lib = _load()
    if lib is None:
        return None
    shape = (ctypes.c_longlong * a.ndim)(*a.shape)
    out_len = ctypes.c_longlong(0)
    kind_code = KIND_TENSOR if kind == "tensor" else KIND_NDARRAY
    buf = lib.sm_format(
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        shape,
        a.ndim,
        kind_code,
        ctypes.byref(out_len),
    )
    if not buf:
        return None
    try:
        return ctypes.string_at(buf, out_len.value)
    finally:
        lib.sm_buf_free(buf)
