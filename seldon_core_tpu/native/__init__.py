"""Native (C++) runtime components — wire codec and support libraries.

The compute path of this framework is JAX/XLA on TPU; the host runtime
around it uses compiled C++ where the hot loops are host-bound, loaded via
ctypes (no pybind11 in this environment).  Every native component has a
pure-Python fallback so the framework degrades gracefully on machines
without a toolchain.
"""

from seldon_core_tpu.native.fastcodec import (  # noqa: F401
    native_available,
    parse_message_fast,
    format_data_fragment,
)
