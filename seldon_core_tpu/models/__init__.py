"""Model families for the judged workloads (reference examples/ directory):
MNIST classifiers (flagship), iris classifier, epsilon-greedy bandit router,
Mahalanobis streaming outlier detector."""

from seldon_core_tpu.models.mnist import (  # noqa: F401
    MnistClassifier,
    MnistCNN,
    QuantizedMnistClassifier,
)
from seldon_core_tpu.models.iris import IrisClassifier  # noqa: F401
from seldon_core_tpu.models.mab import EpsilonGreedyRouter  # noqa: F401
from seldon_core_tpu.models.outlier import MahalanobisOutlier  # noqa: F401
from seldon_core_tpu.models.tabular import (  # noqa: F401
    MeanClassifier,
    MeanTransformer,
    ObliviousTreeEnsemble,
    SigmoidPredictor,
)
from seldon_core_tpu.models.generate import TransformerGenerator  # noqa: F401
from seldon_core_tpu.models.speculative import (  # noqa: F401
    SpeculativeGenerator,
    speculative_generate,
)
