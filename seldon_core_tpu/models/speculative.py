"""Speculative decoding — draft/verify generation, exact under greedy.

A small draft LM proposes ``k`` tokens with its own KV cache; the target LM
scores all ``k+1`` positions in ONE forward (one MXU pass instead of k+1
sequential decode steps); the longest prefix where the draft matched the
target's argmax is accepted plus one corrected token.  Greedy acceptance is
exact in exact arithmetic: the output equals vanilla greedy decoding of the
target token-for-token (pinned bit-exact by the f32 tests).  In low
precision an argmax near-tie can flip between the S=1 and S=k+1 segment
forwards (different reduction orders), so bf16 outputs may diverge at tie
positions — same-quality tokens, not errors.  The target runs
~(accepted+1)x fewer sequential passes; acceptance rate tracks how well
the draft approximates the target (an unrelated random draft accepts ~0).

TPU shape: the whole loop is one ``lax.while_loop`` under jit — draft scan,
target segment-verify, acceptance, cache advance — so an entire generation
is still a single device dispatch.  Caches are preallocated; partially
rejected segments need no rewind because attention masks by global position
and later segments overwrite the stale tail (``dynamic_update_slice``).

Batch: rows decode INDEPENDENTLY (per-row caches, per-row acceptance), so
B>1 runs the single-row program under ``vmap`` — JAX lifts the
``while_loop`` to run-until-every-row-finishes with masked carries, which
is the standard batched-speculative trade: rows advance in lockstep
rounds, the fastest rows idle (masked) until the slowest accepts its last
token, and every round's draft scan + target verify is one batched MXU
pass over all rows.  Per-row outputs are exactly the B=1 outputs (pinned
by tests in f32); serving coalesces concurrent callers into one such
batch.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from seldon_core_tpu.graph.units import Unit, register_unit
from seldon_core_tpu.models.generate import (
    init_cache,
    sanitize_prompt,
    segment_forward,
)
from seldon_core_tpu.models.transformer import LMConfig, lm_init

__all__ = ["speculative_generate", "SpeculativeGenerator"]


def speculative_generate(
    target_params,
    draft_params,
    prompt,
    target_cfg: LMConfig,
    draft_cfg: LMConfig,
    max_new_tokens: int = 32,
    k: int = 4,
) -> Tuple[jax.Array, jax.Array]:
    """prompt [B, S] int32 -> (tokens [B, max_new_tokens] int32,
    rounds int32 [B] — verify passes used per row; ~max_new/rounds tokens
    per target pass, vs exactly 1 for vanilla decoding).

    Greedy only; per-row output is exactly vanilla greedy decoding of the
    target.  Rows vmap over the single-row program (see module docstring).
    """
    return jax.vmap(
        lambda row: _speculative_row(
            target_params, draft_params, row, target_cfg, draft_cfg,
            max_new_tokens, k,
        )
    )(prompt)


def _speculative_row(
    target_params, draft_params, row, target_cfg: LMConfig,
    draft_cfg: LMConfig, max_new_tokens: int, k: int,
) -> Tuple[jax.Array, jax.Array]:
    """row [S] int32 -> (tokens [max_new_tokens], rounds scalar)."""
    prompt = row[None, :]
    B, S = prompt.shape
    max_len = S + max_new_tokens + k + 2
    t_cache = init_cache(target_cfg, B, max_len)
    d_cache = init_cache(draft_cfg, B, max_len)

    # prefill both models on the prompt; last-position argmax = first token
    t_logits, t_cache = segment_forward(
        target_params, prompt, t_cache, 0, target_cfg, segment=False)
    _d_logits, d_cache = segment_forward(
        draft_params, prompt, d_cache, 0, draft_cfg, segment=False)
    first = jnp.argmax(t_logits[:, -1, :], axis=-1).astype(jnp.int32)  # [1]

    out = jnp.zeros((max_new_tokens + k + 1,), jnp.int32)
    out = out.at[0].set(first[0])

    def cond(carry):
        n, *_ = carry
        return n < max_new_tokens

    def body(carry):
        n, rounds, out, t_cache, d_cache = carry
        # positions: the last accepted token sits at global index S + n - 1
        last = jax.lax.dynamic_index_in_dim(
            out, n - 1, 0, keepdims=False
        )  # newest token (scalar)

        # -- draft proposes k tokens with its cache ------------------------
        # k+1 steps: the extra step writes the KV of the LAST proposal so a
        # fully-accepted round leaves no cache hole behind (holes would
        # degrade every later round's acceptance); its proposal is unused
        def draft_step(c, i):
            tok, d_cache = c
            logits, d_cache = segment_forward(
                draft_params, tok[None, None], d_cache, S + n - 1 + i,
                draft_cfg)
            nxt = jnp.argmax(logits[0, -1, :]).astype(jnp.int32)
            return (nxt, d_cache), nxt

        (_, d_cache), proposals = jax.lax.scan(
            draft_step, (last, d_cache), jnp.arange(k + 1))  # [k+1]
        draft_toks = proposals[:k]

        # -- target verifies last + k draft tokens in ONE forward ----------
        seg = jnp.concatenate([last[None], draft_toks])[None, :]  # [1, k+1]
        t_logits, t_cache = segment_forward(
            target_params, seg, t_cache, S + n - 1, target_cfg)
        t_argmax = jnp.argmax(t_logits[0], axis=-1).astype(jnp.int32)  # [k+1]

        # greedy acceptance: longest prefix where draft == target argmax
        match = draft_toks == t_argmax[:k]
        accepted = jnp.argmin(
            jnp.concatenate([match, jnp.zeros((1,), bool)])
        )  # first False; k if all matched
        # tokens gained this round: accepted drafts + 1 corrected/extended
        new_toks = jnp.where(
            jnp.arange(k + 1) < accepted,
            jnp.concatenate([draft_toks, jnp.zeros((1,), jnp.int32)]),
            jnp.broadcast_to(
                jax.lax.dynamic_index_in_dim(
                    t_argmax, accepted, 0, keepdims=False
                ),
                (k + 1,),
            ),
        )  # positions > accepted are garbage; masked by the write below
        gained = accepted + 1
        keep = jnp.arange(k + 1) < gained
        cur = jax.lax.dynamic_slice_in_dim(out, n, k + 1)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, jnp.where(keep, new_toks, cur), n, 0)
        return n + gained, rounds + 1, out, t_cache, d_cache

    n0 = jnp.int32(1)
    n, rounds, out, _, _ = jax.lax.while_loop(
        cond, body, (n0, jnp.int32(0), out, t_cache, d_cache))
    return out[:max_new_tokens], rounds


@register_unit("SpeculativeGenerator")
class SpeculativeGenerator(Unit):
    """Serving unit: speculative draft/verify generation over the standard
    data plane.  Target and draft dimensions are graph parameters (draft_*
    defaults to a quarter-size model).  Concurrent callers coalesce into
    one vmapped draft/verify loop (rows independent; lockstep rounds)."""

    pure = True
    # rows are independent (vmapped row programs): concurrent callers
    # coalesce into one batched draft/verify loop like any other unit

    def __init__(self, vocab: int = 256, d_model: int = 128, n_heads: int = 4,
                 n_layers: int = 2, d_ff: int = 512,
                 draft_d_model: int = 0, draft_n_heads: int = 0,
                 draft_n_layers: int = 0, draft_d_ff: int = 0,
                 seed: int = 0, max_new_tokens: int = 32, k: int = 4,
                 dtype: str = "float32", rope: bool = True,
                 rope_base: float = 10000.0):
        dt = jnp.dtype(dtype).type
        rope = bool(rope)
        self.target_cfg = LMConfig(
            vocab=int(vocab), d_model=int(d_model), n_heads=int(n_heads),
            n_layers=int(n_layers), d_ff=int(d_ff), dtype=dt,
            rope=rope, rope_base=float(rope_base),
        )
        dd = int(draft_d_model) or max(16, int(d_model) // 4)
        dh = int(draft_n_heads) or max(2, int(n_heads) // 2)
        # derived defaults must keep hd integral — and EVEN when RoPE is
        # on (rotation pairs dimensions)
        while dd % dh != 0 or (rope and (dd // dh) % 2 != 0):
            if dh <= 1:
                raise ValueError(
                    f"cannot derive a draft head count for d_model={dd} "
                    f"with rope={rope}; set draft_n_heads explicitly"
                )
            dh -= 1
        self.draft_cfg = LMConfig(
            vocab=int(vocab), d_model=dd, n_heads=dh,
            n_layers=int(draft_n_layers) or max(1, int(n_layers) // 2),
            d_ff=int(draft_d_ff) or max(32, int(d_ff) // 4),
            dtype=dt, rope=rope, rope_base=float(rope_base),
        )
        self.seed = int(seed)
        self.max_new_tokens = int(max_new_tokens)
        self.k = int(k)

    def init_state(self, rng):
        if rng is None:
            rng = jax.random.key(self.seed)
        rng = jax.random.fold_in(rng, self.seed)
        kt, kd = jax.random.split(rng)
        return {"target": lm_init(kt, self.target_cfg),
                "draft": lm_init(kd, self.draft_cfg)}

    def predict(self, state, X):
        prompt = sanitize_prompt(X, self.target_cfg.vocab)
        toks, _rounds = speculative_generate(
            state["target"], state["draft"], prompt,
            self.target_cfg, self.draft_cfg,
            max_new_tokens=self.max_new_tokens, k=self.k,
        )
        return toks.astype(jnp.float32)
