"""Speculative decoding — draft/verify generation, exact under greedy.

A small draft LM proposes ``k`` tokens with its own KV cache; the target LM
scores all ``k+1`` positions in ONE forward (one MXU pass instead of k+1
sequential decode steps); the longest prefix where the draft matched the
target's argmax is accepted plus one corrected token.  Greedy acceptance is
exact in exact arithmetic: the output equals vanilla greedy decoding of the
target token-for-token (pinned bit-exact by the f32 tests).  In low
precision an argmax near-tie can flip between the S=1 and S=k+1 segment
forwards (different reduction orders), so bf16 outputs may diverge at tie
positions — same-quality tokens, not errors.  The target runs
~(accepted+1)x fewer sequential passes; acceptance rate tracks how well
the draft approximates the target (an unrelated random draft accepts ~0).

TPU shape: the whole loop is one SHARED batched ``lax.while_loop`` under
jit — every round, ALL rows draft k tokens (batched one-token forwards),
ALL rows verify in one (k+1)-wide target pass, and acceptance is a masked
per-row reduction.  There is no per-row program and no vmap-lifted
while_loop: rows at different sequence lengths share every MXU pass.

The layout trick that makes the shared loop scatter-free: cache slots are
ROUND-ALIGNED.  Round r writes its k+1 candidate K/V at slots
``S + r*(k+1)..`` — the SAME offset for every row — so cache writes are
ordinary ``dynamic_update_slice`` ops, never per-row scatters (the old
vmapped design's per-row offsets lowered each cache write to a scatter).
Rejected candidates leave holes; a per-row VALIDITY BITMAP masks them out
of every later attention (additive -1e30), and RoPE rotates by per-row
LOGICAL positions (apply_rope takes [B, S] position arrays), so the math
over the valid set is exactly vanilla greedy decoding of the target.
Memory trades for regularity: caches are sized S + (max_new-1)*(k+1)
worst-case instead of S + max_new.

Rows that finish early keep riding the loop with their validity updates
masked off (gained = 0), and outputs are written round-aligned
([B, rounds, k+1] + per-row gained counts), compacted once at the end —
the only scatter in the program.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from seldon_core_tpu.graph.units import Unit, register_unit
from seldon_core_tpu.models.generate import (
    _grouped_pv,
    _grouped_qk,
    _heads,
    init_cache,
    sanitize_prompt,
    segment_forward,
)
from seldon_core_tpu.models.transformer import (
    LMConfig,
    _ffn,
    _rmsnorm,
    apply_rope,
    lm_init,
)

__all__ = ["speculative_generate", "SpeculativeGenerator"]


def _forward_seg(params, tokens, cache, off, pos0, valid, cfg: LMConfig):
    """Bitmap-masked segment forward for the shared round loop.

    tokens [B, W] at per-row logical positions pos0[:, None] + arange(W);
    K/V written at cache slots off..off+W-1 (``off`` is round-uniform —
    a regular dus, never a scatter).  Attention allows, per row, the
    ``valid`` [B, L] bitmap slots plus in-segment causal slots (slot
    off+j visible to query i iff j <= i).  Returns
    (logits [B, W, vocab] f32, cache').

    NOTE: this deliberately re-states the per-layer forward that
    generate.py's _block_cached implements for prefix-valid caches —
    the bitmap mask and per-row positions cut across every one of that
    function's masking modes.  The two MUST evolve together (new quant
    modes, attention changes); the float-only guard in
    speculative_generate is the current honest gap."""
    from seldon_core_tpu.ops.quant import lm_matmul

    B, W = tokens.shape
    D = cfg.d_model
    hd = D // cfg.n_heads
    kv_h = cfg.kv_heads
    L = cache["l0"]["k"].shape[2]
    lidx = jnp.arange(L)
    seg = (lidx >= off) & (lidx < off + W)              # [L]
    incause = (lidx - off)[None, :] <= jnp.arange(W)[:, None]  # [W, L]
    allowed = jnp.where(seg[None, None, :], incause[None, :, :],
                        valid[:, None, :])              # [B, W, L]
    mask_add = jnp.where(allowed, 0.0, -1e30).astype(jnp.float32)
    positions = pos0[:, None] + jnp.arange(W)[None, :]  # [B, W]
    x = params["embed"][tokens]                         # [B, W, D]
    for i in range(cfg.n_layers):
        lp = params[f"l{i}"]
        cl = cache[f"l{i}"]
        h = _rmsnorm(x, lp["ln1"])
        qkv = lm_matmul(lp, "wqkv", h, out_dtype=x.dtype)
        q, k, v = jnp.split(qkv, [D, D + kv_h * hd], axis=-1)
        q = _heads(q, B, W, cfg.n_heads, hd)
        k = _heads(k, B, W, kv_h, hd)
        v = _heads(v, B, W, kv_h, hd)
        if cfg.rope:
            q = apply_rope(q, positions, cfg.rope_base)
            k = apply_rope(k, positions, cfg.rope_base)
        cl = {
            "k": jax.lax.dynamic_update_slice(
                cl["k"], k.astype(cl["k"].dtype), (0, 0, off, 0)),
            "v": jax.lax.dynamic_update_slice(
                cl["v"], v.astype(cl["v"].dtype), (0, 0, off, 0)),
        }
        s = _grouped_qk(q, cl["k"])                     # [B,KV,g,W,L]
        s = s + mask_add[:, None, None, :, :]
        p = jax.nn.softmax(s, axis=-1)
        a = _grouped_pv(p, cl["v"], q.shape, q.dtype)
        a = a.transpose(0, 2, 1, 3).reshape(B, W, D)
        x = x + lm_matmul(lp, "wo", a, out_dtype=x.dtype)
        h2 = _rmsnorm(x, lp["ln2"])
        y, _lb = _ffn(lp, h2, cfg, mesh=None)
        x = x + y
        cache[f"l{i}"] = cl
    x = _rmsnorm(x, params["ln_f"])
    return (x @ params["embed"].T).astype(jnp.float32), cache


def speculative_generate(
    target_params,
    draft_params,
    prompt,
    target_cfg: LMConfig,
    draft_cfg: LMConfig,
    max_new_tokens: int = 32,
    k: int = 4,
    max_rounds: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """prompt [B, S] int32 -> (tokens [B, max_new_tokens] int32,
    rounds int32 [B] — verify passes used per row; ~max_new/rounds tokens
    per target pass, vs exactly 1 for vanilla decoding).

    Greedy only; per-row output equals vanilla greedy decoding of the
    target over its confirmed prefix.  One SHARED batched round loop —
    see the module docstring for the round-aligned/bitmap design.

    CACHE SIZING: round-aligned slots make both caches worst-case sized
    ``Lmax = S + R*(k+1)`` where ``R = max_new_tokens - 1`` — about
    (k+1)x the S + max_new a vanilla decode allocates (5x at k=4).
    ``max_rounds > 0`` caps R by an EXPECTED-ACCEPTANCE bound: a draft
    that tracks the target at mean acceptance ``a`` finishes in about
    ``max_new / (a*k + 1)`` rounds, so e.g. ``max_rounds =
    ceil(max_new / (0.5*k + 1)) + slack`` cuts the cache to that many
    rounds' worth.  The cap trades worst-case completeness for memory:
    rows still decoding when rounds run out get zero-padded tails
    (``rounds`` returned == cap for such rows — observable), so pick the
    cap from measured acceptance, not hope.  0 (default) keeps the exact
    worst-case sizing.

    Telemetry: eager calls record the per-request mean acceptance ratio
    into the flight recorder (seldon_tpu_speculative_accept_ratio);
    traced calls skip (trace-time constants are not serving data)."""
    if target_cfg.kv_quant == "int8" or draft_cfg.kv_quant == "int8":
        raise NotImplementedError(
            "speculative decoding runs float KV caches; quantize weights "
            "(quant='int8'), not the cache")
    B, S = prompt.shape
    W = k + 1
    R = max(max_new_tokens - 1, 1)  # worst case: 1 token gained per round
    if max_rounds > 0:
        R = min(R, int(max_rounds))
    Lmax = S + R * W
    t_cache = init_cache(target_cfg, B, Lmax)
    d_cache = init_cache(draft_cfg, B, Lmax)

    # prefill both models on the prompt; last-position argmax = first token
    t_logits, t_cache = segment_forward(
        target_params, prompt, t_cache, 0, target_cfg, segment=False)
    _d_logits, d_cache = segment_forward(
        draft_params, prompt, d_cache, 0, draft_cfg, segment=False)
    first = jnp.argmax(t_logits[:, -1, :], axis=-1).astype(jnp.int32)  # [B]
    if max_new_tokens == 1:
        return first[:, None], jnp.zeros((B,), jnp.int32)

    valid0 = jnp.broadcast_to(jnp.arange(Lmax) < S, (B, Lmax))
    toks_rounds = jnp.zeros((B, R, W), jnp.int32)
    gained_rounds = jnp.zeros((B, R), jnp.int32)

    def cond(c):
        r, n = c[0], c[1]
        return (r < R) & jnp.any(n < max_new_tokens)

    def body(c):
        (r, n, last, toks_rounds, gained_rounds, rounds_used,
         t_cache, d_cache, t_valid, d_valid) = c
        off = S + r * W
        P = S + n - 1  # logical position of `last`, per row [B]

        # -- every row drafts k tokens: k+1 batched one-token forwards.
        # The extra step writes the LAST proposal's KV so a fully-
        # accepted round leaves no cache hole.  Earlier in-round slots
        # become visible through the provisional bitmap ``dv``.
        def draft_step(carry, i):
            tok, d_cache, dv = carry
            logits, d_cache = _forward_seg(
                draft_params, tok[:, None], d_cache, off + i, P + i,
                dv, draft_cfg)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            dv = jax.lax.dynamic_update_slice(
                dv, jnp.ones((B, 1), bool), (0, off + i))
            return (nxt, d_cache, dv), tok

        (_, d_cache, _), seg_toks = jax.lax.scan(
            draft_step, (last, d_cache, d_valid), jnp.arange(W))
        # seg_toks[i] is the token FED at step i: [last, d1..dk]
        seg_toks = seg_toks.T  # [B, W]
        draft_toks = seg_toks[:, 1:]  # [B, k]

        # -- one (k+1)-wide target pass verifies every row ----------------
        t_logits, t_cache = _forward_seg(
            target_params, seg_toks, t_cache, off, P, t_valid, target_cfg)
        t_argmax = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [B,W]

        # greedy acceptance: longest prefix where draft == target argmax
        match = draft_toks == t_argmax[:, :k]  # [B, k]
        a = jnp.argmin(
            jnp.concatenate([match, jnp.zeros((B, 1), bool)], axis=1),
            axis=1,
        )  # [B] first False; k if all matched
        corrected = jnp.take_along_axis(t_argmax, a[:, None], axis=1)[:, 0]
        padded = jnp.concatenate(
            [draft_toks, jnp.zeros((B, 1), jnp.int32)], axis=1)  # [B, W]
        new_toks = jnp.where(
            jnp.arange(W)[None, :] < a[:, None], padded, corrected[:, None])
        active = n < max_new_tokens
        gained = jnp.where(active, a + 1, 0)

        toks_rounds = jax.lax.dynamic_update_slice(
            toks_rounds, new_toks[:, None, :], (0, r, 0))
        gained_rounds = jax.lax.dynamic_update_slice(
            gained_rounds, gained[:, None], (0, r))
        # confirmed slots this round: off+0 (last) .. off+a — `last` was
        # materialised here for the first time (the corrected token is
        # never forwarded in the round it is emitted), so slot 0 is the
        # ONLY copy and stays valid; rejected tails stay holes
        vmask = ((jnp.arange(W)[None, :] <= a[:, None])
                 & active[:, None])  # [B, W]
        t_valid = jax.lax.dynamic_update_slice(t_valid, vmask, (0, off))
        d_valid = jax.lax.dynamic_update_slice(d_valid, vmask, (0, off))
        last = jnp.where(active, corrected, last)
        return (r + 1, n + gained, last, toks_rounds, gained_rounds,
                rounds_used + active.astype(jnp.int32),
                t_cache, d_cache, t_valid, d_valid)

    (r, n, last, toks_rounds, gained_rounds, rounds_used,
     *_rest) = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0), jnp.ones((B,), jnp.int32), first, toks_rounds,
         gained_rounds, jnp.zeros((B,), jnp.int32), t_cache, d_cache,
         valid0, valid0),
    )

    # compact the round-aligned tokens into dense rows — the program's
    # ONE scatter, run once after the loop
    flat = toks_rounds.reshape(B, R * W)
    keep = (jnp.arange(W)[None, None, :]
            < gained_rounds[:, :, None]).reshape(B, R * W)
    dest = jnp.cumsum(keep, axis=1)  # kept token j -> output index 1..
    pad = max_new_tokens + W  # clipped rows' overflow lands past the end
    dest = jnp.where(keep, jnp.minimum(dest, pad), pad)
    out = jnp.zeros((B, pad + 1), jnp.int32)
    out = out.at[:, 0].set(first)
    out = out.at[jnp.arange(B)[:, None], dest].set(
        jnp.where(keep, flat, 0))
    toks_out = out[:, :max_new_tokens]
    if not isinstance(rounds_used, jax.core.Tracer):
        # eager execution: per-request acceptance telemetry.  gained
        # tokens per round = accepted drafts + 1 corrected, so accepted
        # fraction = (emitted_after_first - rounds) / (rounds * k)
        import numpy as _np

        from seldon_core_tpu.utils.telemetry import RECORDER

        rounds = _np.asarray(rounds_used, dtype=_np.float64)
        emitted = _np.minimum(
            _np.asarray(n, dtype=_np.float64), float(max_new_tokens)) - 1.0
        with _np.errstate(divide="ignore", invalid="ignore"):
            ratio = _np.where(
                rounds > 0, (emitted - rounds) / (rounds * max(k, 1)), 0.0)
        RECORDER.observe_accept_ratio(
            float(_np.clip(ratio, 0.0, 1.0).mean()))
    return toks_out, rounds_used


@register_unit("SpeculativeGenerator")
class SpeculativeGenerator(Unit):
    """Serving unit: speculative draft/verify generation over the standard
    data plane.  Target and draft dimensions are graph parameters (draft_*
    defaults to a quarter-size model).  Concurrent callers coalesce into
    ONE shared batched round loop (round-aligned cache slots + per-row
    validity bitmaps — see speculative_generate); per-row outputs equal
    the single-row outputs, so coalescing never changes an answer.

    MEMORY: round-aligned cache slots size BOTH the target and draft KV
    caches at ``Lmax = S + (max_new_tokens - 1) * (k + 1)`` — worst case
    one gained token per verify round, ~(k+1)x the ``S + max_new`` a
    vanilla decode allocates (5x at k=4).  Deployments sized before this
    layout (round 4 and earlier) can OOM on the same graph parameters;
    either lower ``max_new_tokens``/``k`` or set ``max_rounds`` to an
    expected-acceptance bound.  Example: ``max_new_tokens=256, k=4`` is
    worst-case Lmax = S + 1275 slots/row/model; a draft measured at ~50%
    acceptance finishes in ~256/(0.5*4+1) = 86 rounds, so
    ``max_rounds=110`` (bound + ~25% slack) cuts that to S + 550 while
    leaving headroom.  Rows that exhaust the capped rounds get
    zero-padded tails — watch seldon_tpu_speculative_accept_ratio and
    resize when the measured acceptance drifts below the bound."""

    pure = True
    # per-row outputs are independent of co-batched rows (pinned by
    # tests), so concurrent callers coalesce like any other unit

    def __init__(self, vocab: int = 256, d_model: int = 128, n_heads: int = 4,
                 n_layers: int = 2, d_ff: int = 512,
                 draft_d_model: int = 0, draft_n_heads: int = 0,
                 draft_n_layers: int = 0, draft_d_ff: int = 0,
                 seed: int = 0, max_new_tokens: int = 32, k: int = 4,
                 max_rounds: int = 0,
                 dtype: str = "float32", rope: bool = True,
                 rope_base: float = 10000.0):
        dt = jnp.dtype(dtype).type
        rope = bool(rope)
        self.target_cfg = LMConfig(
            vocab=int(vocab), d_model=int(d_model), n_heads=int(n_heads),
            n_layers=int(n_layers), d_ff=int(d_ff), dtype=dt,
            rope=rope, rope_base=float(rope_base),
        )
        dd = int(draft_d_model) or max(16, int(d_model) // 4)
        dh = int(draft_n_heads) or max(2, int(n_heads) // 2)
        # derived defaults must keep hd integral — and EVEN when RoPE is
        # on (rotation pairs dimensions)
        while dd % dh != 0 or (rope and (dd // dh) % 2 != 0):
            if dh <= 1:
                raise ValueError(
                    f"cannot derive a draft head count for d_model={dd} "
                    f"with rope={rope}; set draft_n_heads explicitly"
                )
            dh -= 1
        self.draft_cfg = LMConfig(
            vocab=int(vocab), d_model=dd, n_heads=dh,
            n_layers=int(draft_n_layers) or max(1, int(n_layers) // 2),
            d_ff=int(draft_d_ff) or max(32, int(d_ff) // 4),
            dtype=dt, rope=rope, rope_base=float(rope_base),
        )
        self.seed = int(seed)
        self.max_new_tokens = int(max_new_tokens)
        self.k = int(k)
        self.max_rounds = int(max_rounds)

    def init_state(self, rng):
        if rng is None:
            rng = jax.random.key(self.seed)
        rng = jax.random.fold_in(rng, self.seed)
        kt, kd = jax.random.split(rng)
        return {"target": lm_init(kt, self.target_cfg),
                "draft": lm_init(kd, self.draft_cfg)}

    def continuous_spec(self, state):
        """Scheduler contract for the continuous-batching lane
        (runtime/genserver.py): the draft params/config put the scheduler
        in SPECULATIVE mode — per-step draft/verify rounds over paged
        pools, so the 2.42x trained-draft win composes with continuous
        admission instead of living only in the isolated bench arm.
        Greedy/float-KV only, matching speculative_generate's guards."""
        return {
            "params": state["target"],
            "cfg": self.target_cfg,
            "temperature": 0.0,
            "top_k": 0,
            "top_p": 0.0,
            "eos_token": -1,
            "max_new_tokens": self.max_new_tokens,
            "draft_params": state["draft"],
            "draft_cfg": self.draft_cfg,
            "spec_k": self.k,
            "seed": self.seed,
        }

    def predict(self, state, X):
        prompt = sanitize_prompt(X, self.target_cfg.vocab)
        toks, _rounds = speculative_generate(
            state["target"], state["draft"], prompt,
            self.target_cfg, self.draft_cfg,
            max_new_tokens=self.max_new_tokens, k=self.k,
            max_rounds=self.max_rounds,
        )
        return toks.astype(jnp.float32)
