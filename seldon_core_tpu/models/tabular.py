"""Tabular model families — parity with the reference's small-model zoo.

Covers the reference examples beyond iris/MNIST:
  * ``MeanClassifier``     — sigmoid of (row mean − threshold)
                             (reference examples/models/mean_classifier/
                             MeanClassifier.py:7-27, sans the model.npy file:
                             the threshold is a constructor parameter).
  * ``SigmoidPredictor``   — 2-layer MLP trained at construction on the
                             synthetic sigmoid(x0*x1) task (reference
                             examples/models/sigmoid_predictor/
                             SigmoidPredictor.py:8-21 trains an sklearn
                             MLPClassifier the same way).
  * ``MeanTransformer``    — min-max normalisation input TRANSFORMER
                             (reference examples/transformers/
                             mean_transformer/MeanTransformer.py:3-12).
  * ``ObliviousTreeEnsemble`` — gradient-boosted oblivious trees, the
                             TPU-native stand-in for the reference's H2O GBM
                             example (examples/models/h2o_example): level-wise
                             shared splits mean a tree evaluates as d feature
                             comparisons + a bit-packed leaf lookup, which is
                             a one-hot matmul on the MXU — no per-node
                             branching, fully jit-traceable.

All are pure ``Unit``s: state is a parameter pytree, methods are traceable,
so any of them can compile into the graph's single XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from seldon_core_tpu.graph.units import Unit, register_unit

__all__ = [
    "MeanClassifier",
    "SigmoidPredictor",
    "MeanTransformer",
    "ObliviousTreeEnsemble",
]


@register_unit("MeanClassifier")
class MeanClassifier(Unit):
    """P(positive) = sigmoid(mean(x) - threshold)."""

    class_names = ["proba"]

    def __init__(self, threshold: float = 0.0, intValue: int = 0):
        # the reference's intValue shifts the trained threshold; keep both
        self.threshold = float(threshold) + int(intValue)

    def init_state(self, rng):
        return {"threshold": jnp.asarray(self.threshold, jnp.float32)}

    def predict(self, state, X):
        m = jnp.mean(X.astype(jnp.float32), axis=1, keepdims=True)
        return jax.nn.sigmoid(m - state["threshold"])


@register_unit("SigmoidPredictor")
class SigmoidPredictor(Unit):
    """Binary classifier on the synthetic y = [sigmoid(x0*x1) >= 0.5] task,
    trained with a few hundred full-batch gradient steps at init."""

    class_names = ["p0", "p1"]

    def __init__(self, n_features: int = 10, hidden: int = 32,
                 train_samples: int = 2048, train_steps: int = 300,
                 seed: int = 0):
        self.n_features = int(n_features)
        self.hidden = int(hidden)
        self.train_samples = int(train_samples)
        self.train_steps = int(train_steps)
        self.seed = int(seed)

    def init_state(self, rng):
        if rng is None:
            rng = jax.random.key(self.seed)
        kx, k1, k2 = jax.random.split(jax.random.fold_in(rng, self.seed), 3)
        X = jax.random.normal(kx, (self.train_samples, self.n_features))
        y = (jax.nn.sigmoid(X[:, 0] * X[:, 1]) >= 0.5).astype(jnp.int32)
        params = {
            "w1": jax.random.normal(k1, (self.n_features, self.hidden))
            * (self.n_features ** -0.5),
            "b1": jnp.zeros((self.hidden,)),
            "w2": jax.random.normal(k2, (self.hidden, 2)) * (self.hidden ** -0.5),
            "b2": jnp.zeros((2,)),
        }

        def loss(p):
            logits = jnp.tanh(X @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

        def step(p, _):
            g = jax.grad(loss)(p)
            return jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g), None

        params, _ = jax.lax.scan(step, params, None, length=self.train_steps)
        return params

    def predict(self, state, X):
        h = jnp.tanh(X.astype(jnp.float32) @ state["w1"] + state["b1"])
        return jax.nn.softmax(h @ state["w2"] + state["b2"], axis=-1)


@register_unit("MeanTransformer")
class MeanTransformer(Unit):
    """Min-max normalise the whole batch to [0, 1]; constant batch -> zeros
    (reference MeanTransformer.py:8-12 semantics exactly).

    The min/max reduction couples rows, so a request must see only its own
    rows: ``batch_coupled`` opts graphs containing this unit out of
    cross-request coalescing (in the reference each HTTP request was
    normalised by itself, one call per request)."""

    batch_coupled = True

    def transform_input(self, state, X):
        X = X.astype(jnp.float32)
        lo, hi = jnp.min(X), jnp.max(X)
        rng = hi - lo
        safe = jnp.where(rng == 0.0, 1.0, rng)
        return jnp.where(rng == 0.0, jnp.zeros_like(X), (X - lo) / safe)


@register_unit("ObliviousTreeEnsemble")
class ObliviousTreeEnsemble(Unit):
    """Boosted oblivious trees fitted at init on a synthetic regression task
    (or supplied data): every level of a tree shares one (feature, threshold)
    split, so a depth-d tree maps a row to one of 2^d leaves by d vectorised
    comparisons; leaf values are gathered with a one-hot matmul (MXU).

    Fitting is greedy CatBoost-style: per boosting round, pick each level's
    split by scoring a quantile grid of candidate thresholds on the current
    residuals, then set leaf values to the mean residual per leaf.
    """

    class_names = ["prediction"]

    def __init__(self, n_features: int = 8, n_trees: int = 16, depth: int = 3,
                 learning_rate: float = 0.3, train_samples: int = 1024,
                 seed: int = 0):
        self.n_features = int(n_features)
        self.n_trees = int(n_trees)
        self.depth = int(depth)
        self.lr = float(learning_rate)
        self.train_samples = int(train_samples)
        self.seed = int(seed)

    # -- fitting (host-side numpy; runs once at construction) ---------------

    def _synthetic(self, rng):
        X = rng.normal(size=(self.train_samples, self.n_features))
        y = (
            np.sin(X[:, 0]) + 0.5 * X[:, 1] * (X[:, 2] > 0)
            + 0.25 * rng.normal(size=self.train_samples)
        )
        return X, y

    def fit_arrays(self, X, y):
        """Greedy fit; returns (feat [T,d], thresh [T,d], leaves [T,2^d], base)."""
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        base = float(y.mean())
        resid = y - base
        feats = np.zeros((self.n_trees, self.depth), np.int32)
        thrs = np.zeros((self.n_trees, self.depth), np.float64)
        leaves = np.zeros((self.n_trees, 2 ** self.depth), np.float64)
        qgrid = np.linspace(0.1, 0.9, 9)
        # candidate thresholds depend only on X — one vectorised pass
        cand_thrs = np.quantile(X, qgrid, axis=0)  # [Q, F]
        for t in range(self.n_trees):
            codes = np.zeros(len(X), np.int64)
            for lvl in range(self.depth):
                best = (None, None, np.inf)
                for f in range(self.n_features):
                    for qi in range(len(qgrid)):
                        thr = cand_thrs[qi, f]
                        cand = codes * 2 + (X[:, f] > thr)
                        # SSE after assigning mean residual per candidate leaf
                        sums = np.bincount(cand, weights=resid,
                                           minlength=2 ** (lvl + 1))
                        cnts = np.bincount(cand, minlength=2 ** (lvl + 1))
                        means = sums / np.maximum(cnts, 1)
                        sse = np.sum((resid - means[cand]) ** 2)
                        if sse < best[2]:
                            best = (f, thr, sse)
                feats[t, lvl], thrs[t, lvl] = best[0], best[1]
                codes = codes * 2 + (X[:, feats[t, lvl]] > thrs[t, lvl])
            sums = np.bincount(codes, weights=resid, minlength=2 ** self.depth)
            cnts = np.bincount(codes, minlength=2 ** self.depth)
            leaf_vals = self.lr * sums / np.maximum(cnts, 1)
            leaves[t] = leaf_vals
            resid = resid - leaf_vals[codes]
        return feats, thrs, leaves, base

    def init_state(self, rng):
        nprng = np.random.default_rng(self.seed)
        X, y = self._synthetic(nprng)
        feats, thrs, leaves, base = self.fit_arrays(X, y)
        return {
            "feat": jnp.asarray(feats, jnp.int32),         # [T, d]
            "thresh": jnp.asarray(thrs, jnp.float32),      # [T, d]
            "leaves": jnp.asarray(leaves, jnp.float32),    # [T, 2^d]
            "base": jnp.asarray(base, jnp.float32),
        }

    # -- inference (pure, jit-traceable, MXU-friendly) ----------------------

    def predict(self, state, X):
        X = X.astype(jnp.float32)                           # [B, F]
        gathered = X[:, state["feat"].reshape(-1)]          # [B, T*d]
        B = X.shape[0]
        T, d = state["feat"].shape
        bits = (
            gathered.reshape(B, T, d) > state["thresh"][None, :, :]
        ).astype(jnp.int32)                                 # [B, T, d]
        weights = 2 ** jnp.arange(d - 1, -1, -1, dtype=jnp.int32)
        codes = jnp.sum(bits * weights[None, None, :], axis=-1)  # [B, T]
        onehot = jax.nn.one_hot(codes, 2 ** d, dtype=jnp.float32)  # [B,T,2^d]
        per_tree = jnp.einsum("btl,tl->bt", onehot, state["leaves"])
        return (state["base"] + per_tree.sum(axis=1))[:, None]
