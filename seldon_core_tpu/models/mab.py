"""Epsilon-greedy multi-armed-bandit router — parity with the reference's
canonical ROUTER example (examples/routers/epsilon_greedy/EpsilonGreedy.py:12-61):

  * ``route``: with probability 1-epsilon exploit the best branch, otherwise
    explore uniformly among the *other* branches (the reference never
    explores the current best).
  * ``send_feedback``: reward in [0,1] over a batch of n rows counts as
    ``int(reward*n)`` successes / rest failures on the routed branch; the
    best branch is argmax of Laplace-smoothed success ratio
    ``(success+1)/(tries+1)``.

TPU-native: all state (success/tries counters + PRNG key) is an explicit
pytree; route and feedback are pure and traceable, so the whole bandit runs
inside the compiled graph program and online learning is an on-device state
transition replayed from ``meta.routing`` (engine PredictiveUnitBean.java:141-149).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from seldon_core_tpu.graph.units import Unit, UnitAux, register_unit

__all__ = ["EpsilonGreedyRouter"]


@register_unit("EpsilonGreedyRouter")
class EpsilonGreedyRouter(Unit):
    def __init__(self, n_branches: int = None, epsilon: float = 0.1, seed: int = 0):
        if n_branches is None:
            raise ValueError("n_branches parameter must be given")
        self.n = int(n_branches)
        self.epsilon = float(epsilon)
        self.seed = int(seed)

    def init_state(self, rng):
        if rng is None:
            rng = jax.random.key(self.seed)
        return {
            "success": jnp.zeros((self.n,), jnp.float32),
            "tries": jnp.zeros((self.n,), jnp.float32),
            "key": rng,
        }

    def _best(self, state):
        return jnp.argmax((state["success"] + 1.0) / (state["tries"] + 1.0)).astype(
            jnp.int32
        )

    def route(self, state, X):
        key, k_explore, k_choice = jax.random.split(state["key"], 3)
        best = self._best(state)
        # uniform pick among branches != best:
        # draw in [0, n-2] and shift past `best`
        other = jax.random.randint(k_choice, (), 0, max(self.n - 1, 1), jnp.int32)
        other = other + (other >= best).astype(jnp.int32)
        explore = jax.random.uniform(k_explore) <= self.epsilon
        branch = jnp.where(explore, other, best)
        return branch, UnitAux(state={**state, "key": key})

    def send_feedback(self, state, X, branch, reward, truth):
        branch = jnp.asarray(branch, jnp.int32)  # host mode passes python ints
        n_rows = jnp.float32(X.shape[0]) if X is not None else jnp.float32(1.0)
        n_success = jnp.floor(reward * n_rows)
        onehot = jax.nn.one_hot(branch, self.n, dtype=jnp.float32)
        # branch may be -1 (feedback without recorded routing): no-op then
        valid = (branch >= 0).astype(jnp.float32)
        return {
            "success": state["success"] + valid * onehot * n_success,
            "tries": state["tries"] + valid * onehot * n_rows,
            "key": state["key"],
        }
