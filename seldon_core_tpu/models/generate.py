"""Autoregressive decoding with a KV cache — LLM-style serving through the
same graph engine.

The reference predates sequence models entirely (SURVEY.md §5); this module
makes generation a first-class graph workload: ``TransformerGenerator`` is
a MODEL unit whose ``predict`` maps prompt token rows to generated token
rows, so a deployment JSON serves text continuation over the identical
REST/gRPC data plane as every other model.

TPU-shaped decoding:
  * the whole decode loop is ONE ``lax.scan`` inside jit — no Python
    per-token dispatch, no host round-trips between steps;
  * TWO-TIER KV cache: the prompt's K/V live in a read-only MAIN cache
    (``[B, KV, S, hd]``, grouped heads), new tokens write a chunk-sized
    buffer, and attention softmaxes over the concatenated scores.
    Measured motivation (v5e, B=256): mutating a large cache inside the
    scan cost ~200 us per ``dynamic_update_slice`` plus ~2 ms/step of
    layout copies — XLA cannot keep a big while-loop carry in place —
    while the two-tier step runs the same attention at ~1/3 the time;
  * chunks fold into main at most once per ``GEN/STREAM_CHUNK_CAP``
    tokens via a donated (in-place) bulk merge; generations that fit one
    chunk keep main PROMPT-SIZED and never mask or merge at all;
  * optional int8 cache (``LMConfig.kv_quant``): per-token-per-head
    scales, convert fused into the score/PV dot reads;
  * greedy (temperature=0) or sampled decoding via ``jax.random`` keys
    threaded through the scan carry.
"""

from __future__ import annotations

import itertools
import logging
import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from seldon_core_tpu.graph.units import Unit, UnitAux, register_unit
from seldon_core_tpu.utils.telemetry import RECORDER

logger = logging.getLogger(__name__)

_stream_counter = itertools.count()  # per-process sampled-stream key source
from seldon_core_tpu.models.transformer import (
    LMConfig,
    _attention,
    _ffn,
    _rmsnorm,
    apply_rope,
    lm_init,
)

_warned_prefix_flash = False  # one-time flash-vs-prefix warning latch


def _resolve_prefix_flash(prefix, use_flash: bool) -> bool:
    """The shared-prefix path has no flash kernel: the suffix prefill is a
    causal SEGMENT (mid-sequence offsets + cache-wide attention) the fused
    kernel cannot mask.  Rather than warning and letting the caller think
    flash applied, resolve the EFFECTIVE flash setting here: with a prefix
    active, warn once and return False — the safe unfused segment path —
    so every downstream site (plain prefill included) branches on one
    answer instead of re-deriving the hazard.  Decode is unaffected either
    way (the two-tier/paged paths never use flash)."""
    if prefix is None or not use_flash:
        return use_flash
    global _warned_prefix_flash
    if not _warned_prefix_flash:
        _warned_prefix_flash = True
        logger.warning(
            "prefix cache active with use_flash=True: falling back to the "
            "unfused causal-segment suffix prefill (no flash kernel for "
            "causal segments); long suffixes pay O((P+S)*S) unfused "
            "attention"
        )
    return False


def _eager(x) -> bool:
    """True when ``x`` is a concrete array — i.e. we are executing, not
    being traced into someone's jit.  Telemetry must only record on
    execution: a traced ``time.perf_counter()`` would bake trace-time
    constants into the program."""
    return not isinstance(x, jax.core.Tracer)

__all__ = ["init_cache", "init_chunk", "prefill", "decode_step",
           "generate", "stream_chunks", "sample_token", "mask_after_eos",
           "build_prefix_main",
           "init_block_pool", "paged_forward", "paged_decode_round",
           "paged_spec_round", "paged_write_prefix_blocks",
           "paged_write_prefix_tail",
           "TransformerGenerator"]


def init_cache(cfg: LMConfig, batch: int, max_len: int) -> Dict[str, Any]:
    # K/V stored at the GROUPED head count (cfg.kv_heads): with GQA the
    # cache — the HBM stream every decode step pays for — shrinks by
    # n_heads/n_kv_heads.  NOT rounded up to the flash-decode block: that
    # kernel is unwired (measured slower, see ops/flash_decode.py), and
    # padding would bill every decode step for masked slots.
    # kv_quant="int8" stores int8 values + per-token-per-head f32 scales
    # ([B, KV, L] — ~6% size overhead at hd=64), halving the stream.
    hd = cfg.d_model // cfg.n_heads
    kv = cfg.kv_heads

    def layer():
        if cfg.kv_quant == "int8":
            return {
                "k": jnp.zeros((batch, kv, max_len, hd), jnp.int8),
                "v": jnp.zeros((batch, kv, max_len, hd), jnp.int8),
                "k_s": jnp.zeros((batch, kv, max_len), jnp.float32),
                "v_s": jnp.zeros((batch, kv, max_len), jnp.float32),
            }
        return {
            "k": jnp.zeros((batch, kv, max_len, hd), cfg.dtype),
            "v": jnp.zeros((batch, kv, max_len, hd), cfg.dtype),
        }

    return {f"l{i}": layer() for i in range(cfg.n_layers)}


def init_chunk(cfg: LMConfig, batch: int, cap: int) -> Dict[str, Any]:
    """Decode chunk buffer — same layout as init_cache, named for the
    role.  Round-5 restructures (stacked all-layer buffers, position-
    major scales, unrolled sub-scans with straight-line merges, a Pallas
    aliased writer) all measured SLOWER than this layout; see
    scripts/probe_step_profile.py and docs/benchmarking.md for the
    numbers and the while-carry dus serialization analysis."""
    return init_cache(cfg, batch, cap)


def _quantize_kv(t):
    """t [B, KV, S, hd] float -> (int8 values, f32 scales [B, KV, S]).

    Symmetric per-token-per-head absmax — one scale per cache position, so
    the score/PV dots recover it as a rank-1 broadcast over the length
    axis (no per-element dequant tensor ever materialises)."""
    t32 = t.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(t32), axis=-1)
    scales = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(
        jnp.round(t32 / scales[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scales


def _heads(t, B, S, H, hd):
    return t.reshape(B, S, H, hd).transpose(0, 2, 1, 3)


def sanitize_prompt(X, vocab: int):
    """Float wire rows -> int32 token ids in [0, vocab).

    nan_to_num then clip in float space BEFORE the cast: float->int32 of
    NaN or out-of-range values is implementation-defined in XLA (wrap vs
    saturate varies by backend); after this chain the cast input is always
    a finite value in range."""
    return jnp.clip(jnp.nan_to_num(X), 0, vocab - 1).astype(jnp.int32)


def _grouped_qk(q, cache_k, k_s=None):
    """q [B,H,S,hd] x cache_k [B,KV,L,hd] -> scores [B,KV,g,S,L] f32.

    The group axis folds into the dot_general row axis so K streams from
    HBM once at its stored (grouped) size — decode is HBM-bound on exactly
    this stream, and with GQA it is n_heads/n_kv_heads smaller.  Reads use
    the stored dtype with f32 accumulation via ``preferred_element_type``;
    an explicit .astype(f32) would materialise a second, larger copy of
    the cache every step.  Int8 caches (``k_s`` [B,KV,L] scales) cast
    inside the dot — XLA fuses the convert into the weight-side read, the
    dequant_matmul trick — and the per-position scale multiplies the f32
    SCORES (a rank-1 broadcast over L), never the cache."""
    B, H, S, hd = q.shape
    KV, L = cache_k.shape[1], cache_k.shape[2]
    g = H // KV
    scale = jnp.float32(1.0 / (hd ** 0.5))
    k = cache_k.astype(q.dtype) if cache_k.dtype == jnp.int8 else cache_k
    s = jax.lax.dot_general(
        q.reshape(B, KV, g * S, hd), k,
        (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    ) * scale
    s = s.reshape(B, KV, g, S, L)
    if k_s is not None:
        s = s * k_s[:, :, None, None, :]
    return s


def _grouped_pv(p, cache_v, out_shape, out_dtype, v_s=None):
    """p [B,KV,g,S,L] x cache_v [B,KV,L,hd] -> [B,H,S,hd] ``out_dtype``.

    Int8 caches fold the per-position scale into p BEFORE the dot
    (out = (p * v_s) @ v_q): p is [*, L]-shaped so the scale is a cheap
    broadcast there, while scaling V would rebuild a full-size float
    cache copy."""
    B, KV, g, S, L = p.shape
    if v_s is not None:
        p = p * v_s[:, :, None, None, :]
    v = (cache_v.astype(out_dtype)
         if cache_v.dtype == jnp.int8 else cache_v)
    out = jax.lax.dot_general(
        p.astype(out_dtype).reshape(B, KV, g * S, L), v,
        (((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    ).astype(out_dtype)
    return out.reshape(out_shape)


def _pv_f32(p, cache_v, v_s=None):
    """p [B,KV,g,S,L] x cache_v [B,KV,L,hd] -> f32 [B,KV,g*S,hd] partial
    attention output (un-cast so two-tier partials add exactly).

    The dot's input dtype follows the CACHE dtype: bf16 only for bf16 or
    int8 caches — an f32-dtype model keeps f32 weights so its greedy
    ties break identically to prefill/naive decode."""
    B, KV, g, S, L = p.shape
    if v_s is not None:
        p = p * v_s[:, :, None, None, :]
    ct = (jnp.bfloat16 if cache_v.dtype in (jnp.int8, jnp.bfloat16)
          else cache_v.dtype)
    v = cache_v.astype(ct) if cache_v.dtype == jnp.int8 else cache_v
    return jax.lax.dot_general(
        p.astype(ct).reshape(B, KV, g * S, L), v,
        (((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    )


def _attend_two_tier(q, main_layer, chunk_layer, n_main, n_chunk,
                     main_full: bool = False):
    """q [B,H,1,hd] over (frozen main cache)[:n_main] + (chunk
    buffer)[:n_chunk]: one softmax over the concatenated scores, partial
    PV dots summed in f32.

    THE decode-hot-loop formulation: profiling the single-tier scan on
    v5e showed ~half of every step going to dynamic_update_slice on the
    big cache plus ~2 ms/step of layout copies — XLA cannot keep a
    mutated while-loop carry in place at this size.  Keeping the big
    cache READ-ONLY inside the scan and writing only a chunk-sized
    buffer measured 144 us/layer-step vs ~960 us (B=256, L=640; see
    scripts/probe_dus.py and docs/benchmarking.md).

    ``main_full`` (static): caller guarantees every main slot is valid
    (n_main == main length) — skips the validity select, which profiling
    showed streaming the whole f32 score tensor twice per layer
    (bitcast_select_fusion, ~1.2 ms/step at B=256).  The single-chunk
    serving path (prompt-sized main) always qualifies.

    Two score-stream economies (profiled round 5, B=256 — together
    bf16 4.18 -> 3.98 ms/step, int8kv 3.29 -> 3.10):
      * validity masks are ADDED (0 / -1e30) instead of selected —
        jnp.where materialised as its own fusion re-streaming the f32
        chunk scores (~22 us/layer), an add joins the exp chain;
      * the softmax normalisation happens AFTER the PV dots: partial PV
        runs on unnormalised exp weights (globally max-shifted, so in
        [0, 1] like p) and the division by the sum touches only the
        [B, H, 1, hd] output — dividing p re-streamed the full score
        tensor per layer (divide_convert fusions, ~8 us/layer)."""
    sm = _grouped_qk(q, main_layer["k"], main_layer.get("k_s"))
    sc = _grouped_qk(q, chunk_layer["k"], chunk_layer.get("k_s"))
    C = chunk_layer["k"].shape[2]
    if not main_full:
        Lm = main_layer["k"].shape[2]
        sm = sm + jnp.where(jnp.arange(Lm) < n_main, 0.0, -1e30
                            ).astype(jnp.float32)[None, None, None, None, :]
    sc = sc + jnp.where(jnp.arange(C) < n_chunk, 0.0, -1e30
                        ).astype(jnp.float32)[None, None, None, None, :]
    m = jnp.maximum(jnp.max(sm, axis=-1), jnp.max(sc, axis=-1))
    em = jnp.exp(sm - m[..., None])
    ec = jnp.exp(sc - m[..., None])
    l = jnp.sum(em, axis=-1) + jnp.sum(ec, axis=-1)  # [B,KV,g,S]
    om = _pv_f32(em, main_layer["v"], main_layer.get("v_s"))
    oc = _pv_f32(ec, chunk_layer["v"], chunk_layer.get("v_s"))
    B, KV, g, S = m.shape
    out = (om + oc) / l.reshape(B, KV, g * S)[..., None]
    return out.astype(q.dtype).reshape(q.shape)


def _block_two_tier(lp, x, main_layer, chunk_layer, n_main, n_chunk,
                    cfg: LMConfig, main_full: bool = False):
    """One decoder block for a single cached step: K/V written into the
    CHUNK buffer at slot ``n_chunk`` (the big cache is never touched),
    attention over main[:n_main] + chunk[:n_chunk+1].  Global position of
    this token is n_main + n_chunk."""
    from seldon_core_tpu.ops.quant import lm_matmul

    B, S, D = x.shape  # S == 1
    hd = cfg.d_model // cfg.n_heads
    kv_h = cfg.kv_heads
    h = _rmsnorm(x, lp["ln1"])
    qkv = lm_matmul(lp, "wqkv", h, out_dtype=x.dtype)
    q, k, v = jnp.split(qkv, [D, D + kv_h * hd], axis=-1)
    q = _heads(q, B, S, cfg.n_heads, hd)
    k = _heads(k, B, S, kv_h, hd)
    v = _heads(v, B, S, kv_h, hd)
    if cfg.rope:
        positions = n_main + n_chunk + jnp.arange(S)
        q = apply_rope(q, positions, cfg.rope_base)
        k = apply_rope(k, positions, cfg.rope_base)
    if chunk_layer["k"].dtype == jnp.int8:
        k_w, k_sw = _quantize_kv(k)
        v_w, v_sw = _quantize_kv(v)
        new_chunk = {
            "k": jax.lax.dynamic_update_slice(
                chunk_layer["k"], k_w, (0, 0, n_chunk, 0)),
            "v": jax.lax.dynamic_update_slice(
                chunk_layer["v"], v_w, (0, 0, n_chunk, 0)),
            "k_s": jax.lax.dynamic_update_slice(
                chunk_layer["k_s"], k_sw, (0, 0, n_chunk)),
            "v_s": jax.lax.dynamic_update_slice(
                chunk_layer["v_s"], v_sw, (0, 0, n_chunk)),
        }
    else:
        new_chunk = {
            "k": jax.lax.dynamic_update_slice(
                chunk_layer["k"], k.astype(chunk_layer["k"].dtype),
                (0, 0, n_chunk, 0)),
            "v": jax.lax.dynamic_update_slice(
                chunk_layer["v"], v.astype(chunk_layer["v"].dtype),
                (0, 0, n_chunk, 0)),
        }
    a = _attend_two_tier(q, main_layer, new_chunk, n_main, n_chunk + 1,
                         main_full)
    a = a.transpose(0, 2, 1, 3).reshape(B, S, D)
    x = x + lm_matmul(lp, "wo", a, out_dtype=x.dtype)
    h = _rmsnorm(x, lp["ln2"])
    y, _lb = _ffn(lp, h, cfg, mesh=None)
    return x + y, new_chunk


def decode_step_two_tier(params, token, main, chunk, n_main, n_chunk,
                         cfg: LMConfig, main_full: bool = False):
    """One cached step against (frozen main, growing chunk).  token [B]
    -> (logits [B, V], chunk')."""
    x = params["embed"][token][:, None, :]
    for i in range(cfg.n_layers):
        x, chunk[f"l{i}"] = _block_two_tier(
            params[f"l{i}"], x, main[f"l{i}"], chunk[f"l{i}"],
            n_main, n_chunk, cfg, main_full,
        )
    x = _rmsnorm(x, params["ln_f"])
    return (x[:, 0, :] @ params["embed"].T).astype(jnp.float32), chunk


def merge_chunk(main, chunk, n_main, cfg: LMConfig):
    """Fold a (full or partial) chunk buffer into the main cache at
    position ``n_main``.  Callers jit this with the main (and chunk)
    buffers DONATED — measured in-place on v5e, i.e. dispatch-cost only;
    run OUTSIDE the decode scan, once per chunk."""
    out = {}
    for i in range(cfg.n_layers):
        ml, cl = main[f"l{i}"], chunk[f"l{i}"]
        layer = {
            "k": jax.lax.dynamic_update_slice(
                ml["k"], cl["k"].astype(ml["k"].dtype), (0, 0, n_main, 0)),
            "v": jax.lax.dynamic_update_slice(
                ml["v"], cl["v"].astype(ml["v"].dtype), (0, 0, n_main, 0)),
        }
        if "k_s" in ml:
            layer["k_s"] = jax.lax.dynamic_update_slice(
                ml["k_s"], cl["k_s"], (0, 0, n_main))
            layer["v_s"] = jax.lax.dynamic_update_slice(
                ml["v_s"], cl["v_s"], (0, 0, n_main))
        out[f"l{i}"] = layer
    return out


def _attend_cached(q, cache_layer, n_valid):
    """q [B,H,1,hd] against the (possibly grouped, possibly int8) cache
    layer {k, v, k_s?, v_s?}; positions >= n_valid (scalar) masked.

    Deliberately the grouped-XLA formulation: the fused Pallas
    flash-decode kernel (ops/flash_decode.py) was measured SLOWER here —
    a (B*KV, L/128) grid serializes tiny per-step dots where XLA runs
    the whole batch as a few large batched dots (see that module's
    docstring for numbers).  Keep the dots batched; revisit only with a
    batch-blocked kernel design."""
    s = _grouped_qk(q, cache_layer["k"], cache_layer.get("k_s"))
    valid = jnp.arange(cache_layer["k"].shape[2]) < n_valid  # [L]
    s = jnp.where(valid[None, None, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return _grouped_pv(p, cache_layer["v"], q.shape, q.dtype,
                       cache_layer.get("v_s"))


def _attend_cached_causal(q, cache_layer, start):
    """q [B,H,S,hd] for global positions start..start+S-1 over the cache:
    query i may see cache positions <= start + i (speculative segments)."""
    S = q.shape[2]
    s = _grouped_qk(q, cache_layer["k"], cache_layer.get("k_s"))
    qpos = start + jnp.arange(S)[:, None]
    kpos = jnp.arange(cache_layer["k"].shape[2])[None, :]
    mask = kpos <= qpos  # [S, L]
    s = jnp.where(mask[None, None, None, :, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return _grouped_pv(p, cache_layer["v"], q.shape, q.dtype,
                       cache_layer.get("v_s"))


def _block_cached(lp, x, cache_layer, start, n_valid, cfg: LMConfig,
                  use_flash: bool = False, segment: bool = False):
    """One decoder block writing K/V into the cache at ``start`` and
    attending over cache[:n_valid].  x [B,S,D]; returns (x', cache_layer').
    S > 1 with ``segment=False`` means prefill from position 0; with
    ``segment=True`` a mid-sequence continuation at traced offset ``start``
    attending causally over the cache; S == 1 is a cached decode step."""
    from seldon_core_tpu.ops.quant import lm_matmul

    B, S, D = x.shape
    hd = cfg.d_model // cfg.n_heads
    kv_h = cfg.kv_heads
    h = _rmsnorm(x, lp["ln1"])
    qkv = lm_matmul(lp, "wqkv", h, out_dtype=x.dtype)
    q, k, v = jnp.split(qkv, [D, D + kv_h * hd], axis=-1)
    q = _heads(q, B, S, cfg.n_heads, hd)
    k = _heads(k, B, S, kv_h, hd)
    v = _heads(v, B, S, kv_h, hd)
    if cfg.rope:
        # rotate with GLOBAL positions before the cache write, so stored
        # keys are final and cached attention needs no re-rotation
        positions = start + jnp.arange(S)
        q = apply_rope(q, positions, cfg.rope_base)
        k = apply_rope(k, positions, cfg.rope_base)
    whole = (not segment and S == cache_layer["k"].shape[2])
    if cache_layer["k"].dtype == jnp.int8:
        k_w, k_sw = _quantize_kv(k)
        v_w, v_sw = _quantize_kv(v)
        if whole:
            # prompt-sized cache (single-chunk serving): the fresh K/V ARE
            # the cache — a dus into same-sized zeros is a pure copy, and
            # dus on large buffers measured ~200 us each on v5e
            new_cache = {"k": k_w, "v": v_w, "k_s": k_sw, "v_s": v_sw}
        else:
            new_cache = {
                "k": jax.lax.dynamic_update_slice(
                    cache_layer["k"], k_w, (0, 0, start, 0)),
                "v": jax.lax.dynamic_update_slice(
                    cache_layer["v"], v_w, (0, 0, start, 0)),
                "k_s": jax.lax.dynamic_update_slice(
                    cache_layer["k_s"], k_sw, (0, 0, start)),
                "v_s": jax.lax.dynamic_update_slice(
                    cache_layer["v_s"], v_sw, (0, 0, start)),
            }
    elif whole:
        new_cache = {"k": k.astype(cache_layer["k"].dtype),
                     "v": v.astype(cache_layer["v"].dtype)}
    else:
        new_cache = {
            "k": jax.lax.dynamic_update_slice(
                cache_layer["k"], k.astype(cache_layer["k"].dtype),
                (0, 0, start, 0)),
            "v": jax.lax.dynamic_update_slice(
                cache_layer["v"], v.astype(cache_layer["v"].dtype),
                (0, 0, start, 0)),
        }
    if segment:
        # mid-sequence continuation (speculative draft/verify): causal over
        # the whole cache with global position offsets (any S, traced start)
        a = _attend_cached_causal(q, new_cache, start)
    elif S > 1:
        # prefill: causal attention over the fresh k/v only — the cache
        # tail past S is all-masked zeros, no need to attend over it.
        # Reuses the LM's _attention (flash kernel when available, same
        # fallback numerics as lm_apply) so the two paths cannot drift;
        # int8 caches still prefill from the EXACT pre-quantization k/v.
        a = _attention(q, k, v, None, causal=True, use_flash=use_flash)
    else:
        a = _attend_cached(q, new_cache, n_valid)
    a = a.transpose(0, 2, 1, 3).reshape(B, S, D)
    x = x + lm_matmul(lp, "wo", a, out_dtype=x.dtype)
    h = _rmsnorm(x, lp["ln2"])
    y, _lb = _ffn(lp, h, cfg, mesh=None)  # dense or MoE FFN
    x = x + y
    return x, new_cache


def segment_forward(params, tokens, cache, start, cfg: LMConfig,
                    use_flash: bool = False, segment: bool = True,
                    last_only: bool = False):
    """Forward S tokens at global positions start.. over the cache
    (filling it); returns (logits [B, S, V] for EVERY position, cache').
    ``segment=False`` is the prefill special case (start must be 0).
    ``last_only`` unembeds ONLY the final position (returns [B, 1, V]):
    the unembed is ~20% of prefill FLOPs at real vocab sizes and a
    [B, S, V] f32 write besides — generation never reads the rest."""
    x = params["embed"][tokens]
    for i in range(cfg.n_layers):
        x, cache[f"l{i}"] = _block_cached(
            params[f"l{i}"], x, cache[f"l{i}"], start, tokens.shape[1], cfg,
            use_flash, segment,
        )
    if last_only:
        x = x[:, -1:, :]  # before the (positionwise) norm: same numerics
    x = _rmsnorm(x, params["ln_f"])
    return (x @ params["embed"].T).astype(jnp.float32), cache


def prefill(params, tokens, cache, cfg: LMConfig, use_flash: bool = False):
    """Consume the prompt in one pass, filling the cache.

    tokens [B, S_prompt] -> (last-position logits [B, V], cache')."""
    logits, cache = segment_forward(
        params, tokens, cache, 0, cfg, use_flash, segment=False,
        last_only=True,
    )
    return logits[:, -1, :], cache


def decode_step(params, token, cache, pos, cfg: LMConfig):
    """One cached step.  token [B] int32, pos scalar -> (logits [B,V],
    cache')."""
    x = params["embed"][token][:, None, :]  # [B,1,D]
    for i in range(cfg.n_layers):
        x, cache[f"l{i}"] = _block_cached(
            params[f"l{i}"], x, cache[f"l{i}"], pos, pos + 1, cfg
        )
    x = _rmsnorm(x, params["ln_f"])
    return (x[:, 0, :] @ params["embed"].T).astype(jnp.float32), cache


def build_prefix_main(prefix_cache, batch: int, total_len: int,
                      cfg: LMConfig):
    """Batched main cache [B, KV, total_len, hd] whose first P slots are
    a shared B=1 PREFIX cache broadcast across the batch — the serving
    trick for common system prompts: the prefix's K/V are computed once
    per deployment (init_state), so each request prefills only its
    suffix (prefill FLOPs drop by the prefix's share of S², which at
    long prefixes is most of them)."""
    out = {}
    for li, layer in prefix_cache.items():
        new_layer = {}
        for kk, vv in layer.items():
            P = vv.shape[2]
            pad_shape = list(vv.shape)
            pad_shape[0] = batch
            pad_shape[2] = total_len - P
            pref = jnp.broadcast_to(vv, (batch,) + vv.shape[1:])
            new_layer[kk] = jnp.concatenate(
                [pref, jnp.zeros(pad_shape, vv.dtype)], axis=2)
        out[li] = new_layer
    return out


#: generation chunk-buffer capacity: generations up to this length run
def sample_token(logits, key, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0):
    """[B, V] f32 logits -> [B] int32 next-token ids.

    All knobs are STATIC python values (jit caches one executable per
    sampling config): temperature <= 0 is greedy argmax; otherwise
    temperature-scaled sampling, optionally truncated to the ``top_k``
    highest logits and/or the top-p nucleus (the smallest set of tokens
    whose cumulative probability reaches ``top_p`` — always at least
    one).  Nucleus filtering sorts the [B, V] logits per step (~17
    bitonic passes over the row at V=32k — measurable but small next to
    the decode step's cache stream); top-k alone uses lax.top_k."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = (logits / temperature).astype(jnp.float32)
    if top_k and top_k > 0:
        # clamp: a deployment's top_k may exceed a small model's vocab,
        # and lax.top_k would raise at trace time inside the scan
        kk = min(int(top_k), logits.shape[-1])
        kth = jax.lax.top_k(logits, kk)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and 0.0 < top_p < 1.0:
        srt = jnp.sort(logits, axis=-1)[..., ::-1]      # descending
        probs = jax.nn.softmax(srt, axis=-1)
        mass_before = jnp.cumsum(probs, axis=-1) - probs
        keep = mass_before < top_p                       # >= 1 token
        cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _chunk_eos_mask(toks, seen_eos, eos_token: int):
    """Per-chunk after-eos masking with a carried latch — the DEVICE-side
    form of mask_after_eos for streaming: rows already stopped
    (``seen_eos`` [B] bool) are forced to eos wholesale, within-chunk
    positions after a fresh eos are forced to eos, and the latch is
    updated.  Returns (masked [B, n], seen_eos', all_done scalar).  The
    caller reads back ONLY the scalar ``all_done`` flag to drive the
    early-stop branch — the token chunk itself stays on device (the old
    host-side masking forced a full [B, n] readback per chunk, serializing
    the stream's device/host overlap)."""
    eos = jnp.int32(eos_token)
    t = jnp.where(seen_eos[:, None], eos, toks)
    is_eos = t == eos
    after = (jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
             - is_eos.astype(jnp.int32)) > 0
    t = jnp.where(after, eos, t)
    seen2 = seen_eos | is_eos.any(axis=1)
    return t, seen2, jnp.all(seen2)


_chunk_eos_mask_jit = jax.jit(_chunk_eos_mask, static_argnames=("eos_token",))


def mask_after_eos(toks, eos_token: int):
    """Force every position strictly AFTER a row's first ``eos_token``
    to eos: fixed-shape scans keep decoding past a stop token, so the
    serving contract is 'output is eos-padded after the stop'.  No-op
    when eos_token < 0 (disabled)."""
    if eos_token < 0:
        return toks
    is_eos = toks == eos_token
    after = (jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
             - is_eos.astype(jnp.int32)) > 0
    return jnp.where(after, jnp.int32(eos_token), toks)


#: with a prompt-sized main cache and ZERO merges; longer ones merge the
#: chunk into main once per CAP tokens (a donated-in-place bulk write)
GEN_CHUNK_CAP = 256


def generate(
    params,
    prompt,
    cfg: LMConfig,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    use_flash: bool = False,
    top_k: int = 0,
    top_p: float = 0.0,
    eos_token: int = -1,
    prefix: Optional[Dict[str, Any]] = None,
) -> jax.Array:
    """prompt [B, S] int32 -> generated [B, max_new_tokens] int32.

    Greedy when temperature == 0 (a static python branch), else sampled
    (optionally top-k / nucleus truncated — sample_token); rows that
    emit ``eos_token`` are eos-padded afterwards (mask_after_eos).

    ``prefix``: optional B=1 prefix KV cache (build it once with
    prefill at B=1; its length is its own shape).  The request then
    prefills only its suffix (``prompt`` holds the suffix tokens)
    against the broadcast prefix via the causal segment path; decode is
    unchanged.  Positions are global, so outputs equal generating over
    the concatenated sequence EXACTLY for float caches; with
    ``kv_quant="int8"`` the prefix is read back quantized where a full
    prefill attends pre-quantization k/v, so near-tie argmaxes may
    differ (same class as every int8-KV read-back).  NOTE: prefix mode
    DISABLES flash for the suffix prefill — the causal-segment attend
    (mid-sequence offsets over the whole cache) has no flash kernel, so
    ``use_flash=True`` is ignored there with a one-time warning; plain
    (no-prefix) prefill still uses the flash kernel when available.

    Telemetry (eager calls only — traced calls skip; see _eager):
    time-to-first-token and whole-call tokens/sec land in the flight
    recorder (``seldon_tpu_ttft_seconds`` /
    ``seldon_tpu_decode_tokens_per_second``).  TTFT costs ONE host sync
    at the prefill boundary — the decode scan depends on the first token
    anyway, so no device idle is added, only the host-side enqueue
    overlap of one dispatch.
    Decode runs the TWO-TIER cache: the prefilled main cache is read-only
    inside the scan (mutating a large while-loop carry measured ~10x the
    logical write cost in dus + layout copies — see _attend_two_tier),
    new K/V land in a chunk buffer, merged into main between scans only
    when max_new_tokens exceeds GEN_CHUNK_CAP."""
    B, S = prompt.shape
    P = 0 if prefix is None else prefix["l0"]["k"].shape[2]
    eager = _eager(prompt)
    t0 = time.perf_counter() if eager else 0.0
    use_flash = _resolve_prefix_flash(prefix, use_flash)
    chunked = max_new_tokens - 1 > GEN_CHUNK_CAP
    # single-chunk generations never merge, so main holds ONLY the prompt
    # — decode then streams P+S cache slots, not P+S+max_new masked ones
    main_len = P + S + max_new_tokens if chunked else P + S
    if prefix is None:
        main = init_cache(cfg, B, main_len)
        logits, main = prefill(params, prompt, main, cfg, use_flash)
    else:
        # suffix-prefill against a cache sized EXACTLY P+S (the causal
        # segment dots stream the whole buffer, so pre-sizing to
        # main_len would bill every suffix position for max_new dead
        # slots); chunked mode pads up to main_len afterwards, once
        main = build_prefix_main(prefix, B, P + S, cfg)
        logits, main = segment_forward(
            params, prompt, main, P, cfg, segment=True, last_only=True)
        logits = logits[:, -1, :]
        if main_len > P + S:
            main = {
                li: {
                    kk: jnp.concatenate(
                        [vv, jnp.zeros(
                            vv.shape[:2] + (main_len - P - S,)
                            + vv.shape[3:], vv.dtype)], axis=2)
                    for kk, vv in layer.items()
                }
                for li, layer in main.items()
            }
    if rng is None:
        rng = jax.random.key(0)

    key0, rng = jax.random.split(rng)
    first = sample_token(logits, key0, temperature, top_k, top_p)
    if eager:
        # the decode scan depends on `first` anyway — blocking here adds
        # no device idle, just surfaces the true prefill latency
        jax.block_until_ready(first)
        RECORDER.observe_ttft(time.perf_counter() - t0)
        RECORDER.set_kv_slots(
            active=B * (P + S), reserved=B * (main_len - P - S)
        )

    def scan_steps(main, n_main, token, key, n, cap):
        # n_main is a python int here: slice the valid prefix statically,
        # so the scan neither streams nor masks the unwritten tail and
        # the validity select disappears (main_full)
        if main["l0"]["k"].shape[2] > n_main:
            main = {
                li: {kk: vv[:, :, :n_main] for kk, vv in layer.items()}
                for li, layer in main.items()
            }
        chunk = init_chunk(cfg, B, cap)
        # one scan body for one-shot and streamed decoding — the
        # stream-equals-generate contract rests on this delegation
        toks, (token, chunk, _, key) = _chunk_step(
            params, token, main, chunk, jnp.int32(n_main), jnp.int32(0),
            key, cfg, n, temperature, main_full=True,
            top_k=top_k, top_p=top_p,
        )
        return toks, chunk, token, key

    # first token came from prefill; the scans emit the remaining N-1 (no
    # wasted final forward whose logits would be discarded)
    out = [first[:, None]]
    token, key = first, rng
    n_main, remaining = P + S, max_new_tokens - 1
    while remaining > 0:
        n = min(remaining, GEN_CHUNK_CAP) if chunked else remaining
        toks, chunk, token, key = scan_steps(
            main, n_main, token, key, n, GEN_CHUNK_CAP if chunked else n
        )
        out.append(toks)
        remaining -= n
        if remaining > 0:  # fold the finished chunk in before the next
            main = merge_chunk(main, chunk, n_main, cfg)
            n_main += n
    result = mask_after_eos(
        jnp.concatenate(out, axis=1), eos_token)  # [B, max_new]
    if eager:
        # block before timing: serving callers materialize next anyway
        jax.block_until_ready(result)
        elapsed = time.perf_counter() - t0
        if elapsed > 0:
            RECORDER.observe_decode_rate(B * max_new_tokens / elapsed)
    return result


def _chunk_step(params, token, main, chunk_buf, n_main, used, key,
                cfg: LMConfig, n: int, temperature: float,
                main_full: bool = False, top_k: int = 0,
                top_p: float = 0.0):
    """n cached decode steps as ONE jitted scan over the two-tier cache:
    main is READ-ONLY (see _attend_two_tier), new K/V go to ``chunk_buf``
    slots used..used+n-1.  Returns (tokens [B, n], (token, chunk_buf,
    used', key)).  The per-(B, n) executable is cached by jit, so a
    stream costs ceil(max_new/chunk) device dispatches regardless of
    length."""

    def step(carry, _):
        token, chunk_buf, used, key = carry
        key, sub = jax.random.split(key)
        logits, chunk_buf = decode_step_two_tier(
            params, token, main, chunk_buf, n_main, used, cfg, main_full
        )
        nxt = sample_token(logits, sub, temperature, top_k, top_p)
        return (nxt, chunk_buf, used + 1, key), nxt

    (token, chunk_buf, used, key), toks = jax.lax.scan(
        step, (token, chunk_buf, used, key), None, length=n
    )
    return toks.T, (token, chunk_buf, used, key)  # [B, n]


# chunk buffer DONATED across chunk dispatches (each SSE chunk would
# otherwise copy it in and out of the program); main is NOT donated — it
# is read-only and stays resident across every dispatch of a stream.
# Callers must treat the passed chunk_buf as consumed — stream_chunks
# reassigns it every iteration.
_chunk_step_jit = jax.jit(
    _chunk_step,
    static_argnames=("cfg", "n", "temperature", "main_full", "top_k",
                     "top_p"),
    donate_argnums=(3,),
)

def grow_merge(main, chunk, cfg: LMConfig, used: int):
    """Concatenate chunk[:used] onto main along the length axis, returning
    a main cache that is EXACTLY full (every slot valid).

    Streams use this instead of a dus into a max_new-sized preallocation:
    a big mostly-empty main would make every decode step pay the QK dot
    and validity select over unwritten slots (the bitcast_select_fusion
    cost, ~1.2 ms/step at B=256, the two-tier design exists to remove).
    The full-buffer copy here runs once per STREAM_CHUNK_CAP tokens —
    ~2 decode-steps' worth of HBM traffic amortised over 128 steps — and
    buys ``main_full=True`` on every step of arbitrarily long streams.

    Costs, stated plainly:
      * each merge grows main's length, so the NEXT chunk-scan is a new
        shape — one XLA compile per merge point.  Merge offsets are fixed
        for a given (B, S, chunk, cap), the serving engine pins max_new
        per deployment, and the persistent compile cache keeps them
        across restarts, so this is a one-time cost per deployment shape
        (the one-shot ``generate`` path has sliced main to n_main per
        chunk since round 4 — same shape-per-chunk property).  The
        steady-state alternative (fixed max_new-sized main) pays the
        mostly-empty select ~1.2 ms/EVERY step at B=256 instead;
      * concat cannot donate, so a merge transiently holds old+new main
        (~2x cache HBM) before GC frees the old one.  Streams whose KV
        cache approaches half of free HBM should lower max_new or batch
        instead of relying on this path."""
    out = {}
    for i in range(cfg.n_layers):
        ml, cl = main[f"l{i}"], chunk[f"l{i}"]
        layer = {
            "k": jnp.concatenate(
                [ml["k"], cl["k"][:, :, :used].astype(ml["k"].dtype)], axis=2),
            "v": jnp.concatenate(
                [ml["v"], cl["v"][:, :, :used].astype(ml["v"].dtype)], axis=2),
        }
        if "k_s" in ml:
            layer["k_s"] = jnp.concatenate(
                [ml["k_s"], cl["k_s"][:, :, :used]], axis=2)
            layer["v_s"] = jnp.concatenate(
                [ml["v_s"], cl["v_s"][:, :, :used]], axis=2)
        out[f"l{i}"] = layer
    return out


# shape-changing, so donation cannot alias outputs to inputs; freeing the
# old buffers immediately after is the caller's job (Python GC suffices)
_grow_merge_jit = jax.jit(grow_merge, static_argnames=("cfg", "used"))

#: stream chunk-buffer capacity (slots between merges)
STREAM_CHUNK_CAP = 128


def stream_chunks(params, prompt, cfg: LMConfig, max_new_tokens: int,
                  chunk: int = 8, temperature: float = 0.0,
                  rng: Optional[jax.Array] = None,
                  use_flash: bool = False, top_k: int = 0,
                  top_p: float = 0.0, eos_token: int = -1,
                  prefix=None):
    """Incremental decoding: yields token arrays [B, <=chunk] whose
    concatenation equals ``generate(...)`` token-for-token (same
    sampling semantics, same PRNG stream, same eos padding, same
    optional shared-prefix cache).

    With ``eos_token`` set, once EVERY row has emitted it the remaining
    chunks are host-generated eos padding — no further device work —
    and within-stream tokens after a row's first eos are masked to eos
    (the generate() contract).

    The host loop exists ONLY to surface tokens early — each iteration is
    one jitted scan over ``chunk`` two-tier cached steps, so the device
    work is the same one-scan-per-chunk shape serving wants; first token
    arrives after prefill + (chunk-1) steps instead of after
    max_new_tokens steps.  When the chunk buffer fills
    (STREAM_CHUNK_CAP), the host grows the main cache by the buffered
    tokens (grow_merge — main stays exactly full, so every step of a
    long stream decodes over valid slots only) and continues.

    With ``eos_token`` set, after-eos masking runs ON DEVICE
    (_chunk_eos_mask: a carried ``seen_eos`` latch jitted with the mask)
    and the host reads back only a scalar all-done flag per chunk to
    drive the early-stop branch — yielded chunks stay device arrays, so
    the consumer decides when to pay the readback.

    Telemetry (flight recorder): TTFT recorded at the first sampled
    token (one host sync at the prefill boundary — the first scan
    depends on that token anyway), tokens/sec over the whole stream at
    exhaustion, KV slot occupancy per merge."""
    B, S = prompt.shape
    t0 = time.perf_counter()
    cap = STREAM_CHUNK_CAP
    # a per-dispatch scan may not outgrow the chunk buffer: a larger
    # request would dus past the buffer (clamped to the last slot =
    # silent KV corruption).  Engine clients may ask up to 256.
    chunk = min(int(chunk), cap)
    # main starts prompt-sized and GROWS at each merge (grow_merge), so
    # it is exactly full at every decode step — long streams never pay
    # the mostly-empty-buffer QK dot + validity select
    P = 0 if prefix is None else prefix["l0"]["k"].shape[2]
    use_flash = _resolve_prefix_flash(prefix, use_flash)
    if prefix is None:
        main = init_cache(cfg, B, S)
        logits, main = prefill(params, prompt, main, cfg, use_flash)
    else:
        main = build_prefix_main(prefix, B, P + S, cfg)
        logits, main = segment_forward(
            params, prompt, main, P, cfg, segment=True, last_only=True)
        logits = logits[:, -1, :]
    if rng is None:
        rng = jax.random.key(0)
    key0, rng = jax.random.split(rng)
    first = sample_token(logits, key0, temperature, top_k, top_p)
    jax.block_until_ready(first)  # the first scan depends on it anyway
    RECORDER.observe_ttft(time.perf_counter() - t0)

    token, key = first, rng
    chunk_buf = init_chunk(cfg, B, cap)
    n_main, used = P + S, 0
    done = 0
    # per-row "has emitted eos" latch — DEVICE-side; the host sees only
    # the scalar all_done flag (one tiny readback per chunk instead of
    # the whole [B, chunk] token array)
    seen_eos = jnp.zeros((B,), bool)
    all_done = False

    def finalize(toks):
        nonlocal seen_eos, all_done
        if eos_token < 0:
            return toks
        toks, seen_eos, flag = _chunk_eos_mask_jit(
            toks, seen_eos, eos_token=eos_token
        )
        all_done = bool(flag)  # scalar readback drives the early stop
        return toks

    def emit(n):
        nonlocal token, key, chunk_buf, main, n_main, used
        if used + n > cap:  # grow main by the buffered tokens, continue
            main = _grow_merge_jit(main, chunk_buf, cfg=cfg, used=used)
            n_main += used
            chunk_buf = init_chunk(cfg, B, cap)
            used = 0
            RECORDER.set_kv_slots(
                active=B * n_main, reserved=B * cap
            )
        toks, (token, chunk_buf, _, key) = _chunk_step_jit(
            params, token, main, chunk_buf, jnp.int32(n_main),
            jnp.int32(used), key, cfg=cfg, n=n, temperature=temperature,
            # grow_merge keeps main exactly full at every step
            main_full=True, top_k=top_k, top_p=top_p,
        )
        used += n
        return toks

    # first chunk: the prefill token + (chunk-1) scanned steps
    n_first = min(chunk - 1, max_new_tokens - 1)
    if n_first > 0:
        yield finalize(jnp.concatenate([first[:, None], emit(n_first)],
                                       axis=1))
    else:
        yield finalize(first[:, None])
    done = 1 + n_first
    decoded = done  # device-decoded tokens only (host eos pads excluded)
    while done < max_new_tokens:
        n = min(chunk, max_new_tokens - done)
        if eos_token >= 0 and all_done:
            # every row is finished: pad from the host, skip the device
            yield jnp.full((B, n), jnp.int32(eos_token))
        else:
            yield finalize(emit(n))
            decoded += n
        done += n
    elapsed = time.perf_counter() - t0
    if elapsed > 0:
        # rate counts only device-decoded tokens — an early-stopped
        # stream's host-padded filler must not inflate the SLO histogram
        RECORDER.observe_decode_rate(B * decoded / elapsed)


# ---------------------------------------------------------------------------
# Paged KV-block cache — the continuous-batching serving lane
# (runtime/genserver.py drives these; see docs/operations.md "tuning the
# generation scheduler")
# ---------------------------------------------------------------------------
#
# The dense caches above are per-REQUEST: one [B, KV, L, hd] buffer sized
# for one request's batch and lifetime.  Continuous batching co-schedules
# sequences of different ages in one decode batch, so the cache becomes a
# process-wide POOL of fixed-size blocks ([num_blocks, block_size, KV, hd]
# per layer) and each sequence carries a BLOCK TABLE mapping its logical
# block i to a physical pool block.  Allocation/free/eviction and
# occupancy accounting are host-side (runtime/genserver.py BlockAllocator);
# the device side below is three programs:
#
#   * paged_forward      — W tokens of one-or-more rows at per-row offsets
#                          (chunked prefill AND the speculative verify pass)
#   * paged_decode_round — `span` single-token steps for the whole
#                          in-flight batch as ONE lax.scan (per-row
#                          positions, per-row sampling keys, on-device
#                          after-eos latch)
#   * paged_spec_round   — draft k+1 paged steps + one (k+1)-wide target
#                          verify + greedy acceptance (speculative decoding
#                          on the serving path)
#
# Reads GATHER the row's blocks into a position-ordered dense view
# (pool[tables] — the pure-XLA formulation of paged attention; a Pallas
# block-table kernel is future work, and the repo's flash-decode precedent
# says measure before fusing).  Writes SCATTER fresh K/V at
# (table[pos // bs], pos % bs) — the vLLM reshape_and_cache shape.  Block 0
# is a reserved SCRATCH block: masked rows and pad positions write there,
# so inactive slots never need a branch.


def init_block_pool(cfg: LMConfig, num_blocks: int, block_size: int
                    ) -> Dict[str, Any]:
    """Per-layer {k, v[, k_s, v_s]} pools shaped
    ``[num_blocks, block_size, KV, hd]``.  Block 0 is the scratch block —
    the allocator (runtime/genserver.py) hands out ids >= 1.  int8 pools
    carry per-position scale planes exactly like init_cache."""
    hd = cfg.d_model // cfg.n_heads
    kv = cfg.kv_heads
    # XLA:CPU has no native bf16 scatter: a bf16 pool pays TWO whole-pool
    # converts (bf16 -> f32 scatter -> bf16) around EVERY write, which
    # scales step cost with POOL size instead of batch size (measured:
    # 211 ms vs 6 ms per decode round at 1024 blocks).  CPU backends
    # store the pool f32; TPU/GPU keep the configured dtype (bf16 native,
    # half the HBM) — same degradation pattern as the quality observatory.
    dtype = cfg.dtype
    if dtype == jnp.bfloat16 and jax.default_backend() == "cpu":
        dtype = jnp.float32

    def layer():
        if cfg.kv_quant == "int8":
            return {
                "k": jnp.zeros((num_blocks, block_size, kv, hd), jnp.int8),
                "v": jnp.zeros((num_blocks, block_size, kv, hd), jnp.int8),
                "k_s": jnp.zeros((num_blocks, block_size, kv), jnp.float32),
                "v_s": jnp.zeros((num_blocks, block_size, kv), jnp.float32),
            }
        return {
            "k": jnp.zeros((num_blocks, block_size, kv, hd), dtype),
            "v": jnp.zeros((num_blocks, block_size, kv, hd), dtype),
        }

    return {f"l{i}": layer() for i in range(cfg.n_layers)}


def _paged_view(layer, tables):
    """Gather one layer's blocks into a dense position-ordered cache view:
    pool [N, bs, KV, hd] + tables [B, nblk] -> {k, v[, k_s, v_s]} with k/v
    [B, KV, nblk*bs, hd] — the _grouped_qk/_grouped_pv layout, so paged
    attention reuses the exact dot formulations the dense caches use."""
    out = {}
    for name in ("k", "v"):
        g = layer[name][tables]  # [B, nblk, bs, KV, hd]
        B, nblk, bs, KV, hd = g.shape
        out[name] = g.transpose(0, 3, 1, 2, 4).reshape(B, KV, nblk * bs, hd)
    for name in ("k_s", "v_s"):
        if name in layer:
            g = layer[name][tables]  # [B, nblk, bs, KV]
            B, nblk, bs, KV = g.shape
            out[name] = g.transpose(0, 3, 1, 2).reshape(B, KV, nblk * bs)
    return out


def _paged_write(layer, tables, pos, valid, k_new, v_new):
    """Scatter fresh K/V (``[B, KV, W, hd]``) into the pool at per-token
    (block, offset) targets: ``pos`` [B, W] global positions, resolved
    through each row's table.  ``valid`` [B, W] False routes the write to
    the scratch block 0 (masked rows / pad positions) — garbage lands in
    scratch, never in a live sequence's blocks.  int8 pools quantize here
    (per-token absmax, _quantize_kv) and scatter the scale planes too."""
    bs = layer["k"].shape[1]
    nblk = tables.shape[1]
    idx = jnp.clip(pos // bs, 0, nblk - 1)
    blk = jnp.take_along_axis(tables, idx, axis=1)  # [B, W]
    blk = jnp.where(valid, blk, 0)
    off = pos % bs
    out = dict(layer)
    if layer["k"].dtype == jnp.int8:
        k_q, k_s = _quantize_kv(k_new)
        v_q, v_s = _quantize_kv(v_new)
        out["k"] = layer["k"].at[blk, off].set(k_q.transpose(0, 2, 1, 3))
        out["v"] = layer["v"].at[blk, off].set(v_q.transpose(0, 2, 1, 3))
        out["k_s"] = layer["k_s"].at[blk, off].set(k_s.transpose(0, 2, 1))
        out["v_s"] = layer["v_s"].at[blk, off].set(v_s.transpose(0, 2, 1))
    else:
        out["k"] = layer["k"].at[blk, off].set(
            k_new.transpose(0, 2, 1, 3).astype(layer["k"].dtype))
        out["v"] = layer["v"].at[blk, off].set(
            v_new.transpose(0, 2, 1, 3).astype(layer["v"].dtype))
    return out


def _attend_paged(q, view, start):
    """q [B, H, W, hd] over a dense paged view; query i of row b sees
    positions <= start[b] + i (its own fresh K/V is already in the pool).
    Per-row ``start`` is what separates this from _attend_cached_causal:
    co-scheduled rows sit at different sequence lengths.  W == 1 with
    start == n_valid is exactly the cached decode mask (kpos <= n_valid)."""
    s = _grouped_qk(q, view["k"], view.get("k_s"))  # [B, KV, g, W, L]
    L = view["k"].shape[2]
    W = q.shape[2]
    qpos = start[:, None] + jnp.arange(W)[None, :]          # [B, W]
    allowed = jnp.arange(L)[None, None, :] <= qpos[:, :, None]  # [B, W, L]
    s = jnp.where(allowed[:, None, None, :, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return _grouped_pv(p, view["v"], q.shape, q.dtype, view.get("v_s"))


def _paged_block(lp, x, pool_layer, tables, start, valid, cfg: LMConfig):
    """One decoder block over the paged pool: K/V written at per-row
    positions start[b] + i (scratch-routed where ``valid`` is False),
    attention over each row's own blocks.  x [B, W, D]."""
    from seldon_core_tpu.ops.quant import lm_matmul

    B, W, D = x.shape
    hd = cfg.d_model // cfg.n_heads
    kv_h = cfg.kv_heads
    h = _rmsnorm(x, lp["ln1"])
    qkv = lm_matmul(lp, "wqkv", h, out_dtype=x.dtype)
    q, k, v = jnp.split(qkv, [D, D + kv_h * hd], axis=-1)
    q = _heads(q, B, W, cfg.n_heads, hd)
    k = _heads(k, B, W, kv_h, hd)
    v = _heads(v, B, W, kv_h, hd)
    positions = start[:, None] + jnp.arange(W)[None, :]  # [B, W] per-row
    if cfg.rope:
        q = apply_rope(q, positions, cfg.rope_base)
        k = apply_rope(k, positions, cfg.rope_base)
    pool_layer = _paged_write(pool_layer, tables, positions, valid, k, v)
    view = _paged_view(pool_layer, tables)
    a = _attend_paged(q, view, start)
    a = a.transpose(0, 2, 1, 3).reshape(B, W, D)
    x = x + lm_matmul(lp, "wo", a, out_dtype=x.dtype)
    h = _rmsnorm(x, lp["ln2"])
    y, _lb = _ffn(lp, h, cfg, mesh=None)
    return x + y, pool_layer


def paged_forward(params, tokens, pool, tables, start, width,
                  cfg: LMConfig, last_only: bool = True):
    """Forward W tokens per row at per-row offsets over the paged pool —
    chunked prefill (one prompt chunk at a time, decode never stalls for
    the whole prompt) and the speculative verify pass share this program.

    tokens [B, W] int32; start [B] per-row global offset of token 0;
    width [B] valid token count per row (positions past it are pad: their
    K/V go to scratch, their logits are garbage nobody reads).  Returns
    (logits, pool'): logits [B, V] at each row's LAST valid position when
    ``last_only`` (prefill needs only the next-token distribution — the
    unembed is ~20% of prefill FLOPs at real vocab sizes), else [B, W, V]
    for every position (the verify pass scores all of them)."""
    B, W = tokens.shape
    valid = jnp.arange(W)[None, :] < width[:, None]  # [B, W]
    x = params["embed"][tokens]
    for i in range(cfg.n_layers):
        x, pool[f"l{i}"] = _paged_block(
            params[f"l{i}"], x, pool[f"l{i}"], tables, start, valid, cfg
        )
    if last_only:
        idx = jnp.clip(width - 1, 0, W - 1)
        x = jnp.take_along_axis(
            x, jnp.broadcast_to(idx[:, None, None], (B, 1, x.shape[2])),
            axis=1,
        )  # [B, 1, D] — before the (positionwise) norm: same numerics
    x = _rmsnorm(x, params["ln_f"])
    logits = (x @ params["embed"].T).astype(jnp.float32)
    return (logits[:, 0, :] if last_only else logits), pool


def paged_decode_round(params, pool, tables, token, n_valid, active,
                       seen_eos, keys, cfg: LMConfig, *, span: int,
                       temperature: float, top_k: int, top_p: float,
                       eos_token: int):
    """``span`` cached decode steps for the whole in-flight batch as ONE
    lax.scan — the scheduler's unit of work between admission points.

    token [B] pending tokens; n_valid [B] per-row cache length; active [B]
    masks empty slots (their writes go to scratch, their samples are
    forced to 0); seen_eos [B] is the device-side after-eos latch (rows
    past their stop keep riding the scan but emit eos — the generate()
    output contract — until the host retires them at the round boundary);
    keys [B] per-ROW PRNG keys (sampled decoding must not couple co-batched
    requests the way a shared batch key does).  Returns
    (toks [B, span], pool', token', n_valid', seen_eos', keys')."""

    def step(carry, _):
        pool, token, n_valid, seen_eos, keys = carry
        x = params["embed"][token][:, None, :]
        for i in range(cfg.n_layers):
            x, pool[f"l{i}"] = _paged_block(
                params[f"l{i}"], x, pool[f"l{i}"], tables, n_valid,
                active[:, None], cfg,
            )
        x = _rmsnorm(x, params["ln_f"])
        logits = (x[:, 0, :] @ params["embed"].T).astype(jnp.float32)
        if temperature <= 0.0:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            split = jax.vmap(jax.random.split)(keys)  # [B, 2] keys
            keys = split[:, 0]
            nxt = jax.vmap(
                lambda lg, kk: sample_token(
                    lg[None, :], kk, temperature, top_k, top_p
                )[0]
            )(logits, split[:, 1])
        if eos_token >= 0:
            nxt = jnp.where(seen_eos, jnp.int32(eos_token), nxt)
            seen_eos = seen_eos | (nxt == eos_token)
        nxt = jnp.where(active, nxt, 0)
        n_valid = n_valid + active.astype(jnp.int32)
        return (pool, nxt, n_valid, seen_eos, keys), nxt

    (pool, token, n_valid, seen_eos, keys), toks = jax.lax.scan(
        step, (pool, token, n_valid, seen_eos, keys), None, length=span
    )
    return toks.T, pool, token, n_valid, seen_eos, keys


def paged_spec_round(t_params, d_params, t_pool, d_pool, t_tables,
                     d_tables, token, n_valid, active, t_cfg: LMConfig,
                     d_cfg: LMConfig, *, k: int):
    """One speculative draft/verify round over paged pools — speculative
    decoding composed with continuous batching (greedy, float KV, the
    speculative.py constraints).

    The paged layout makes this SIMPLER than speculative.py's round-
    aligned holes: pools are mutable buffers donated across rounds, so
    rejected candidates' K/V are just stale slots past ``n_valid`` that
    the next round overwrites before anything can attend them (attention
    masks at n_valid).  Draft runs k+1 single-token paged steps (the +1
    writes the last proposal's K/V so a fully-accepted round leaves no
    draft-cache hole — same trick as speculative.py), target verifies all
    k+1 positions in one paged_forward, and greedy acceptance takes the
    longest matched prefix plus the corrected token.  Returns
    (new_toks [B, k+1], gained [B], corrected [B], t_pool', d_pool'):
    row b's round output is new_toks[b, :gained[b]], its next pending
    token is corrected[b]."""
    B = token.shape[0]
    W = k + 1

    def dstep(carry, _):
        d_pool, tok, nv = carry
        x = d_params["embed"][tok][:, None, :]
        for i in range(d_cfg.n_layers):
            x, d_pool[f"l{i}"] = _paged_block(
                d_params[f"l{i}"], x, d_pool[f"l{i}"], d_tables, nv,
                active[:, None], d_cfg,
            )
        x = _rmsnorm(x, d_params["ln_f"])
        logits = (x[:, 0, :] @ d_params["embed"].T).astype(jnp.float32)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (d_pool, nxt, nv + 1), tok

    (d_pool, _, _), seg = jax.lax.scan(
        dstep, (d_pool, token, n_valid), None, length=W
    )
    seg = seg.transpose(1, 0)  # [B, W] = [pending, d1 .. dk]
    widths = jnp.where(active, jnp.int32(W), jnp.int32(0))
    t_logits, t_pool = paged_forward(
        t_params, seg, t_pool, t_tables, n_valid, widths, t_cfg,
        last_only=False,
    )
    t_argmax = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [B, W]
    draft = seg[:, 1:]  # [B, k]
    match = draft == t_argmax[:, :k]
    a = jnp.argmin(
        jnp.concatenate([match, jnp.zeros((B, 1), bool)], axis=1), axis=1
    )  # first mismatch; k if all matched
    corrected = jnp.take_along_axis(t_argmax, a[:, None], axis=1)[:, 0]
    padded = jnp.concatenate([draft, jnp.zeros((B, 1), jnp.int32)], axis=1)
    new_toks = jnp.where(
        jnp.arange(W)[None, :] < a[:, None], padded, corrected[:, None]
    )
    gained = jnp.where(active, a + 1, 0).astype(jnp.int32)
    return new_toks, gained, corrected, t_pool, d_pool


def paged_write_prefix_tail(pool, prefix, blk, cfg: LMConfig, *, p0: int):
    """Copy the shared-prefix TAIL (positions p0..P-1, the part that does
    not fill a whole block) into one private pool block ``blk`` at offsets
    0..r-1.  Full prefix blocks are written once and SHARED by block-table
    reference across every sequence (pinned in the allocator); the
    partially-filled boundary block must be private because the sequence's
    own tokens continue into it."""
    out = {}
    for li, layer in pool.items():
        pl = prefix[li]
        new = dict(layer)
        r = pl["k"].shape[2] - p0
        new["k"] = layer["k"].at[blk, 0:r].set(
            pl["k"][0, :, p0:, :].transpose(1, 0, 2).astype(
                layer["k"].dtype))
        new["v"] = layer["v"].at[blk, 0:r].set(
            pl["v"][0, :, p0:, :].transpose(1, 0, 2).astype(
                layer["v"].dtype))
        if "k_s" in layer:
            new["k_s"] = layer["k_s"].at[blk, 0:r].set(
                pl["k_s"][0, :, p0:].transpose(1, 0))
            new["v_s"] = layer["v_s"].at[blk, 0:r].set(
                pl["v_s"][0, :, p0:].transpose(1, 0))
        out[li] = new
    return out


def paged_write_prefix_blocks(pool, prefix, blocks, cfg: LMConfig):
    """Write the full-block part of a shared prefix into pool blocks
    ``blocks`` (a python list of block ids, len = P // block_size) — run
    ONCE per deployment; every admitted sequence then references these
    blocks through its table without copying."""
    bs = pool["l0"]["k"].shape[1]
    out = pool
    for j, blk in enumerate(blocks):
        seg = {}
        for li, layer in out.items():
            pl = prefix[li]
            new = dict(layer)
            lo = j * bs
            new["k"] = layer["k"].at[blk, 0:bs].set(
                pl["k"][0, :, lo:lo + bs, :].transpose(1, 0, 2).astype(
                    layer["k"].dtype))
            new["v"] = layer["v"].at[blk, 0:bs].set(
                pl["v"][0, :, lo:lo + bs, :].transpose(1, 0, 2).astype(
                    layer["v"].dtype))
            if "k_s" in layer:
                new["k_s"] = layer["k_s"].at[blk, 0:bs].set(
                    pl["k_s"][0, :, lo:lo + bs].transpose(1, 0))
                new["v_s"] = layer["v_s"].at[blk, 0:bs].set(
                    pl["v_s"][0, :, lo:lo + bs].transpose(1, 0))
            seg[li] = new
        out = seg
    return out


# pools are DONATED through every paged program: the scheduler owns exactly
# one live pool pytree per model and rebinds it after each dispatch, so XLA
# mutates the blocks in place instead of copying the whole pool per step
paged_forward_jit = jax.jit(
    paged_forward, static_argnames=("cfg", "last_only"), donate_argnums=(2,)
)
paged_decode_round_jit = jax.jit(
    paged_decode_round,
    static_argnames=("cfg", "span", "temperature", "top_k", "top_p",
                     "eos_token"),
    donate_argnums=(1,),
)
paged_spec_round_jit = jax.jit(
    paged_spec_round, static_argnames=("t_cfg", "d_cfg", "k"),
    donate_argnums=(2, 3),
)
paged_write_prefix_tail_jit = jax.jit(
    paged_write_prefix_tail, static_argnames=("cfg", "p0"),
    donate_argnums=(0,),
)
# blocks is a STATIC tuple: the loop unrolls into one fused scatter program
# compiled once per deployment (the prefix is written exactly once)
paged_write_prefix_blocks_jit = jax.jit(
    paged_write_prefix_blocks, static_argnames=("cfg", "blocks"),
    donate_argnums=(0,),
)


@register_unit("TransformerGenerator")
class TransformerGenerator(Unit):
    """Serving unit: prompt token rows in, generated token rows out, over
    the standard data plane.  Generation length and temperature are graph
    parameters, so a deployment JSON fully describes the decode behavior.

    Input contract: prompt values are truncated to int32 and CLAMPED to
    [0, vocab) — jit-compiled programs cannot reject data-dependent values
    per-request, so out-of-range ids degrade deterministically instead of
    hitting XLA's unspecified out-of-bounds gather.

    Sampling: temperature>0 threads a request counter through unit state,
    so repeated identical prompts draw fresh continuations (a fixed key
    would make sampling a worse greedy); the counter update rides the
    normal state write-back."""

    pure = True
    class_names = None

    def __init__(self, vocab: int = 256, d_model: int = 128, n_heads: int = 4,
                 n_layers: int = 2, d_ff: int = 512, seed: int = 0,
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0, eos_token: int = -1,
                 prefix_tokens: str = "",
                 dtype: str = "bfloat16", moe_every: int = 0,
                 n_experts: int = 8, moe_k: int = 2, mesh=None,
                 quant: str = "none", attention: str = "auto",
                 kv_quant: str = "none",
                 n_kv_heads: int = 0, weights_path: str = "",
                 rope: bool = True, rope_base: float = 10000.0):
        # mesh (from the binding's mesh_axes, e.g. {"tp": 4}): params are
        # laid out with the LM's tp shardings and GSPMD partitions the
        # whole prefill+decode program across the mesh — one generator
        # graph node spans multiple chips through the deployment JSON
        self.mesh = mesh
        self.cfg = LMConfig(
            vocab=int(vocab), d_model=int(d_model), n_heads=int(n_heads),
            n_layers=int(n_layers), d_ff=int(d_ff),
            dtype=jnp.dtype(dtype).type,
            moe_every=int(moe_every), n_experts=int(n_experts),
            moe_k=int(moe_k), quant=str(quant),
            kv_quant=str(kv_quant),
            n_kv_heads=int(n_kv_heads),
            rope=bool(rope), rope_base=float(rope_base),
        )
        from seldon_core_tpu.models.transformer import resolve_flash

        self.use_flash = resolve_flash(str(attention), mesh)
        self.weights_path = str(weights_path)
        self.seed = int(seed)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token = int(eos_token)
        # shared system-prompt prefix ("1,2,3" token ids): its KV cache
        # is computed ONCE in init_state and reused by every request
        self.prefix_ids = [
            int(t) for t in str(prefix_tokens).replace(" ", "").split(",")
            if t != ""
        ]
        for t in self.prefix_ids:
            if not 0 <= t < self.cfg.vocab:
                raise ValueError(
                    f"prefix token {t} outside vocab [0, {self.cfg.vocab})")
        # sampled decoding draws per-row noise from one key, so a row's
        # tokens depend on its position in the stacked batch; MoE capacity
        # routing likewise couples rows (shared capacity over the flattened
        # token stream) — either way, coalescing other callers' rows would
        # change this caller's answer.  The request counter in state
        # additionally varies the sampling key per request.
        self.batch_coupled = (
            self.temperature > 0.0 or self.cfg.moe_every > 0
        )
        self.updates_state_on_predict = self.temperature > 0.0

    def _prefix(self, state):
        return state.get("prefix_cache")

    def init_state(self, rng):
        from seldon_core_tpu.models.transformer import load_lm_weights

        if rng is None:
            rng = jax.random.key(self.seed)
        params = lm_init(jax.random.fold_in(rng, self.seed), self.cfg)
        params = load_lm_weights(params, self.weights_path)
        if self.cfg.quant == "int8":
            from seldon_core_tpu.ops.quant import quantize_lm_params

            params = quantize_lm_params(params)
        if self.mesh is not None:
            from seldon_core_tpu.models.transformer import param_shardings

            params = jax.device_put(
                params, param_shardings(self.mesh, params)
            )
        state = {"params": params, "requests": jnp.zeros((), jnp.int32)}
        if self.prefix_ids:
            pc = init_cache(self.cfg, 1, len(self.prefix_ids))
            _, pc = prefill(
                params, jnp.asarray([self.prefix_ids], jnp.int32), pc,
                self.cfg, self.use_flash,
            )
            state["prefix_cache"] = pc
        return state

    def predict(self, state, X):
        prompt = sanitize_prompt(X, self.cfg.vocab)
        key = jax.random.fold_in(jax.random.key(self.seed),
                                 state["requests"])
        y = generate(
            state["params"], prompt, self.cfg,
            max_new_tokens=self.max_new_tokens,
            temperature=self.temperature,
            rng=key,
            use_flash=self.use_flash,
            top_k=self.top_k, top_p=self.top_p,
            eos_token=self.eos_token,
            prefix=self._prefix(state),
        ).astype(jnp.float32)
        if self.temperature > 0.0:
            # preserve EVERY state key (prefix_cache!) — only the
            # request counter advances
            new_state = {**state, "requests": state["requests"] + 1}
            return y, UnitAux(state=new_state)
        return y

    def continuous_spec(self, state):
        """Scheduler contract for the continuous-batching generation lane
        (runtime/genserver.py): everything the per-step scheduler needs to
        run this unit's decoding — params, config, sampling knobs, the
        shared-prefix cache.  Returns None when the unit cannot be
        continuously scheduled: MoE capacity routing couples co-batched
        rows through the shared expert-capacity reduction, so co-scheduling
        other requests' rows would change this request's answer."""
        if self.cfg.moe_every > 0:
            return None
        return {
            "params": state["params"],
            "cfg": self.cfg,
            "temperature": self.temperature,
            "top_k": self.top_k,
            "top_p": self.top_p,
            "eos_token": self.eos_token,
            "max_new_tokens": self.max_new_tokens,
            "prefix_cache": state.get("prefix_cache"),
            "seed": self.seed,
            # tensor-parallel dispatch (runtime/servingmesh.py): the
            # scheduler lays its paged KV pool out over the same mesh
            # the params are sharded on, so prefill/decode programs
            # compile SPMD across the chips
            "mesh": self.mesh,
        }

    def stream_tokens(self, state, X, chunk: int = 8):
        """Incremental serving: yields [B, <=chunk] int32 arrays; the
        concatenation equals ``predict``'s output for greedy decoding
        (streaming bypasses the batcher and state write-back, so sampled
        streams draw a fresh key per call instead of threading the request
        counter — same quality, different stream)."""
        prompt = sanitize_prompt(jnp.asarray(X), self.cfg.vocab)
        if self.temperature > 0.0:
            key = jax.random.fold_in(
                jax.random.key(self.seed), next(_stream_counter)
            )
        else:
            key = jax.random.fold_in(jax.random.key(self.seed), 0)
        yield from stream_chunks(
            state["params"], prompt, self.cfg,
            max_new_tokens=self.max_new_tokens, chunk=int(chunk),
            temperature=self.temperature, rng=key,
            use_flash=self.use_flash,
            top_k=self.top_k, top_p=self.top_p,
            eos_token=self.eos_token,
            prefix=self._prefix(state),
        )


