"""Iris classifier — the reference's canonical single-MODEL REST workload
(examples/models/sklearn_iris/IrisClassifier.py:1-9: joblib-loaded sklearn
model answering predict_proba).

TPU-native version: a softmax-regression trained in JAX at construction time
on the classic iris dataset (bundled with scikit-learn, no network).  Serving
is a single fused matmul + softmax."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from seldon_core_tpu.graph.units import Unit, UnitAux, register_unit

__all__ = ["IrisClassifier"]


def _load_iris():
    try:
        from sklearn.datasets import load_iris

        ds = load_iris()
        return (
            np.asarray(ds.data, np.float32),
            np.asarray(ds.target, np.int32),
            [str(n) for n in ds.target_names],
        )
    except Exception:  # pragma: no cover - sklearn always present in CI image
        rng = np.random.default_rng(0)
        X = rng.normal(size=(150, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        return X, y, ["t:0", "t:1", "t:2"]


@register_unit("IrisClassifier")
class IrisClassifier(Unit):
    """Multinomial logistic regression; `predict` returns class probabilities
    (the reference's predict_proba contract)."""

    def __init__(self, steps: int = 200, lr: float = 0.5, seed: int = 0):
        X, y, names = _load_iris()
        self.class_names = names
        # standardise features; keep the scaler in the unit for serving
        self._mu = X.mean(axis=0)
        self._sigma = X.std(axis=0) + 1e-6
        Xn = (X - self._mu) / self._sigma
        n_classes = int(y.max()) + 1

        def loss(params):
            logits = Xn @ params["w"] + params["b"]
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(logp[jnp.arange(Xn.shape[0]), y])

        key = jax.random.key(seed)
        params = {
            "w": 0.01 * jax.random.normal(key, (Xn.shape[1], n_classes), jnp.float32),
            "b": jnp.zeros((n_classes,), jnp.float32),
        }

        @jax.jit
        def fit(params):
            def step(p, _):
                g = jax.grad(loss)(p)
                return (
                    jax.tree_util.tree_map(lambda a, b: a - lr * b, p, g),
                    None,
                )

            params, _ = jax.lax.scan(step, params, None, length=steps)
            return params

        self._params = jax.device_get(fit(params))
        self._train_accuracy = float(
            np.mean(np.argmax(Xn @ self._params["w"] + self._params["b"], axis=1) == y)
        )

    def init_state(self, rng):
        return {
            "w": jnp.asarray(self._params["w"]),
            "b": jnp.asarray(self._params["b"]),
            "mu": jnp.asarray(self._mu),
            "sigma": jnp.asarray(self._sigma),
        }

    def predict(self, state, X):
        Xn = (X - state["mu"]) / state["sigma"]
        return jax.nn.softmax(Xn @ state["w"] + state["b"], axis=-1)
