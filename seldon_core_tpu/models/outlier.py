"""Streaming Mahalanobis outlier detector — parity with the reference's
outlier TRANSFORMER (examples/transformers/outlier_mahalanobis/
OutlierMahalanobis.py:6-80): tracks running mean/covariance online, projects
onto the top principal components, scores each row by Mahalanobis distance in
the PC subspace, and tags the scores into ``meta.tags['outlierScore']`` while
passing the data through unchanged (wrappers/python/
outlier_detector_microservice.py:36-56).

TPU-native redesign: instead of the reference's Python loop with an iterative
inverse-covariance update, the state transition is a batched covariance
update (one rank-k correction per request batch) and scoring is a solve
against the regularised projected covariance — eigh + solve are small dense
ops that XLA fuses around the surrounding graph.  Shapes are static
(``n_features`` is a constructor parameter) so the unit compiles into the
graph program."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from seldon_core_tpu.graph.units import Unit, UnitAux, register_unit

__all__ = ["MahalanobisOutlier"]

_EPS = 1e-6


@register_unit("MahalanobisOutlier")
class MahalanobisOutlier(Unit):
    updates_state_on_predict = True  # running mean/cov count every row seen

    def __init__(self, n_features: int, n_components: int = 3, max_n: int = -1):
        self.p = int(n_features)
        self.k = min(int(n_components), self.p)
        self.max_n = int(max_n)  # -1: unbounded (reference max_n=None)

    def init_state(self, rng):
        return {
            "mean": jnp.zeros((self.p,), jnp.float32),
            "C": jnp.zeros((self.p, self.p), jnp.float32),
            "n": jnp.float32(0.0),
        }

    def transform_input(self, state, X):
        X = X.reshape(X.shape[0], -1).astype(jnp.float32)
        nb = X.shape[0]
        n = state["n"]
        if self.max_n > 0:
            n = jnp.minimum(n, jnp.float32(self.max_n))

        # --- update running mean / covariance with this batch -------------
        batch_mean = jnp.mean(X, axis=0)
        new_mean = state["mean"] + (nb / (n + nb)) * (batch_mean - state["mean"])
        centered = X - new_mean[None, :]
        batch_cov = (centered.T @ centered) / nb
        new_C = jnp.where(
            n > 0,
            (n / (n + nb)) * state["C"] + (nb / (n + nb)) * batch_cov,
            batch_cov,
        )

        # --- project onto top-k principal components ----------------------
        eigvals, eigvects = jnp.linalg.eigh(new_C)  # ascending
        top = eigvects[:, -self.k :]  # [p, k]
        proj = centered @ top  # [nb, k]
        proj_cov = top.T @ new_C @ top + _EPS * jnp.eye(self.k)

        # --- Mahalanobis distance in the PC subspace ----------------------
        solved = jnp.linalg.solve(proj_cov, proj.T)  # [k, nb]
        scores = jnp.sum(proj * solved.T, axis=1)  # [nb]

        new_state = {"mean": new_mean, "C": new_C, "n": state["n"] + nb}
        return X, UnitAux(state=new_state, tags={"outlierScore": scores})
