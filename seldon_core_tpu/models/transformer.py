"""Decoder-only transformer LM — the long-context / distributed flagship.

The reference serves no sequence models (SURVEY.md §5: long-context absent,
pre-LLM era); this family exists so the graph IR's nodes can span a TPU mesh
slice, which the task's north star requires.  Parallelism is GSPMD-first
(the scaling-book recipe): parameters carry ``NamedSharding``s —

    wqkv [D, 3D]   P(None, 'tp')     heads sharded over tp
    wo   [D, D]    P('tp', None)     row-sharded; XLA inserts the psum
    w1   [D, F]    P(None, 'tp')
    w2   [F, D]    P('tp', None)
    embed [V, D]   replicated (small vocabs); norms replicated

activations shard as tokens ``[B, S] : P('dp', 'sp')``, and attention runs
as a ``shard_map`` ring over the ``sp`` axis (parallel/ring_attention.py),
rotating K/V blocks over ICI with online-softmax accumulation.  Everything
else — gradient all-reduce over dp, activation collectives for tp — is
inserted by XLA from the shardings.

``train_step`` is a pure (params, opt_state, batch) -> (params, opt_state,
loss) function; jit it over the mesh for the full dp/tp/sp-parallel training
step (used by ``__graft_entry__.dryrun_multichip``)."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from seldon_core_tpu.graph.units import Unit, register_unit
from seldon_core_tpu.parallel.moe import moe_leaf_spec
from seldon_core_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_apply,
    split_microbatches,
    stack_stage_params,
    stage_param_shardings,
)
from seldon_core_tpu.parallel.mesh import shard_map as compat_shard_map
from seldon_core_tpu.parallel.ring_attention import ring_attention

__all__ = ["LMConfig", "lm_init", "lm_apply", "lm_loss", "lm_train_step",
           "param_shardings", "TransformerLM", "resolve_flash",
           "save_lm_weights", "load_lm_weights",
           "lm_pipeline_params", "lm_pipeline_apply", "lm_pipeline_loss",
           "lm_pipeline_train_step"]


@dataclass(frozen=True)
class LMConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    # grouped-query attention (LLaMA-2/Mistral-style): n_kv_heads < n_heads
    # shares each K/V head across n_heads/n_kv_heads query heads.  On TPU
    # this is a SERVING lever first: the KV cache shrinks by the group
    # factor, and cached decode is HBM-bound on exactly that stream.
    # 0 = multi-head attention (n_kv_heads == n_heads).
    n_kv_heads: int = 0
    dtype: Any = jnp.bfloat16
    # MoE: every ``moe_every``-th block (1-indexed) swaps its dense FFN for
    # a mixture of ``n_experts`` experts, top-``moe_k`` routed, sharded over
    # the mesh's ``ep`` axis (parallel/moe.py).  0 = dense everywhere.
    moe_every: int = 0
    n_experts: int = 8
    moe_k: int = 2
    # "int8": serve layer matmuls from symmetric per-channel int8 weights,
    # weight-only W8A16 (ops/quant.py dequant_matmul) — weights stream at
    # half the bytes, activations never quantize.  Serving-only.
    quant: str = "none"
    # "int8": store the KV cache as int8 with per-token-per-head f32
    # scales (absmax over the head dim).  Cached decode is HBM-bound on
    # the K/V stream — at large batch it is ~6x the weight stream — so
    # halving cache bytes is the decode-throughput lever int8 WEIGHTS
    # cannot be (models/generate.py reads the scales back into the score
    # and PV dots; prefill/training numerics untouched).  Serving-only.
    kv_quant: str = "none"
    # rotary position embeddings (RoPE, the modern standard).  Without ANY
    # positional signal a causal transformer cannot express
    # position-relative behavior (it must fall back to content-based
    # induction); rotation is applied to q/k after the head split, so the
    # KV cache stores rotated keys and cached decode needs no extra state.
    rope: bool = True
    rope_base: float = 10000.0

    def is_moe_layer(self, i: int) -> bool:
        return self.moe_every > 0 and (i + 1) % self.moe_every == 0

    def __post_init__(self):
        if self.d_model % self.n_heads != 0:
            # caught at config construction (graph load), not as an opaque
            # reshape error at first-request trace time
            raise ValueError(
                f"d_model={self.d_model} not divisible by "
                f"n_heads={self.n_heads}"
            )
        if self.quant not in ("none", "int8"):
            raise ValueError(
                f"quant={self.quant!r} not supported (none | int8)"
            )
        if self.kv_quant not in ("none", "int8"):
            raise ValueError(
                f"kv_quant={self.kv_quant!r} not supported (none | int8)"
            )
        kv = self.kv_heads
        if self.n_heads % kv != 0:
            raise ValueError(
                f"n_heads={self.n_heads} not divisible by "
                f"n_kv_heads={kv}"
            )
        if self.rope and (self.d_model // self.n_heads) % 2 != 0:
            raise ValueError(
                f"RoPE needs an even head dim, got "
                f"{self.d_model // self.n_heads}"
            )

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads


def _rmsnorm(x, w, eps=1e-6):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * w


def apply_rope(x, positions, base: float = 10000.0):
    """Rotate [B, H, S, hd] by per-position angles; positions [S] shared
    across the batch (may be traced — cached decode passes start+arange)
    or [B, S] PER-ROW (batched speculative decoding, where rows sit at
    different sequence lengths).  Half-split convention; f32 trig,
    output in the input dtype.

    The rotate-half is computed as ``x @ R`` with R the constant signed
    permutation [[0, I], [-I, 0]] — EXACT arithmetic (each output is
    ±one input) and MXU-fusable.  The obvious
    ``concat([-x2, x1])`` lowers to lane-dim pad+maximum fusions that
    cannot fuse into the flash kernel's custom-call boundary: profiled
    at ~290 us/layer on the B=32 S=512 prefill (~3.5 ms/pass, ~7% of
    the whole forward)."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = base ** (
        -jnp.arange(0, half, dtype=jnp.float32) / half
    )  # [half]
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [...,S,half]
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    if angles.ndim == 2:  # shared positions [S, half]
        c = jnp.concatenate([cos, cos], axis=-1)[None, None]  # [1,1,S,hd]
        s = jnp.concatenate([sin, sin], axis=-1)[None, None]
    else:  # per-row positions [B, S, half] -> broadcast over heads
        c = jnp.concatenate([cos, cos], axis=-1)[:, None]  # [B,1,S,hd]
        s = jnp.concatenate([sin, sin], axis=-1)[:, None]
    eye = jnp.eye(half, dtype=x.dtype)
    zero = jnp.zeros((half, half), x.dtype)
    rot = jnp.concatenate([
        jnp.concatenate([zero, eye], axis=1),    # rows i<half: +x1 -> out2
        jnp.concatenate([-eye, zero], axis=1),   # rows i>=half: -x2 -> out1
    ], axis=0)  # [hd, hd]
    rx = jax.lax.dot_general(
        x, rot, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    out = x.astype(jnp.float32) * c + rx * s
    return out.astype(x.dtype)


def lm_init(rng, cfg: LMConfig) -> Dict[str, Any]:
    keys = jax.random.split(rng, cfg.n_layers * 4 + 1)
    dt = cfg.dtype

    def dense(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, jnp.float32) * (fan_in ** -0.5)
        ).astype(dt)

    params: Dict[str, Any] = {
        "embed": dense(keys[0], (cfg.vocab, cfg.d_model), cfg.d_model),
    }
    hd = cfg.d_model // cfg.n_heads
    qkv_out = cfg.d_model + 2 * cfg.kv_heads * hd  # q | k | v segments
    for i in range(cfg.n_layers):
        k = keys[1 + 4 * i : 1 + 4 * (i + 1)]
        lp = {
            "ln1": jnp.ones((cfg.d_model,), dt),
            "wqkv": dense(k[0], (cfg.d_model, qkv_out), cfg.d_model),
            "wo": dense(k[1], (cfg.d_model, cfg.d_model), cfg.d_model),
            "ln2": jnp.ones((cfg.d_model,), dt),
        }
        if cfg.is_moe_layer(i):
            from seldon_core_tpu.parallel.moe import MoEConfig, moe_init

            lp["moe"] = moe_init(
                k[2],
                MoEConfig(d_model=cfg.d_model, d_ff=cfg.d_ff,
                          n_experts=cfg.n_experts, k=cfg.moe_k, dtype=dt),
            )
        else:
            lp["w1"] = dense(k[2], (cfg.d_model, cfg.d_ff), cfg.d_model)
            lp["w2"] = dense(k[3], (cfg.d_ff, cfg.d_model), cfg.d_ff)
        params[f"l{i}"] = lp
    params["ln_f"] = jnp.ones((cfg.d_model,), dt)
    return params


def param_shardings(mesh: Mesh, params) -> Any:
    """NamedShardings for the tp layout above (replicated where not listed)."""

    def spec_for(path, leaf) -> P:
        # path is a tuple of DictKey objects; the leaf name is the last key
        names = [getattr(p, "key", str(p)) for p in path]
        name = names[-1]
        if "moe" in names:
            return moe_leaf_spec(name, leaf, mesh)
        has_tp = "tp" in mesh.axis_names
        # int8 layout (quantize_lm_params): w_q shards like w; the
        # per-output-channel scales follow the output axis' sharding
        if name.endswith("_q") or name.endswith("_s"):
            base, kind = name[:-2], name[-1]
            if base in ("wqkv", "w1"):
                if kind == "q":
                    return P(None, "tp") if has_tp else P()
                return P("tp") if has_tp else P()
            if base in ("wo", "w2"):
                # output axis replicated (the psum happens over tp)
                return P("tp", None) if (has_tp and kind == "q") else P()
            return P()
        if name in ("wqkv", "w1"):
            return P(None, "tp") if has_tp else P()
        if name in ("wo", "w2"):
            return P("tp", None) if has_tp else P()
        return P()

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    shardings = [NamedSharding(mesh, spec_for(path, leaf))
                 for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, shardings)


def gqa_attention(q, k, v, causal: bool):
    """Grouped-query attention without materialising repeated K/V.

    q [B, H, S, hd]; k/v [B, KV, S_k, hd] with H = KV * g.  The group axis
    rides the dot_general batch dims, so K/V stream from HBM ONCE at their
    stored (grouped) size — an explicit head-repeat would rebuild the full
    MHA-sized tensors and erase GQA's bandwidth win."""
    B, H, S, hd = q.shape
    KV = k.shape[1]
    g = H // KV
    qg = q.reshape(B, KV, g * S, hd)  # group heads fold into the row axis
    scale = jnp.float32(1.0 / (hd ** 0.5))
    s = jax.lax.dot_general(
        qg, k, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    ) * scale  # [B, KV, g*S, S_k]
    s = s.reshape(B, KV, g, S, k.shape[2])
    if causal:
        qpos = jnp.arange(S)[:, None]
        kpos = jnp.arange(k.shape[2])[None, :]
        s = jnp.where((qpos >= kpos)[None, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    out = jax.lax.dot_general(
        p.reshape(B, KV, g * S, k.shape[2]), v,
        (((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)  # [B, KV, g*S, hd]
    return out.reshape(B, H, S, hd)


def _attention(q, k, v, mesh: Optional[Mesh], causal: bool,
               use_flash: bool = False):
    """q [B, H, S, hd], k/v [B, KV, S, hd] -> [B, H, S, hd]; ring over sp
    when the mesh shards S.

    ``use_flash`` opts the single-chip path into the Pallas flash kernel
    (differentiable — custom flash VJP); constraint violations fall back
    to the plain XLA path silently.  Grouped K/V (KV < H) takes the GQA
    formulation; the ring path requires full MHA heads."""
    # auto mode only takes the kernel where it measures faster than XLA's
    # fused attention (thresholds above; grouped K/V wins from much
    # shorter S); "force" overrides (explicit opt-in / the benchmarking
    # arm)
    auto_min = (FLASH_AUTO_MIN_S_GQA if k.shape[1] != q.shape[1]
                else FLASH_AUTO_MIN_S)
    flash_eligible = use_flash == "force" or (
        use_flash and q.shape[2] >= auto_min
    )
    if k.shape[1] != q.shape[1]:
        if mesh is not None and "sp" in mesh.axis_names and mesh.shape["sp"] > 1:
            raise ValueError(
                "sequence-parallel ring attention requires "
                "n_kv_heads == n_heads"
            )
        if flash_eligible and (mesh is None or mesh.size == 1):
            # the flash kernel is GQA-native (grouped K/V block indexing)
            from seldon_core_tpu.ops.flash_attention import flash_attention

            try:
                return flash_attention(q, k, v, causal=causal)
            except ValueError:
                pass  # shape constraints unmet -> grouped XLA path
        return gqa_attention(q, k, v, causal)
    if flash_eligible and (mesh is None or mesh.size == 1):
        # single-chip only: pallas_call is not auto-partitionable under
        # GSPMD, so any multi-device mesh (tp/dp/sp) keeps the XLA path
        from seldon_core_tpu.ops.flash_attention import flash_attention

        try:
            return flash_attention(q, k, v, causal=causal)
        except ValueError:
            pass  # shape constraints unmet -> XLA path below
    if mesh is not None and "sp" in mesh.axis_names and mesh.shape["sp"] > 1:
        specs = P(
            "dp" if "dp" in mesh.axis_names else None,
            "tp" if "tp" in mesh.axis_names else None,
            "sp",
            None,
        )

        ring = partial(
            compat_shard_map,
            mesh=mesh,
            in_specs=(specs, specs, specs),
            out_specs=specs,
        )(lambda q_, k_, v_: ring_attention(q_, k_, v_, "sp", causal=causal))
        return ring(q, k, v)
    # single-block fallback: plain causal attention
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qpos = jnp.arange(q.shape[2])[:, None]
        kpos = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qpos >= kpos, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)


def _block(lp, x, cfg: LMConfig, mesh: Optional[Mesh], causal: bool,
           use_flash: bool = False):
    """One decoder block: attn + FFN (dense or MoE) with residuals.
    x [B,S,D] -> (x', lb_loss) where lb_loss is 0 for dense layers."""
    from seldon_core_tpu.ops.quant import lm_matmul

    B, S, D = x.shape
    hd = cfg.d_model // cfg.n_heads
    kv = cfg.kv_heads
    h = _rmsnorm(x, lp["ln1"])
    qkv = lm_matmul(lp, "wqkv", h, out_dtype=x.dtype)  # [B,S,D+2*kv*hd]
    q, k, v = jnp.split(qkv, [D, D + kv * hd], axis=-1)

    def heads(t, n):
        return t.reshape(B, S, n, hd).transpose(0, 2, 1, 3)

    q, k, v = heads(q, cfg.n_heads), heads(k, kv), heads(v, kv)
    if cfg.rope:
        # rotation BEFORE any sharded attention: positions are global, so
        # the sp ring path needs no per-shard offsets
        positions = jnp.arange(S)
        q = apply_rope(q, positions, cfg.rope_base)
        k = apply_rope(k, positions, cfg.rope_base)
    a = _attention(q, k, v, mesh, causal, use_flash)
    a = a.transpose(0, 2, 1, 3).reshape(B, S, D)
    x = x + lm_matmul(lp, "wo", a, out_dtype=x.dtype)
    h = _rmsnorm(x, lp["ln2"])
    y, lb = _ffn(lp, h, cfg, mesh)
    return x + y, lb


def _ffn(lp, h, cfg: LMConfig, mesh: Optional[Mesh]):
    """Dense or MoE feed-forward on h [B,S,D] -> (y, lb_loss)."""
    if "moe" in lp:
        from seldon_core_tpu.parallel.moe import MoEConfig, moe_apply

        mcfg = MoEConfig(d_model=cfg.d_model, d_ff=cfg.d_ff,
                         n_experts=cfg.n_experts, k=cfg.moe_k,
                         dtype=cfg.dtype)
        y, aux = moe_apply(lp["moe"], h, mcfg, mesh=mesh)
        return y, aux["lb_loss"]
    from seldon_core_tpu.ops.quant import lm_matmul

    u = jax.nn.gelu(lm_matmul(lp, "w1", h, out_dtype=h.dtype))
    return lm_matmul(lp, "w2", u, out_dtype=h.dtype), jnp.float32(0.0)


def lm_apply(
    params, tokens, cfg: LMConfig, mesh: Optional[Mesh] = None,
    causal: bool = True, use_flash: bool = False, return_lb: bool = False
):
    """tokens [B, S] int32 -> logits [B, S, V] (f32).  ``use_flash`` uses
    the Pallas flash kernel on single-chip meshes (differentiable).
    ``return_lb`` additionally returns the summed MoE load-balance loss."""
    x = params["embed"][tokens]  # [B,S,D]
    lb_total = jnp.float32(0.0)
    for i in range(cfg.n_layers):
        x, lb = _block(params[f"l{i}"], x, cfg, mesh, causal, use_flash)
        lb_total = lb_total + lb
    x = _rmsnorm(x, params["ln_f"])
    logits = (x @ params["embed"].T).astype(jnp.float32)
    return (logits, lb_total) if return_lb else logits


LB_LOSS_COEF = 0.01  # Switch-style aux-loss weight


def lm_loss(params, batch, cfg: LMConfig, mesh: Optional[Mesh] = None,
            apply_fn=None, use_flash: Optional[bool] = None):
    """Next-token cross-entropy (+ weighted MoE load-balance loss when the
    config has MoE layers); batch = {tokens: [B, S+1]}.

    ``apply_fn(params, tokens) -> logits`` overrides the forward (used by the
    pipelined variant); defaults to ``lm_apply``.  ``use_flash=None`` picks
    the Pallas flash kernel automatically on single-chip TPU (the kernel
    carries a custom VJP, so training uses it too); shapes outside its
    constraints fall back to XLA attention inside ``_attention``."""
    tokens = batch["tokens"]
    lb_total = jnp.float32(0.0)
    if use_flash is None:
        from seldon_core_tpu.ops.fused_mlp import pallas_supported

        use_flash = pallas_supported()
    if apply_fn is None:
        logits, lb_total = lm_apply(params, tokens[:, :-1], cfg, mesh,
                                    return_lb=True, use_flash=use_flash)
    else:
        if cfg.moe_every:
            # a custom forward cannot report the lb loss through this
            # interface; training without it collapses the router
            raise ValueError(
                "lm_loss(apply_fn=...) does not support MoE configs"
            )
        logits = apply_fn(params, tokens[:, :-1])
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll) + LB_LOSS_COEF * lb_total


def _grad_update(loss_fn, params, opt_state, batch, optimizer):
    loss, grads = jax.value_and_grad(loss_fn)(params, batch)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = jax.tree_util.tree_map(lambda p, u: p + u.astype(p.dtype), params, updates)
    return params, opt_state, loss


def lm_train_step(params, opt_state, batch, optimizer, cfg: LMConfig,
                  mesh: Optional[Mesh] = None,
                  use_flash: Optional[bool] = None):
    if cfg.quant != "none":
        # int8 weights are not differentiable — quantization is a serving
        # transform (quantize_lm_params), applied after training
        raise ValueError("lm_train_step requires quant='none'")
    return _grad_update(
        lambda p, b: lm_loss(p, b, cfg, mesh, use_flash=use_flash), params,
        opt_state, batch, optimizer,
    )


# ---------------------------------------------------------------------------
# Pipeline-parallel variant: the layer stack splits into pp stages, one stage
# per chip; microbatched GPipe schedule over ICI (parallel/pipeline.py).
# Embed/unembed stay outside the pipeline (replicated, batch over dp).
# ---------------------------------------------------------------------------


def lm_pipeline_params(params, cfg: LMConfig, n_stages: int, mesh: Mesh):
    """Re-layout lm_init params for a pp-stage pipeline.

    Returns {embed, ln_f, stages} where ``stages`` leaves are stacked
    [n_stages, layers_per_stage, ...] and sharded P('pp', ...).
    """
    if cfg.n_layers % n_stages != 0:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp={n_stages}"
        )
    if cfg.moe_every:
        # MoE layers have a different param tree than dense ones, so they
        # cannot stack into a homogeneous per-stage scan; also their
        # lb_loss would be silently dropped by the pipeline schedule
        raise ValueError("pipeline parallelism does not support MoE layers")
    lps = cfg.n_layers // n_stages
    per_stage = []
    for s in range(n_stages):
        layers = [params[f"l{s * lps + j}"] for j in range(lps)]
        per_stage.append(
            jax.tree_util.tree_map(lambda *ls: jnp.stack(ls, 0), *layers)
        )
    stages = stack_stage_params(per_stage)
    stages = jax.device_put(stages, stage_param_shardings(mesh, stages))
    return {"embed": params["embed"], "ln_f": params["ln_f"], "stages": stages}


def lm_pipeline_apply(pp_params, tokens, cfg: LMConfig, mesh: Mesh,
                      n_micro: int = 4, causal: bool = True):
    """Pipelined forward: tokens [B, S] -> logits [B, S, V]."""

    def stage_fn(stage_params, x):
        # stage_params leaves: [layers_per_stage, ...]; scan the sub-stack
        def body(h, lp):
            h2, _lb = _block(lp, h, cfg, mesh=None, causal=causal)
            return h2, None

        out, _ = jax.lax.scan(body, x, stage_params)
        return out

    x = pp_params["embed"][tokens]  # [B,S,D]
    xm = split_microbatches(x, n_micro)
    ym = pipeline_apply(stage_fn, pp_params["stages"], xm, mesh=mesh)
    x = merge_microbatches(ym)
    x = _rmsnorm(x, pp_params["ln_f"])
    return (x @ pp_params["embed"].T).astype(jnp.float32)


def lm_pipeline_loss(pp_params, batch, cfg: LMConfig, mesh: Mesh,
                     n_micro: int = 4):
    return lm_loss(
        pp_params, batch, cfg, mesh,
        apply_fn=lambda p, t: lm_pipeline_apply(p, t, cfg, mesh, n_micro),
    )


def lm_pipeline_train_step(pp_params, opt_state, batch, optimizer,
                           cfg: LMConfig, mesh: Mesh, n_micro: int = 4):
    """Full pipeline-parallel train step — backward replays the GPipe
    schedule in reverse through the scan+ppermute graph."""
    return _grad_update(
        lambda p, b: lm_pipeline_loss(p, b, cfg, mesh, n_micro),
        pp_params, opt_state, batch, optimizer,
    )


#: ``auto`` mode thresholds, from interleaved A/B through the LM forward
#: on v5e (round 4, wide-block kernel: bq<=512/bk<=1024).  MHA hd=128:
#: 0.93x XLA at S=2048, 1.36x at S=8192 — kernel from 4096 up.  GROUPED
#: K/V (GQA) wins much earlier: 1.20x at S=512/B=32 and 3.13x at
#: S=2048/B=4 (hd=64, kv=4) — XLA's fallback materialises the grouped
#: score tensor while the kernel streams K/V once at stored size.
FLASH_AUTO_MIN_S = 4096
FLASH_AUTO_MIN_S_GQA = 512


def resolve_flash(attention: str, mesh: Optional[Mesh]):
    """Deployment-parameter attention mode -> static flash decision.

    ``auto``  — Pallas flash kernel when the runtime supports it, the
                mesh is single-chip (pallas_call is not auto-partitionable
                under GSPMD), AND the sequence is long enough to win —
                checked per call in ``_attention``: grouped K/V (GQA)
                from ``FLASH_AUTO_MIN_S_GQA`` (512) up, MHA from
                ``FLASH_AUTO_MIN_S`` (4096) up;  returns True/False;
    ``flash`` — force the kernel at ANY length (returns ``"force"``, the
                benchmarking arm / explicit opt-in); a runtime without
                Pallas support or a multi-chip mesh still falls back to
                XLA (degrade, don't crash-loop the pod);
    ``xla``   — force the plain XLA attention (the control arm)."""
    if attention == "xla":
        return False
    if attention not in ("auto", "flash"):
        raise ValueError(
            f"attention={attention!r} not supported (auto | flash | xla)"
        )
    multi = mesh is not None and mesh.size > 1
    from seldon_core_tpu.ops.fused_mlp import pallas_supported

    supported = pallas_supported() and not multi
    if attention == "flash":
        return "force" if supported else False
    return supported


def save_lm_weights(params, path: str) -> str:
    """Checkpoint an lm_init-shaped params tree to one .npz — the
    train->serve hand-off (runtime/persistence.py flat-pytree format, so
    the same file also restores through the persistence machinery)."""
    from seldon_core_tpu.runtime.persistence import save_state_to_path

    return save_state_to_path(path, params)


def load_lm_weights(params, path: str):
    """Load trained weights onto a freshly-initialised params tree (the
    ``weights_path`` unit parameter).  Structure/dtype follow the serving
    config — an f32 training checkpoint serves as bf16, and quantization
    applies AFTER loading.

    STRICT: a missing file, a checkpoint whose keys don't cover the
    serving config's tree (layer-count mismatch, a state checkpoint
    rather than a params checkpoint), or a shape mismatch (wrong
    d_model/vocab/...) all raise a one-line config error at LOAD time —
    a generator pod silently serving random or misshapen weights is the
    worst failure mode."""
    if not path:
        return params
    import os as _os

    if not _os.path.exists(path):
        raise FileNotFoundError(f"weights_path {path!r} does not exist")
    import numpy as _np

    import jax as _jax

    from seldon_core_tpu.runtime.persistence import state_from_host

    with _np.load(path) as data:
        flat = dict(data)
    want = {
        _jax.tree_util.keystr(p): _np.asarray(leaf).shape
        for p, leaf in _jax.tree_util.tree_flatten_with_path(params)[0]
    }
    missing = sorted(set(want) - set(flat))
    if missing:
        raise ValueError(
            f"weights_path {path!r} does not cover the serving config: "
            f"{len(missing)} missing leaves (first: {missing[0]}); is the "
            f"checkpoint from a different architecture, or a unit-STATE "
            f"snapshot rather than save_lm_weights params?"
        )
    bad = [
        (k, flat[k].shape, want[k])
        for k in want if tuple(flat[k].shape) != tuple(want[k])
    ]
    if bad:
        k, got, exp = bad[0]
        raise ValueError(
            f"weights_path {path!r} shape mismatch at {k}: checkpoint "
            f"{got} vs serving config {exp} (+{len(bad) - 1} more)"
        )
    return state_from_host(flat, params)


@register_unit("TransformerLM")
class TransformerLM(Unit):
    """Serving unit: next-token logits for a token batch.  For multi-chip
    serving construct with a mesh; params shard per ``param_shardings``."""

    def __init__(
        self,
        vocab: int = 256,
        d_model: int = 128,
        n_heads: int = 4,
        n_layers: int = 2,
        d_ff: int = 512,
        seed: int = 0,
        mesh: Optional[Mesh] = None,
        dtype: str = "bfloat16",
        moe_every: int = 0,
        n_experts: int = 8,
        moe_k: int = 2,
        quant: str = "none",
        attention: str = "auto",
        n_kv_heads: int = 0,
        weights_path: str = "",
        rope: bool = True,
        rope_base: float = 10000.0,
    ):
        self.weights_path = str(weights_path)
        self.cfg = LMConfig(
            vocab=int(vocab), d_model=int(d_model), n_heads=int(n_heads),
            n_layers=int(n_layers), d_ff=int(d_ff),
            dtype=jnp.dtype(dtype).type,
            moe_every=int(moe_every), n_experts=int(n_experts),
            moe_k=int(moe_k), quant=str(quant),
            n_kv_heads=int(n_kv_heads),
            rope=bool(rope), rope_base=float(rope_base),
        )
        self.seed = int(seed)
        self.mesh = mesh
        self.use_flash = resolve_flash(str(attention), mesh)
        # MoE capacity routing flattens the stacked batch into one token
        # stream (shared capacity, cumsum slot order), so co-batched rows
        # change each other's overflow — no cross-request coalescing
        self.batch_coupled = self.cfg.moe_every > 0

    def init_state(self, rng):
        if rng is None:
            rng = jax.random.key(self.seed)
        rng = jax.random.fold_in(rng, self.seed)
        params = lm_init(rng, self.cfg)
        params = load_lm_weights(params, self.weights_path)
        if self.cfg.quant == "int8":
            from seldon_core_tpu.ops.quant import quantize_lm_params

            params = quantize_lm_params(params)
        if self.mesh is not None:
            params = jax.device_put(params, param_shardings(self.mesh, params))
        return params

    def predict(self, state, X):
        tokens = X.astype(jnp.int32)
        return lm_apply(
            state, tokens, self.cfg, self.mesh,
            use_flash=self.use_flash,
        )
