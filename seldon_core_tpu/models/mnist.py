"""MNIST classifier family — the flagship serving workload.

The reference serves a TF softmax-regression MNIST graph
(examples/models/deep_mnist/DeepMnist.py:1-17: restore session, sess.run on a
784-feature batch).  Here the models are pure-JAX functions designed for the
MXU: bfloat16 weights, batched matmuls, no Python control flow under jit.
Two variants:

  * ``MnistClassifier`` — MLP (784 -> hidden^depth -> 10).  The serving
    flagship: big fused matmuls, bf16 on the MXU, f32 softmax out.
  * ``MnistCNN``        — small convnet for parity with "deep" MNIST demos.

Both expose a functional training API (``init_params`` / ``apply`` /
``train_step``) used by the multi-chip dry-run and the feedback/online-
learning path; ``train_step`` is pure and pjit-shardable over (data, model)
mesh axes.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from seldon_core_tpu.graph.units import Unit, register_unit

__all__ = ["MnistClassifier", "QuantizedMnistClassifier", "MnistCNN",
           "mlp_init", "mlp_apply", "train_step"]

NUM_CLASSES = 10
INPUT_DIM = 784


# ---------------------------------------------------------------------------
# Functional MLP core
# ---------------------------------------------------------------------------


def mlp_init(
    rng,
    hidden: int = 512,
    depth: int = 2,
    in_dim: int = INPUT_DIM,
    out_dim: int = NUM_CLASSES,
    dtype=jnp.bfloat16,
) -> Dict[str, Any]:
    """He-initialised MLP parameters as a flat dict pytree."""
    dims = [in_dim] + [hidden] * depth + [out_dim]
    params: Dict[str, Any] = {}
    keys = jax.random.split(rng, len(dims) - 1)
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        scale = jnp.sqrt(2.0 / d_in)
        params[f"w{i}"] = (
            jax.random.normal(keys[i], (d_in, d_out), jnp.float32) * scale
        ).astype(dtype)
        params[f"b{i}"] = jnp.zeros((d_out,), dtype)
    return params


def mlp_apply(params: Dict[str, Any], x) -> jax.Array:
    """Logits.  Compute in the params' dtype (bf16 on the MXU), accumulate
    the final logits in f32."""
    n_layers = len(params) // 2
    dtype = params["w0"].dtype
    h = x.astype(dtype)
    for i in range(n_layers - 1):
        h = jnp.maximum(h @ params[f"w{i}"] + params[f"b{i}"], 0.0)
    logits = (h @ params[f"w{n_layers-1}"]).astype(jnp.float32) + params[
        f"b{n_layers-1}"
    ].astype(jnp.float32)
    return logits


def loss_fn(params, batch) -> jax.Array:
    x, y = batch["image"], batch["label"]
    logits = mlp_apply(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def train_step(params, opt_state, batch, optimizer) -> Tuple[Any, Any, jax.Array]:
    """One SGD/optax step; pure, shardable with pjit over a (data, model)
    mesh — gradients reduce over the data axis via XLA collectives."""
    loss, grads = jax.value_and_grad(loss_fn)(params, batch)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = jax.tree_util.tree_map(
        lambda p, u: (p + u.astype(p.dtype)), params, updates
    )
    return params, opt_state, loss


# ---------------------------------------------------------------------------
# Serving units
# ---------------------------------------------------------------------------


@register_unit("MnistClassifier")
class MnistClassifier(Unit):
    """MLP MNIST unit.  Params live in the unit *state* so the compiled graph
    threads them (ready for sharding / hot-swap); predict returns class
    probabilities like the reference wrapper's predict_proba convention."""

    class_names = [f"class:{i}" for i in range(NUM_CLASSES)]

    def __init__(
        self,
        hidden: int = 512,
        depth: int = 2,
        seed: int = 0,
        dtype: str = "bfloat16",
        use_pallas: str = "auto",
    ):
        self.hidden = int(hidden)
        self.depth = int(depth)
        self.seed = int(seed)
        self.dtype = jnp.dtype(dtype)
        # kernel-path decision is made HERE (static under jit): "auto" probes
        # the backend once; "never" forces the XLA path; "interpret" runs the
        # kernel in interpreter mode (CPU tests of the kernel itself)
        self.use_pallas = str(use_pallas)
        if self.use_pallas == "auto":
            from seldon_core_tpu.ops.fused_mlp import pallas_supported

            self._pallas = pallas_supported()
        else:
            self._pallas = self.use_pallas == "interpret"

    def init_state(self, rng):
        if rng is None:
            rng = jax.random.key(self.seed)
        # fold in the construction seed so two ensemble members with different
        # seeds differ even under one graph rng
        rng = jax.random.fold_in(rng, self.seed)
        return mlp_init(rng, hidden=self.hidden, depth=self.depth, dtype=self.dtype)

    def predict(self, state, X):
        X = X.reshape(X.shape[0], -1)
        if self._pallas:
            from seldon_core_tpu.ops.fused_mlp import fused_mlp_softmax

            try:
                return fused_mlp_softmax(
                    state, X, interpret=self.use_pallas == "interpret"
                )
            except ValueError:
                pass  # shape/VMEM constraints — XLA path below
        return jax.nn.softmax(mlp_apply(state, X), axis=-1)


@register_unit("QuantizedMnistClassifier")
class QuantizedMnistClassifier(MnistClassifier):
    """Int8 serving variant: weights quantize once at init (symmetric
    per-channel) and serve weight-only (dequant_matmul: XLA fuses the
    convert+scale into the dot's weight read, so weights stream at int8
    size — ops/quant.py records the measured trade-offs).  Activations
    are never quantized; argmax-stable for classifier heads."""

    def init_state(self, rng):
        from seldon_core_tpu.ops.quant import quantize_mlp_params

        return quantize_mlp_params(super().init_state(rng))

    def predict(self, state, X):
        from seldon_core_tpu.ops.quant import QuantizedMLP

        return QuantizedMLP.apply(state, X.reshape(X.shape[0], -1))


@register_unit("MnistCNN")
class MnistCNN(Unit):
    """Small convnet (2x conv+pool, 1 dense).  Accepts [B, 784] or
    [B, 28, 28, 1] input; NHWC layout for TPU convolutions."""

    class_names = [f"class:{i}" for i in range(NUM_CLASSES)]

    def __init__(self, channels: int = 32, seed: int = 0, dtype: str = "bfloat16"):
        self.channels = int(channels)
        self.seed = int(seed)
        self.dtype = jnp.dtype(dtype)

    def init_state(self, rng):
        if rng is None:
            rng = jax.random.key(self.seed)
        rng = jax.random.fold_in(rng, self.seed)
        k1, k2, k3 = jax.random.split(rng, 3)
        c = self.channels
        dt = self.dtype

        def conv_w(key, shape):
            fan_in = shape[0] * shape[1] * shape[2]
            return (
                jax.random.normal(key, shape, jnp.float32) * jnp.sqrt(2.0 / fan_in)
            ).astype(dt)

        return {
            "c1": conv_w(k1, (3, 3, 1, c)),
            "c2": conv_w(k2, (3, 3, c, 2 * c)),
            "w": (
                jax.random.normal(k3, (7 * 7 * 2 * c, NUM_CLASSES), jnp.float32)
                * jnp.sqrt(2.0 / (7 * 7 * 2 * c))
            ).astype(dt),
            "b": jnp.zeros((NUM_CLASSES,), dt),
        }

    def predict(self, state, X):
        if X.ndim == 2:
            X = X.reshape(-1, 28, 28, 1)
        h = X.astype(self.dtype)
        for w in (state["c1"], state["c2"]):
            h = jax.lax.conv_general_dilated(
                h, w, window_strides=(1, 1), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            h = jnp.maximum(h, 0.0)
            h = jax.lax.reduce_window(
                h, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
            )
        h = h.reshape(h.shape[0], -1)
        logits = (h @ state["w"]).astype(jnp.float32) + state["b"].astype(jnp.float32)
        return jax.nn.softmax(logits, axis=-1)
