"""seldon_core_tpu — a TPU-native inference-graph serving framework.

A ground-up JAX/XLA/pjit/Pallas re-design of the capabilities of Seldon Core
(reference: santi81/seldon-core).  Users describe a runtime inference graph of
MODEL / ROUTER / COMBINER / TRANSFORMER / OUTPUT_TRANSFORMER units; the
framework serves it over REST and gRPC with a `SeldonMessage`-compatible tensor
API.  Unlike the reference's Java microservice mesh (one engine pod fanning out
HTTP/gRPC hops per graph node), this framework *compiles* the inference graph:
when every node is a pure JAX callable the whole graph lowers to one XLA
program on a TPU mesh — ensembles fan out across chips and reduce over ICI,
routing happens via `lax.switch`, and network hops exist only at ingress.

Layout:
  messages        core data plane (SeldonMessage, Meta, Feedback, codecs)
  graph/          graph spec (CRD-equivalent), defaulting/validation,
                  host interpreter + compiled-graph executor, built-in units
  runtime/        model-wrapper runtime, REST/gRPC servers, engine service,
                  internal clients, batching
  gateway/        ingress gateway (auth, deployment routing, firehose log)
  operator/       deployment materializer (local process equivalent of the
                  reference's k8s operator)
  parallel/       device-mesh management, ensemble sharding, ring attention,
                  collectives
  models/         example / judged-workload model families
  ops/            Pallas TPU kernels
  utils/          metrics, puid, tracing, config
"""

__version__ = "0.1.0"

from seldon_core_tpu.messages import (  # noqa: F401
    DefaultData,
    Feedback,
    Meta,
    SeldonMessage,
    SeldonMessageList,
    Status,
)
