"""Causal distributed tracing — span trees, W3C context propagation,
critical-path analysis, trace export + TPU device profiling.

The reference has no distributed tracing: it logs per-hop call durations
(engine InternalPredictionService.java:267-268) and threads ``puid``
through every hop as a flat correlation id (PredictionService.java:52-58).
PR 1's flight recorder inherited that shape — a flat ring of spans.  This
module promotes it to a *causal* tracer:

  * Every span carries ``trace_id`` / ``span_id`` / ``parent_span_id``.
    The active span lives in a contextvar (``TRACE_VAR``, parallel to the
    deadline budget of runtime/resilience.py), so nesting is automatic:
    a span opened inside another becomes its child, across ``await`` and
    ``asyncio.gather`` fan-out (tasks inherit a context copy).
  * Trace context rides every hop as a W3C ``traceparent`` header (REST)
    / metadata entry (gRPC), so a multi-process graph — gateway → engine
    → unit microservices — reassembles into ONE tree, queryable at any
    participant's ``GET /trace?puid=`` (or ``trace_id=``).
  * ``critical_path`` walks the assembled tree and attributes the root
    span's wall clock to the chain of spans that actually gated it;
    ``phase_decomposition`` buckets those segments into
    queue / retry+backoff / network / dispatch / decode — the per-phase
    latency data ROADMAP's perf work steers by.
  * ``chrome_trace`` emits Chrome trace-event JSON (``GET /trace/export``)
    loadable in Perfetto / chrome://tracing.
  * Head sampling: ``SELDON_TPU_TRACE_SAMPLE=0.01`` decides ONCE at the
    trace root; the decision propagates in the traceparent flags byte, so
    tracing can stay on under production load.  ``sample=0`` records
    nothing anywhere in the tree.
  * ``device_profile`` wraps ``jax.profiler`` tracing for XLA/TPU-level
    timelines (the compiled graph is ONE XLA program, so intra-graph
    timing lives in the device profile, not host spans).  Re-entrancy
    safe: a nested/concurrent profile request becomes a span event, not
    a ``jax.profiler`` exception.

Tracing is off by default (``SELDON_TPU_TRACE=1`` or ``TRACER.enable()``);
disabled spans cost one attribute load and return a shared null context.
Lookups (``trace()`` / ``by_trace()``) are O(result) via bounded
secondary indexes kept in lockstep with the span ring — they never scan
the full ring under the lock the hot-path ``add()`` needs.
"""

from __future__ import annotations

import contextvars
import os
import random
import threading
import time
from collections import deque
from contextlib import contextmanager, nullcontext
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "Span",
    "SpanHandle",
    "Tracer",
    "TRACER",
    "TraceContext",
    "TRACE_VAR",
    "TRACEPARENT_HEADER",
    "current_trace_context",
    "current_trace_puid",
    "new_trace_id",
    "new_span_id",
    "parse_traceparent",
    "traceparent_header_value",
    "trace_scope",
    "assemble_tree",
    "assembly_fields",
    "critical_path",
    "phase_decomposition",
    "chrome_trace",
    "trace_document",
    "export_document",
    "span_from_json_dict",
    "partial_markers",
    "device_profile",
    "profile_window_start",
    "profile_window_stop",
    "profile_window_status",
    "ProfileBusyError",
]

#: wire name of the trace context (W3C Trace Context, level 1).  The same
#: name is used as the gRPC metadata key — gRPC metadata keys are
#: lowercase by spec, and W3C defines the header name case-insensitively.
TRACEPARENT_HEADER = "traceparent"


def new_trace_id() -> str:
    """128-bit random trace id, 32 lowercase hex chars (W3C trace-id)."""
    return f"{random.getrandbits(128):032x}"


def new_span_id() -> str:
    """64-bit random span id, 16 lowercase hex chars (W3C parent-id)."""
    return f"{random.getrandbits(64):016x}"


@dataclass
class TraceContext:
    """The active span's identity — what a child span needs to link to its
    parent, and what rides the wire to the next process.  ``puid`` tags
    along so spans opened without an explicit puid (client aggregate hops,
    feedback with a bare payload) inherit the request's correlation id
    instead of guessing from message payloads."""

    trace_id: str
    span_id: str
    sampled: bool = True
    puid: str = ""
    #: tail-capture (postmortem) bit: a sampled-out trace whose root drew
    #: pm=True still records spans — flagged ``pm_only`` and routed ONLY
    #: to the postmortem pending buffer (utils/postmortem.py), never the
    #: tracer ring.  Rides bit 0x02 of the traceparent flags byte; peers
    #: that predate it read only 0x01 and degrade to local-only capture.
    pm: bool = False

    def child(self, puid: str = "") -> "TraceContext":
        return TraceContext(
            trace_id=self.trace_id,
            span_id=new_span_id(),
            sampled=self.sampled,
            puid=puid or self.puid,
            pm=self.pm,
        )


TRACE_VAR: contextvars.ContextVar[Optional[TraceContext]] = contextvars.ContextVar(
    "seldon_tpu_trace", default=None
)


def current_trace_context() -> Optional[TraceContext]:
    return TRACE_VAR.get()


def current_trace_puid() -> str:
    """The active trace's puid ('' when no trace is active) — the
    authoritative correlation id for hops whose payload doesn't carry
    one (aggregate lists, response-less feedback)."""
    ctx = TRACE_VAR.get()
    return ctx.puid if ctx is not None else ""


def traceparent_header_value() -> Optional[str]:
    """The active context serialized per W3C Trace Context
    (``00-<trace-id>-<parent-id>-<flags>``); None when no trace is
    active.  The sampled bit propagates the root's head-sampling decision
    so a sampled-out request records nothing in ANY process."""
    ctx = TRACE_VAR.get()
    if ctx is None or not ctx.trace_id or not ctx.span_id:
        return None
    flags = (0x01 if ctx.sampled else 0x00) | (0x02 if ctx.pm else 0x00)
    return "00-%s-%s-%02x" % (ctx.trace_id, ctx.span_id, flags)


def parse_traceparent(raw: Optional[str]) -> Optional[TraceContext]:
    """Parse an incoming ``traceparent`` value; lenient — absent or
    malformed context means "start a fresh trace" (a bad header must not
    fail a request that would otherwise serve)."""
    if not raw:
        return None
    parts = raw.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if len(version) != 2 or version == "ff":
        return None
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
            return None
        bits = int(flags[:2], 16)
        sampled = bool(bits & 0x01)
        pm = bool(bits & 0x02)
    except ValueError:
        return None
    return TraceContext(trace_id=trace_id, span_id=span_id, sampled=sampled,
                        pm=pm)


def trace_scope(ctx: Optional[TraceContext]):
    """Adopt a remote trace context for the enclosed block (server edges:
    the next span opened becomes the remote caller's child).  No-op when
    ctx is None — the first span then roots a fresh trace."""
    if ctx is None:
        return nullcontext()
    return _ctx_scope(ctx)


@contextmanager
def _ctx_scope(ctx: TraceContext):
    token = TRACE_VAR.set(ctx)
    try:
        yield ctx
    finally:
        TRACE_VAR.reset(token)


@dataclass
class Span:
    puid: str
    name: str  # node name, or "request" / "dispatch" / "batch_queue"
    kind: str  # "request" | "node" | "dispatch" | "client" | "server" | "queue" | "batch"
    method: str  # predict / route / aggregate / ...
    start_s: float  # epoch seconds
    duration_ms: float
    attrs: Dict[str, Any] = field(default_factory=dict)
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str = ""
    #: point-in-time occurrences inside the span: retry attempts, backoff
    #: sleeps, breaker-open short-circuits, degradation fallbacks —
    #: [{"name": ..., "ts": epoch_s, "attrs": {...}}]
    events: List[Dict[str, Any]] = field(default_factory=list)
    #: recorded for the postmortem pending buffer ONLY (the trace was
    #: head-sampled out) — must never reach the tracer ring, indexes, or
    #: per-kind span metrics; deliberately absent from ``to_json_dict``
    pm_only: bool = False

    @property
    def end_s(self) -> float:
        return self.start_s + self.duration_ms / 1e3

    def to_json_dict(self) -> dict:
        out = {
            "puid": self.puid,
            "name": self.name,
            "kind": self.kind,
            "method": self.method,
            "start_s": round(self.start_s, 6),
            "duration_ms": round(self.duration_ms, 3),
        }
        if self.trace_id:
            out["trace_id"] = self.trace_id
        if self.span_id:
            out["span_id"] = self.span_id
        if self.parent_span_id:
            out["parent_span_id"] = self.parent_span_id
        if self.attrs:
            out["attrs"] = self.attrs
        if self.events:
            out["events"] = self.events
        return out


def span_from_json_dict(d: dict) -> Span:
    """Rebuild a :class:`Span` from its ``to_json_dict`` form — the
    federated-trace merge path (gateway/fleet.py) deserializes remote
    participants' spans with this so assembly/critical-path code runs on
    one in-memory shape regardless of which process recorded a span."""
    return Span(
        puid=str(d.get("puid", "") or ""),
        name=str(d.get("name", "") or ""),
        kind=str(d.get("kind", "") or ""),
        method=str(d.get("method", "") or ""),
        start_s=float(d.get("start_s", 0.0) or 0.0),
        duration_ms=float(d.get("duration_ms", 0.0) or 0.0),
        attrs=dict(d.get("attrs") or {}),
        trace_id=str(d.get("trace_id", "") or ""),
        span_id=str(d.get("span_id", "") or ""),
        parent_span_id=str(d.get("parent_span_id", "") or ""),
        events=list(d.get("events") or []),
    )


class SpanHandle(dict):
    """What an open ``tracer.span(...)`` yields.  IS the span's attrs dict
    (``sp["rows"] = 4`` keeps working, and ``isinstance(sp, dict)`` call
    sites stay valid) plus ``event()`` for point-in-time records."""

    def __init__(self, attrs: Optional[dict] = None):
        super().__init__(attrs or {})
        self.events: List[Dict[str, Any]] = []

    def event(self, name: str, **attrs: Any) -> None:
        ev: Dict[str, Any] = {"name": name, "ts": round(time.time(), 6)}
        if attrs:
            ev["attrs"] = attrs
        self.events.append(ev)


class Tracer:
    """Bounded ring of recent spans with puid / trace_id secondary
    indexes.  Thread-safe: spans arrive from the event loop and from
    device-dispatch executor threads."""

    def __init__(
        self,
        capacity: int = 8192,
        enabled: Optional[bool] = None,
        sample: Optional[float] = None,
    ):
        if enabled is None:
            enabled = os.environ.get("SELDON_TPU_TRACE", "") not in ("", "0")
        if sample is None:
            try:
                sample = float(os.environ.get("SELDON_TPU_TRACE_SAMPLE", "1.0"))
            except ValueError:
                sample = 1.0
        self.enabled = bool(enabled)
        self.sample = min(max(float(sample), 0.0), 1.0)
        self.capacity = int(capacity)
        self._spans: deque = deque()
        # secondary indexes share the ring's insertion order, so eviction
        # is popleft on both sides — trace()/by_trace() never scan the
        # ring under the hot-path lock (satellite: the old O(capacity)
        # linear scan serialized queries against add() at volume)
        self._by_puid: Dict[str, deque] = {}
        self._by_trace: Dict[str, deque] = {}
        #: open spans by span_id — event() targets the active one
        self._open: Dict[str, SpanHandle] = {}
        self._lock = threading.Lock()
        self._null = nullcontext()
        self._rng = random  # tests may inject random.Random(seed)
        self.recorded_total = 0
        self.sampled_out_total = 0
        #: telemetry-spine wiring (utils/hotrecord.py), set on the global
        #: TRACER only: ``sink`` routes finished spans into the per-thread
        #: ring (one write per hop, folded off-path); ``drain_hook`` folds
        #: pending records before any query reads.  Local instances keep
        #: the inline synchronous path (both default None).
        self.sink = None
        self.drain_hook = None
        #: tail-capture wiring (utils/postmortem.py), set on the global
        #: TRACER only when postmortem capture is enabled: every folded
        #: span — sampled or pm_only — is offered to the pending buffer
        #: so the keep/drop decision can wait for request completion.
        #: None (the default, and always for local instances) restores
        #: head-sampling behavior bit-for-bit.
        self.pm_hook = None

    # -- admin -------------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def _drain(self) -> None:
        """Fold any ring-pending spans before a read — queries stay
        exactly as current as the old inline path made them."""
        if self.drain_hook is not None:
            self.drain_hook()

    def clear(self) -> None:
        self._drain()  # pending records must not resurrect after clear
        with self._lock:
            self._spans.clear()
            self._by_puid.clear()
            self._by_trace.clear()

    def snapshot(self) -> Dict[str, Any]:
        """Tracer health for ``/stats``."""
        self._drain()
        with self._lock:
            spans = len(self._spans)
            traces = len(self._by_trace)
        return {
            "enabled": self.enabled,
            "sample": self.sample,
            "spans": spans,
            "traces_indexed": traces,
            "capacity": self.capacity,
            "recorded_total": self.recorded_total,
            "sampled_out_total": self.sampled_out_total,
        }

    # -- recording ---------------------------------------------------------

    def span(self, puid: str, name: str, kind: str = "node",
             method: str = "", **attrs):
        if not self.enabled:
            return self._null
        parent = TRACE_VAR.get()
        if parent is not None:
            if not parent.sampled:
                # the root's head decision governs the RING; a pm-flagged
                # trace still records, pm_only, into the pending buffer
                if parent.pm and self.pm_hook is not None:
                    ctx = parent.child(puid)
                    return self._record(puid or ctx.puid, name, kind,
                                        method, attrs, ctx,
                                        parent.span_id, pm_only=True)
                return self._null
            ctx = parent.child(puid)
            parent_id = parent.span_id
        else:
            # head sampling: decided ONCE here, at the trace root; the
            # bit rides the traceparent flags to every other process
            if self.sample < 1.0 and self._rng.random() >= self.sample:
                self.sampled_out_total += 1
                if self.pm_hook is not None:
                    # sampled OUT of the ring but INTO tail capture: the
                    # keep/drop verdict moves to request completion
                    ctx = TraceContext(
                        trace_id=new_trace_id(), span_id=new_span_id(),
                        sampled=False, puid=puid, pm=True,
                    )
                    return self._record(puid, name, kind, method, attrs,
                                        ctx, "", pm_only=True)
                return self._unsampled(puid)
            ctx = TraceContext(
                trace_id=new_trace_id(), span_id=new_span_id(),
                sampled=True, puid=puid, pm=self.pm_hook is not None,
            )
            parent_id = ""
        return self._record(puid or ctx.puid, name, kind, method, attrs,
                            ctx, parent_id)

    @contextmanager
    def _unsampled(self, puid: str):
        """A sampled-out root still sets a (not-sampled) context with real
        ids, so child hops — local and remote — inherit the decision
        instead of re-drawing it and recording orphan subtrees."""
        ctx = TraceContext(
            trace_id=new_trace_id(), span_id=new_span_id(),
            sampled=False, puid=puid,
        )
        token = TRACE_VAR.set(ctx)
        try:
            yield None
        finally:
            TRACE_VAR.reset(token)

    @contextmanager
    def _record(self, puid, name, kind, method, attrs, ctx, parent_id,
                pm_only: bool = False):
        handle = SpanHandle(attrs)
        token = TRACE_VAR.set(ctx)
        self._open[ctx.span_id] = handle
        t0 = time.perf_counter()
        start = time.time()
        try:
            yield handle  # callers may add attrs / events while open
        finally:
            TRACE_VAR.reset(token)
            self._open.pop(ctx.span_id, None)
            self.add(
                Span(
                    puid=puid,
                    name=name,
                    kind=kind,
                    method=method,
                    start_s=start,
                    duration_ms=(time.perf_counter() - t0) * 1e3,
                    attrs=dict(handle),
                    trace_id=ctx.trace_id,
                    span_id=ctx.span_id,
                    parent_span_id=parent_id,
                    events=handle.events,
                    pm_only=pm_only,
                )
            )

    def event(self, name: str, **attrs: Any) -> bool:
        """Attach a point-in-time event to the ACTIVE span (retry attempt,
        backoff sleep, breaker-open short-circuit, fallback).  Returns
        False (and records nothing) when tracing is off, the trace is
        sampled out (and not under postmortem capture), or no span is
        open.  The gate is handle presence, not ``ctx.sampled``: a
        pm_only span HAS an open handle and its events (preempt, breaker
        open, retry) are exactly what the postmortem retention policy
        keys on."""
        if not self.enabled:
            return False
        ctx = TRACE_VAR.get()
        if ctx is None:
            return False
        handle = self._open.get(ctx.span_id)
        if handle is None:
            return False
        handle.event(name, **attrs)
        return True

    def annotate(self, **attrs: Any) -> bool:
        """Merge attrs into the ACTIVE span (status codes, typed-error
        names, shed verdicts — stamped at catch sites so the postmortem
        retention policy can read them at completion).  Same gating as
        :meth:`event`; returns False when nothing was open to annotate."""
        if not self.enabled:
            return False
        ctx = TRACE_VAR.get()
        if ctx is None:
            return False
        handle = self._open.get(ctx.span_id)
        if handle is None:
            return False
        handle.update(attrs)
        return True

    def record_span(
        self,
        name: str,
        kind: str,
        method: str = "",
        start_s: float = 0.0,
        duration_ms: float = 0.0,
        ctx: Optional[TraceContext] = None,
        puid: str = "",
        **attrs: Any,
    ) -> None:
        """Record an already-measured span — for phases whose start and
        end are observed from outside a ``with`` block (micro-batch queue
        wait: enqueue in one task, dequeue in the flush task).  ``ctx``
        (captured at the causal start) parents the span; a not-sampled
        ctx records nothing."""
        if not self.enabled:
            return
        pm_only = False
        if ctx is not None:
            if not ctx.sampled:
                if not (ctx.pm and self.pm_hook is not None):
                    return
                pm_only = True  # pending buffer only, never the ring
            trace_id, parent_id = ctx.trace_id, ctx.span_id
            puid = puid or ctx.puid
        else:
            if self.sample < 1.0 and self._rng.random() >= self.sample:
                return
            trace_id, parent_id = "", ""
        self.add(
            Span(
                puid=puid, name=name, kind=kind, method=method,
                start_s=start_s, duration_ms=duration_ms, attrs=attrs,
                trace_id=trace_id, span_id=new_span_id(),
                parent_span_id=parent_id, pm_only=pm_only,
            )
        )

    def add(self, span: Span) -> None:
        """Record one finished span.  With a telemetry-spine sink wired
        (the process-global TRACER) this is ONE lock-free ring write; the
        drainer folds the span into the ring/indexes off-path via
        ``_fold``.  Without a sink (local tracers, spine disabled) it
        folds inline — identical end state either way."""
        if self.sink is not None:
            self.sink(span)
            return
        self._fold(span)

    def _fold(self, span: Span) -> None:
        hook = self.pm_hook
        if hook is not None:
            try:
                hook(span)  # tail-capture pending buffer (postmortem)
            except Exception:  # noqa: BLE001 - capture must never fail a fold
                pass
        if span.pm_only:
            # head-sampled-out span: it exists ONLY for the pending
            # buffer — ring, indexes, and span metrics stay untouched
            return
        with self._lock:
            self._spans.append(span)
            if span.puid:
                self._by_puid.setdefault(span.puid, deque()).append(span)
            if span.trace_id:
                self._by_trace.setdefault(span.trace_id, deque()).append(span)
            while len(self._spans) > self.capacity:
                old = self._spans.popleft()
                # index deques share insertion order with the ring, so the
                # evictee is the head of its index entries
                for index, key in (
                    (self._by_puid, old.puid), (self._by_trace, old.trace_id)
                ):
                    if not key:
                        continue
                    entries = index.get(key)
                    if entries:
                        entries.popleft()
                        if not entries:
                            del index[key]
            self.recorded_total += 1
        from seldon_core_tpu.utils.telemetry import RECORDER

        RECORDER.record_trace_span(span.kind or "span")

    # -- queries -----------------------------------------------------------

    def trace(self, puid: str) -> List[Span]:
        """All recorded spans of one request, in start order — O(result)
        via the puid index."""
        self._drain()
        with self._lock:
            found = list(self._by_puid.get(puid, ()))
        return sorted(found, key=lambda s: s.start_s)

    def by_trace(self, trace_id: str) -> List[Span]:
        """All recorded spans of one trace, in start order — O(result)."""
        self._drain()
        with self._lock:
            found = list(self._by_trace.get(trace_id, ()))
        return sorted(found, key=lambda s: s.start_s)

    def recent(self, n: int = 100) -> List[Span]:
        self._drain()
        with self._lock:
            return list(self._spans)[-int(n):]


TRACER = Tracer()


# ---------------------------------------------------------------------------
# Trace assembly: span tree, critical path, phase decomposition, export
# ---------------------------------------------------------------------------


def _links(spans: List[Span]) -> Tuple[List[Span], Dict[str, List[Span]]]:
    """(roots, children-by-parent-span-id).  A span whose parent is not in
    the set is a root (the parent lives in a process we can't see, or the
    span predates the causal tracer)."""
    by_id = {s.span_id: s for s in spans if s.span_id}
    kids: Dict[str, List[Span]] = {}
    roots: List[Span] = []
    for s in spans:
        if s.parent_span_id and s.parent_span_id in by_id:
            kids.setdefault(s.parent_span_id, []).append(s)
        else:
            roots.append(s)
    for lst in kids.values():
        lst.sort(key=lambda s: s.start_s)
    return roots, kids


def assemble_tree(spans: List[Span]) -> List[dict]:
    """Nested JSON span tree(s) — one entry per root, children ordered by
    start time."""
    roots, kids = _links(spans)

    def node(s: Span) -> dict:
        out = s.to_json_dict()
        out["children"] = [node(c) for c in kids.get(s.span_id, [])]
        return out

    return [node(r) for r in sorted(roots, key=lambda s: s.start_s)]


#: span kinds that ANNOTATE a window rather than represent exclusive
#: execution: a gen_seq lifecycle timeline overlaps the very dispatch /
#: kv_handoff legs it narrates, so letting it gate the critical path
#: would swallow those legs (it ends last and has no children)
_ANNOTATION_KINDS = frozenset({"gen_seq"})


def critical_path(spans: List[Span]) -> Tuple[Optional[Span], List[Tuple[Span, float]]]:
    """(root, segments): the chain of spans that gated the root's wall
    clock, as ``(span, self_ms)`` contributions.  Walks backward from the
    root's end, descending into the latest-ending child each time — the
    standard span-tree critical path.  Segment self-times sum to the root
    duration exactly (children are clipped to their parent's window), so
    the decomposition accounts for 100% of observed latency.  Annotation
    spans (``_ANNOTATION_KINDS``) stay in the tree but never gate the
    path."""
    roots, kids = _links(spans)
    if not roots:
        return None, []
    # prefer the request-edge span; fall back to the longest root
    # (annotation spans last — an orphaned timeline must not become
    # the root while a real execution root is present)
    root = max(roots, key=lambda s: (
        s.kind == "request", s.kind not in _ANNOTATION_KINDS,
        s.duration_ms))
    segments: List[Tuple[Span, float]] = []

    def visit(sp: Span, cutoff: float, floor: float) -> None:
        # both bounds clip to the parent's window: cross-process clocks
        # skew, and reconstructed spans (queue waits) mix time.time() with
        # perf_counter deltas — without the floor a child that "starts"
        # before its parent would leak time outside the root's duration
        # and break the sums-exactly invariant
        start = max(sp.start_s, floor)
        cursor = min(sp.end_s, cutoff)
        children = sorted(
            (c for c in kids.get(sp.span_id, [])
             if c.kind not in _ANNOTATION_KINDS),
            key=lambda c: c.end_s)
        while children and cursor > start:
            c = children.pop()  # latest-ending child gates the parent
            c_end = min(c.end_s, cursor)
            c_start = max(c.start_s, start)
            if c_end <= c_start or c_start >= cursor:
                continue
            if cursor > c_end:
                segments.append((sp, (cursor - c_end) * 1e3))
            visit(c, c_end, c_start)
            cursor = c_start
        if cursor > start:
            segments.append((sp, (cursor - start) * 1e3))

    visit(root, root.end_s, root.start_s)
    return root, segments


#: span kind -> latency phase of the per-phase decomposition
_PHASE_BY_KIND = {
    "queue": "queue_ms",
    "client": "network_ms",
    "dispatch": "dispatch_ms",
    "batch": "dispatch_ms",
    "kv_handoff": "kv_handoff_ms",
    "kv_import": "kv_handoff_ms",
}


def phase_decomposition(segments: List[Tuple[Span, float]]) -> Dict[str, float]:
    """Bucket critical-path segments into the phases perf work steers by:
    queue (micro-batch wait) / retry+backoff (sleeps between attempts) /
    network (client-span self time: wire + remote queueing we can't see) /
    dispatch (device) / decode (token generation) / kv_handoff (fenced
    KV-block streaming between prefill and decode) / other (host logic).
    Sums to the root duration."""
    phases = {
        "queue_ms": 0.0, "retry_backoff_ms": 0.0, "network_ms": 0.0,
        "dispatch_ms": 0.0, "decode_ms": 0.0, "kv_handoff_ms": 0.0,
        "other_ms": 0.0,
    }
    for sp, self_ms in segments:
        if sp.method in ("generate_stream", "decode"):
            key = "decode_ms"
        else:
            key = _PHASE_BY_KIND.get(sp.kind, "other_ms")
        if sp.kind == "client" and sp.events:
            # backoff sleeps happen inside the client span's wall time but
            # are retry cost, not network cost
            backoff = sum(
                float((e.get("attrs") or {}).get("backoff_ms", 0.0))
                for e in sp.events
                if e.get("name") == "retry"
            )
            take = min(backoff, self_ms)
            phases["retry_backoff_ms"] += take
            self_ms -= take
        phases[key] += self_ms
    phases["total_ms"] = round(sum(phases.values()), 3)
    for k in list(phases):
        phases[k] = round(phases[k], 3)
    return phases


def chrome_trace(
    spans: List[Span],
    process_name: Optional[str] = None,
    pid: int = 0,
    base_s: Optional[float] = None,
) -> dict:
    """Chrome trace-event JSON (the ``{"traceEvents": [...]}`` object
    format) — loadable in Perfetto / chrome://tracing.  Spans become
    complete ('X') events on one lane per (kind, name); span events become
    instant ('i') marks on the owner's lane.

    ``process_name`` labels this span set's Perfetto process track
    (replica/role — the federated export gives every participant its own
    ``pid`` so a multi-process tree renders legibly); ``base_s`` pins the
    timestamp origin so several processes' events share one timeline."""
    events: List[dict] = []
    if not spans:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    base = base_s if base_s is not None else min(s.start_s for s in spans)
    lanes: Dict[Tuple[str, str], int] = {}
    for s in sorted(spans, key=lambda x: x.start_s):
        tid = lanes.setdefault((s.kind, s.name), len(lanes) + 1)
        args: Dict[str, Any] = dict(s.attrs)
        if s.puid:
            args["puid"] = s.puid
        if s.span_id:
            args["span_id"] = s.span_id
        if s.parent_span_id:
            args["parent_span_id"] = s.parent_span_id
        events.append({
            "name": f"{s.name}:{s.method}" if s.method else s.name,
            "cat": s.kind or "span",
            "ph": "X",
            "ts": round((s.start_s - base) * 1e6, 1),
            "dur": round(s.duration_ms * 1e3, 1),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
        for ev in s.events:
            events.append({
                "name": ev.get("name", "event"),
                "cat": "event",
                "ph": "i",
                "s": "t",
                "ts": round((float(ev.get("ts", s.start_s)) - base) * 1e6, 1),
                "pid": pid,
                "tid": tid,
                "args": ev.get("attrs", {}),
            })
    for (kind, name), tid in lanes.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": f"{kind}:{name}"},
        })
    if process_name:
        events.append({
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": process_name},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def partial_markers(spans: List[Span], named_query: bool) -> dict:
    """The partial-trace contract (fleet observability): a query that
    names a specific request must never answer an empty or silently
    truncated result when the ring evicted part (or all) of the subtree.
    Returns ``{"partial": bool, "missing": [...]}`` — ``missing`` lists
    the parent span ids that are referenced but absent (evicted locally
    or living in a process this tracer can't see)."""
    if not named_query:
        return {"partial": False, "missing": []}
    present = {s.span_id for s in spans if s.span_id}
    orphans = sorted({
        s.parent_span_id for s in spans
        if s.parent_span_id and s.parent_span_id not in present
    })
    missing: List[Any] = [
        {"parent_span_id": p, "reason": "parent span not found "
         "(evicted from the ring or recorded in another process)"}
        for p in orphans
    ]
    if not spans:
        missing.append({"reason": "no spans found for this query "
                        "(evicted from the ring, or never sampled)"})
    return {"partial": bool(missing), "missing": missing}


def _select_spans(
    tracer: Tracer, puid: str = "", trace_id: str = "", limit: int = 100
) -> List[Span]:
    """Spans for one request: by trace_id directly, or by puid widened to
    every trace the puid participates in (picks up same-trace spans that
    carry no puid, e.g. flush/dispatch internals)."""
    if trace_id:
        return tracer.by_trace(trace_id)
    if not puid:
        return tracer.recent(limit)
    spans = list(tracer.trace(puid))
    seen = {id(s) for s in spans}
    for tid in {s.trace_id for s in spans if s.trace_id}:
        for s in tracer.by_trace(tid):
            if id(s) not in seen:
                seen.add(id(s))
                spans.append(s)
    return sorted(spans, key=lambda s: s.start_s)


def assembly_fields(spans: List[Span]) -> Dict[str, Any]:
    """The named-query assembly block shared by the local and federated
    ``GET /trace`` bodies: partial markers, nested tree, critical path,
    per-phase decomposition, root identity.  One implementation so the
    two surfaces can never drift."""
    doc: Dict[str, Any] = {}
    # a named query whose subtree was (partly) evicted answers the
    # partial tree with an explicit marker, never a silent empty
    doc.update(partial_markers(spans, named_query=True))
    doc["tree"] = assemble_tree(spans)
    root, segments = critical_path(spans)
    doc["critical_path"] = [
        {
            "span_id": sp.span_id,
            "name": sp.name,
            "kind": sp.kind,
            "method": sp.method,
            "self_ms": round(self_ms, 3),
        }
        for sp, self_ms in segments
    ]
    doc["phases"] = phase_decomposition(segments)
    if root is not None:
        doc["root_span_id"] = root.span_id
        doc["root_duration_ms"] = round(root.duration_ms, 3)
    return doc


def trace_document(
    tracer: Tracer, puid: str = "", trace_id: str = "", limit: int = 100
) -> dict:
    """The ``GET /trace`` body: flat spans (back-compat) plus the
    assembled tree, critical path, and per-phase decomposition when a
    specific request is named."""
    spans = _select_spans(tracer, puid, trace_id, limit)
    doc: Dict[str, Any] = {
        "enabled": tracer.enabled,
        "sample": tracer.sample,
        "spans": [s.to_json_dict() for s in spans],
    }
    if puid or trace_id:
        doc.update(assembly_fields(spans))
    return doc


def export_document(
    tracer: Tracer, puid: str = "", trace_id: str = "",
    limit: int = 1000, process_name: Optional[str] = None,
) -> dict:
    """The ``GET /trace/export`` body — Chrome trace-event JSON.
    ``process_name`` labels this process's Perfetto track (replica/role)
    so exports merged across a mesh render legibly."""
    return chrome_trace(
        _select_spans(tracer, puid, trace_id, limit),
        process_name=process_name,
    )


# ---------------------------------------------------------------------------
# Device profiling
# ---------------------------------------------------------------------------

_PROFILE_LOCK = threading.Lock()


@contextmanager
def device_profile(logdir: str):
    """Capture a jax.profiler trace (XLA op timeline, TPU utilisation) for
    the enclosed block; view with TensorBoard/xprof.  This is the
    device-level complement to host spans: inside one compiled graph the
    per-op timing only exists here.

    Re-entrancy safe: ``jax.profiler.start_trace`` raises when a trace is
    already active, so a nested or concurrent profile request records a
    ``device_profile_skipped`` span event (or a zero-length span when no
    span is open) and the block runs unprofiled."""
    import jax

    if not _PROFILE_LOCK.acquire(blocking=False):
        if not TRACER.event(
            "device_profile_skipped", logdir=str(logdir),
            reason="profiler already active",
        ):
            TRACER.record_span(
                "device_profile_skipped", kind="profile",
                start_s=time.time(), duration_ms=0.0,
                ctx=current_trace_context(), logdir=str(logdir),
            )
        yield
        return
    try:
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
    finally:
        _PROFILE_LOCK.release()


# ---------------------------------------------------------------------------
# Coordinated profiling windows (fleet observability)
# ---------------------------------------------------------------------------

class ProfileBusyError(RuntimeError):
    """A profile window (or a ``device_profile`` block) is already
    active in this process — overlapping windows are refused, never
    queued: the second window's data would be attributed to the first."""


#: hard ceiling on a window's duration — a start whose stop never
#: arrives must not profile forever (profiling has real overhead)
def _profile_max_s() -> float:
    try:
        return float(os.environ.get("SELDON_TPU_PROFILE_MAX_S", "") or 60.0)
    except ValueError:
        return 60.0


_WINDOW_STATE_LOCK = threading.Lock()
_WINDOW: Dict[str, Any] = {
    "active": False, "logdir": None, "started_s": 0.0,
    "duration_s": 0.0, "window": "", "timer": None, "last": None,
}


def profile_window_start(logdir: str, duration_s: float = 0.0,
                         window: str = "") -> Dict[str, Any]:
    """Open a bounded-duration ``jax.profiler`` trace for THIS process —
    the per-engine half of a coordinated fleet profile window
    (gateway/fleet.py fans one ``POST /profile/start`` out to every
    replica so the mesh is captured simultaneously).

    Holds the module profile lock for the window's lifetime, so a
    concurrent ``device_profile`` block degrades to a span event exactly
    as it does against any active profiler session.  The window closes
    on ``profile_window_stop()`` or automatically after ``duration_s``
    (clamped to ``SELDON_TPU_PROFILE_MAX_S``).  Raises
    :class:`ProfileBusyError` when a window/profile is already active —
    overlapping windows are refused by contract."""
    import jax

    duration_s = float(duration_s or 0.0)
    max_s = _profile_max_s()
    if duration_s <= 0.0 or duration_s > max_s:
        duration_s = max_s
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise ProfileBusyError(
            "a profile window or device_profile block is already active "
            "in this process — stop it before opening another")
    try:
        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)
    except BaseException:
        _PROFILE_LOCK.release()
        raise
    with _WINDOW_STATE_LOCK:
        _WINDOW.update(
            active=True, logdir=str(logdir), started_s=time.time(),
            duration_s=duration_s, window=window or new_span_id(),
        )
        timer = threading.Timer(duration_s, profile_window_stop)
        timer.daemon = True
        _WINDOW["timer"] = timer
        timer.start()
        return {
            "active": True, "window": _WINDOW["window"],
            "artifact": _WINDOW["logdir"],
            "started_s": _WINDOW["started_s"],
            "duration_s": duration_s,
        }


def profile_window_stop() -> Dict[str, Any]:
    """Close the active window (idempotent — the auto-stop timer and an
    explicit stop may race; whichever runs second is a no-op).  Returns
    the finished window's manifest entry, or the LAST one when no window
    is active."""
    import jax

    with _WINDOW_STATE_LOCK:
        if not _WINDOW["active"]:
            return {"active": False, "last": _WINDOW["last"]}
        timer = _WINDOW.pop("timer", None)
        if timer is not None:
            timer.cancel()
        _WINDOW["timer"] = None
        _WINDOW["active"] = False
        entry = {
            "window": _WINDOW["window"],
            "artifact": _WINDOW["logdir"],
            "started_s": _WINDOW["started_s"],
            "duration_s": round(time.time() - _WINDOW["started_s"], 3),
        }
        _WINDOW["last"] = entry
    try:
        jax.profiler.stop_trace()
    except Exception as e:  # noqa: BLE001 - backend already stopped
        entry = dict(entry, error=f"{type(e).__name__}: {e}")
        with _WINDOW_STATE_LOCK:
            _WINDOW["last"] = entry
    finally:
        _PROFILE_LOCK.release()
    return {"active": False, "last": entry}


def profile_window_start_request(body: dict) -> Dict[str, Any]:
    """The engine-side ``POST /profile/start`` contract shared by the
    aiohttp and fast HTTP lanes: body ``{"duration_s", "window",
    "logdir"}`` (all optional) opens a bounded window in THIS process
    and returns its manifest entry.  Raises :class:`ProfileBusyError`
    on overlap — the route answers 409."""
    import tempfile

    window = str(body.get("window", "") or "") or new_span_id()
    base = os.environ.get("SELDON_TPU_PROFILE_DIR", "") or \
        os.path.join(tempfile.gettempdir(), "seldon-tpu-profiles")
    logdir = str(body.get("logdir", "") or "")
    # a caller-supplied logdir must stay INSIDE the configured profile
    # dir — the route is reachable by any client that can reach the
    # engine, and an arbitrary path would let it create directories and
    # write profiler artifacts anywhere the engine user can.  Anything
    # escaping the base falls back to the derived default.
    if logdir:
        base_real = os.path.realpath(base)
        if not os.path.realpath(
                os.path.join(base, logdir)).startswith(
                base_real + os.sep):
            logdir = ""
        else:
            logdir = os.path.join(base, logdir)
    if not logdir:
        logdir = os.path.join(base, window, f"engine-{os.getpid()}")
    try:
        duration_s = float(body.get("duration_s", 0.0) or 0.0)
    except (TypeError, ValueError):
        duration_s = 0.0
    return profile_window_start(logdir, duration_s, window=window)


def profile_window_status() -> Dict[str, Any]:
    """The process-local window state for ``GET /profile``."""
    with _WINDOW_STATE_LOCK:
        return {
            "active": _WINDOW["active"],
            "window": _WINDOW["window"] if _WINDOW["active"] else None,
            "artifact": _WINDOW["logdir"] if _WINDOW["active"] else None,
            "started_s": (
                _WINDOW["started_s"] if _WINDOW["active"] else None
            ),
            "duration_s": (
                _WINDOW["duration_s"] if _WINDOW["active"] else None
            ),
            "last": _WINDOW["last"],
        }
