"""Request tracing — puid-correlated spans + TPU device profiling.

The reference has no distributed tracing: it logs per-hop call durations
(engine InternalPredictionService.java:267-268) and threads ``puid``
through every hop and the Kafka firehose as the correlation id
(engine PredictionService.java:52-58).  This module makes that design
first-class:

  * ``Tracer`` records bounded in-memory spans — one per node call in host
    mode, one per device dispatch in compiled mode, one per request at the
    engine edge — each tagged with the request ``puid`` so a trace can be
    reassembled across the graph (and across processes, since the puid rides
    the wire in ``meta``).
  * The engine exposes ``GET /trace?puid=`` and enable/disable admin
    endpoints (runtime/rest.py).
  * ``device_profile`` wraps ``jax.profiler`` tracing for XLA/TPU-level
    timelines (the compiled graph is ONE XLA program, so intra-graph timing
    lives in the device profile, not host spans — that's the TPU-native
    analogue of the reference's per-microservice-hop latencies).

Tracing is off by default (`SELDON_TPU_TRACE=1` or ``TRACER.enable()``);
disabled spans cost one attribute load and return a shared null context.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager, nullcontext
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = ["Span", "Tracer", "TRACER", "device_profile"]


@dataclass
class Span:
    puid: str
    name: str  # node name, or "request" / "dispatch"
    kind: str  # "request" | "node" | "dispatch" | "client"
    method: str  # predict / route / aggregate / ...
    start_s: float  # epoch seconds
    duration_ms: float
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_json_dict(self) -> dict:
        out = {
            "puid": self.puid,
            "name": self.name,
            "kind": self.kind,
            "method": self.method,
            "start_s": round(self.start_s, 6),
            "duration_ms": round(self.duration_ms, 3),
        }
        if self.attrs:
            out["attrs"] = self.attrs
        return out


class Tracer:
    """Bounded ring of recent spans, queryable by puid.  Thread-safe: spans
    arrive from the event loop and from device-dispatch executor threads."""

    def __init__(self, capacity: int = 8192, enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("SELDON_TPU_TRACE", "") not in ("", "0")
        self.enabled = bool(enabled)
        self._spans: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._null = nullcontext()

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def span(self, puid: str, name: str, kind: str = "node",
             method: str = "", **attrs):
        if not self.enabled:
            return self._null
        return self._record(puid, name, kind, method, attrs)

    @contextmanager
    def _record(self, puid, name, kind, method, attrs):
        t0 = time.perf_counter()
        start = time.time()
        try:
            yield attrs  # callers may add attrs while the span is open
        finally:
            self.add(
                Span(
                    puid=puid,
                    name=name,
                    kind=kind,
                    method=method,
                    start_s=start,
                    duration_ms=(time.perf_counter() - t0) * 1e3,
                    attrs=attrs,
                )
            )

    def add(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def trace(self, puid: str) -> List[Span]:
        """All recorded spans of one request, in start order."""
        with self._lock:
            found = [s for s in self._spans if s.puid == puid]
        return sorted(found, key=lambda s: s.start_s)

    def recent(self, n: int = 100) -> List[Span]:
        with self._lock:
            return list(self._spans)[-int(n):]


TRACER = Tracer()


@contextmanager
def device_profile(logdir: str):
    """Capture a jax.profiler trace (XLA op timeline, TPU utilisation) for
    the enclosed block; view with TensorBoard/xprof.  This is the
    device-level complement to host spans: inside one compiled graph the
    per-op timing only exists here."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
