"""Tail-sampled postmortem recorder — keep the worst requests, explain
them automatically.

The tracer head-samples ONCE at the trace root (``SELDON_TPU_TRACE_-
SAMPLE``), so at production rates the exact requests an operator needs —
p99 outliers, errors, sheds, preemptions, stream re-homes — are discarded
with probability 1−p before anyone knows they were interesting.  This
module moves the keep/drop decision to request COMPLETION:

  * Every request's spans land in a cheap bounded *pending buffer*
    regardless of the head verdict.  Sampled spans ride their normal
    ``Tracer._fold`` pass; head-sampled-OUT spans are still recorded,
    flagged ``pm_only``, and reach ONLY this buffer (utils/tracing.py
    routes them around the ring, indexes, and span metrics — the
    existing surfaces never see them).  The capture flag rides bit 0x02
    of the W3C traceparent flags byte so child processes feed their own
    pending buffers too; old peers read only 0x01 and degrade to
    local-only postmortems.
  * At completion (the ``kind="request"`` span closing) a retention
    policy keeps the FULL trace iff the request was anomalous: typed
    error / 5xx, a shed/brownout refusal, latency over the tier SLO
    budget, any leg exceeding ``SELDON_TPU_POSTMORTEM_EXCESS_X`` (3x)
    the autopilot's predicted wall for its shape, a genserver
    preemption, a breaker-open short-circuit, a gateway failover /
    stream re-home or lease transition (reported out-of-band via
    :meth:`PostmortemRecorder.note`), or a small reservoir-sampled
    healthy baseline for comparison.
  * Kept exemplars are COPIED OUT at keep time (``to_json_dict``), so a
    postmortem document is immutable once kept — trace-ring eviction
    can never degrade it into a partial tree after the fact.
  * An automatic explainer enriches each kept exemplar: the per-phase
    critical-path decomposition (queue / retry / network / dispatch /
    decode / kv_handoff) diffed against the rolling per-key p50 so the
    document NAMES the guilty phase and its excess milliseconds, plus
    autopilot predicted-vs-actual per dispatch, the p2c pick candidates
    and scores, the genserver per-sequence ledger slice (the gen_seq
    lifecycle timeline), and the request's ``/costs`` attribution row.

Kill switch: ``SELDON_TPU_POSTMORTEM=0`` leaves ``TRACER.pm_hook``
unset (utils/hotrecord.py wires it) — head sampling then behaves
bit-for-bit as before this module existed.  Everything here is bounded:
pending traces/spans, kept exemplars, baseline slots, synthetic notes,
and the per-key baseline table are all capped, with drops counted.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from seldon_core_tpu.utils.tracing import (
    TRACER,
    Span,
    assembly_fields,
)

__all__ = ["PostmortemRecorder", "POSTMORTEM", "postmortem_enabled"]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def postmortem_enabled() -> bool:
    """Capture is ON by default (it is inert unless tracing itself is
    enabled — no spans exist otherwise); ``SELDON_TPU_POSTMORTEM=0``
    restores head-sampling behavior bit-for-bit."""
    return os.environ.get("SELDON_TPU_POSTMORTEM", "1") not in ("", "0")


#: request tier -> multiple of the base SLO budget (interactive requests
#: are judged at 1x; batch and offline tolerate proportionally more wall
#: before a postmortem calls them anomalous).  The repo has no per-tier
#: SLO objectives — these factors ARE the tier budgets, documented in
#: docs/operations.md.
_TIER_SLO_X = {"interactive": 1.0, "batch": 4.0, "offline": 16.0}

#: out-of-band note reasons the retention policy accepts (anything else
#: still keeps, labelled "note" — a typo must not silently drop signal)
_NOTE_REASONS = frozenset({"failover", "rehome", "lease", "breaker"})

#: span kinds that complete their trace.  "request" is the per-request
#: root every Python lane opens; "plane" is the native C++ data plane's
#: per-BATCH root (runtime/nativeplane.py) — C++ never surfaces request
#: boundaries to Python, so on that lane the completable unit is the
#: batch: a failed or over-SLO native dispatch is still retained and
#: explained, only the per-request split degrades (same contract as the
#: cost ledger's anonymous-tenant booking)
_ROOT_KINDS = frozenset({"request", "plane"})


class _PhaseP50:
    """Tiny sliding-window median per phase — the 'expected' side of the
    explainer's phase diff.  A plain bounded deque per phase; median by
    sort at read time (windows are <= 128 samples, read off-path)."""

    __slots__ = ("window", "_by_phase")

    def __init__(self, window: int = 128):
        self.window = int(window)
        self._by_phase: Dict[str, deque] = {}

    def observe(self, phases: Dict[str, float]) -> None:
        for ph, ms in phases.items():
            if ph == "total_ms":
                continue
            dq = self._by_phase.get(ph)
            if dq is None:
                dq = self._by_phase[ph] = deque(maxlen=self.window)
            dq.append(float(ms))

    def p50(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for ph, dq in self._by_phase.items():
            if dq:
                vals = sorted(dq)
                out[ph] = round(vals[len(vals) // 2], 3)
        return out


class _Pending:
    """One trace's pending capture: spans seen so far, out-of-band notes,
    and the last-touch timestamp the TTL sweep judges."""

    __slots__ = ("spans", "notes", "ts", "truncated")

    def __init__(self):
        self.spans: List[Span] = []
        self.notes: List[Dict[str, Any]] = []
        self.ts = time.time()
        self.truncated = 0


class PostmortemRecorder:
    """Deferred (tail-based) retention over the span/hotrecord machinery.

    ``offer(span)`` is the single capture entry point — wired as
    ``TRACER.pm_hook`` so every folded span (sampled or pm_only) passes
    through; it appends to the bounded pending buffer and, when the
    span is a request root, runs the retention policy.  ``note()`` is
    the out-of-band signal path for anomalies that fire with no span
    open (stream re-home, lease transitions, breaker trips observed by
    the balancer).  Thread-safe: offers arrive from the spine drainer
    and inline folds; notes from the event loop."""

    def __init__(
        self,
        enabled: Optional[bool] = None,
        excess_x: Optional[float] = None,
        slo_ms: Optional[float] = None,
        ttl_s: Optional[float] = None,
        pending_traces: Optional[int] = None,
        pending_spans: Optional[int] = None,
        keep: Optional[int] = None,
        baseline: Optional[int] = None,
    ):
        self.enabled = postmortem_enabled() if enabled is None else bool(enabled)
        self.excess_x = (
            _env_float("SELDON_TPU_POSTMORTEM_EXCESS_X", 3.0)
            if excess_x is None else float(excess_x))
        base_slo = (
            _env_float("SELDON_TPU_POSTMORTEM_SLO_MS",
                       _env_float("SELDON_TPU_SLO_P99_MS", 0.0))
            if slo_ms is None else float(slo_ms))
        self.slo_ms = max(base_slo, 0.0)  # 0 = the SLO trigger is inert
        self.ttl_s = (_env_float("SELDON_TPU_POSTMORTEM_TTL_S", 30.0)
                      if ttl_s is None else float(ttl_s))
        self.pending_traces = (
            _env_int("SELDON_TPU_POSTMORTEM_PENDING", 256)
            if pending_traces is None else int(pending_traces))
        self.pending_spans = (
            _env_int("SELDON_TPU_POSTMORTEM_SPANS", 128)
            if pending_spans is None else int(pending_spans))
        self.keep_cap = (_env_int("SELDON_TPU_POSTMORTEM_KEEP", 64)
                         if keep is None else int(keep))
        self.baseline_k = (_env_int("SELDON_TPU_POSTMORTEM_BASELINE", 8)
                           if baseline is None else int(baseline))
        self._lock = threading.Lock()
        self._pending: "OrderedDict[str, _Pending]" = OrderedDict()
        #: anomalous exemplars by trace_id (a later, outer root completion
        #: re-keeps and REPLACES — the widest view of the trace wins)
        self._kept: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        #: Algorithm-R reservoir of healthy exemplars (size baseline_k)
        self._baseline: List[Dict[str, Any]] = []
        self._healthy_n = 0
        #: traceless notes become bounded synthetic exemplars — a lease
        #: flap must not evict real request postmortems
        self._synthetic: deque = deque(maxlen=8)
        #: rolling per-key phase medians — "expected" for the phase diff
        self._phase_p50: "OrderedDict[str, _PhaseP50]" = OrderedDict()
        self._phase_keys_cap = 64
        self._rng = random  # tests may inject random.Random(seed)
        # counters
        self.kept_total: Dict[str, int] = {}
        self.dropped_total = 0
        self.completed_total = 0
        self.noted_total = 0
        self.offer_total = 0
        self.truncated_spans = 0
        #: sampled capture cost (1 in 32 offers measured) — the
        #: postmortem_capture_overhead_ms bench axis
        self._offer_ms: deque = deque(maxlen=256)

    # -- capture ---------------------------------------------------------

    def offer(self, span: Span) -> None:
        """One folded span into the pending buffer — O(1) append under a
        short lock, off the request hot path (spine drainer / fold).
        Never raises (the fold guards it too)."""
        if not self.enabled:
            return
        tid = span.trace_id
        if not tid:
            return  # no trace linkage (flush internals) — nothing to keep
        probe = (self.offer_total & 31) == 0
        t0 = time.perf_counter() if probe else 0.0
        with self._lock:
            self.offer_total += 1
            pend = self._pending.get(tid)
            if pend is None:
                while len(self._pending) >= max(self.pending_traces, 1):
                    self._pending.popitem(last=False)
                    self.dropped_total += 1
                    self._record_dropped()
                pend = _Pending()
                self._pending[tid] = pend
            if len(pend.spans) < self.pending_spans:
                pend.spans.append(span)
            else:
                pend.truncated += 1
                self.truncated_spans += 1
            pend.ts = time.time()
        if span.kind in _ROOT_KINDS:
            self._complete(tid, span)
        if probe:
            self._offer_ms.append((time.perf_counter() - t0) * 1e3)
            self._sweep()

    def note(self, trace_id: str, reason: str, **attrs: Any) -> None:
        """Out-of-band anomaly signal for paths with no open span: stream
        re-home / hedged-unary failover, coordinator lease transitions,
        breaker trips seen from the balancer.  With a trace_id the note
        joins that trace's pending record (and re-triggers retention if
        the root already completed — pending buffers are TTL-evicted,
        not cleared on a drop verdict, exactly so late signals can still
        rescue a trace).  With no trace_id the note becomes a bounded
        synthetic exemplar so the signal still surfaces in
        ``GET /postmortems``."""
        if not self.enabled:
            return
        entry: Dict[str, Any] = {
            "reason": str(reason), "ts": round(time.time(), 6)}
        if attrs:
            entry["attrs"] = attrs
        root: Optional[Span] = None
        with self._lock:
            self.noted_total += 1
            if not trace_id:
                doc = {
                    "puid": str(attrs.get("puid", "") or ""),
                    "trace_id": "",
                    "kept_at_s": entry["ts"],
                    "reason": entry["reason"],
                    "reasons": [entry["reason"]],
                    "synthetic": True,
                    "note": entry,
                    "spans": [],
                    "pinned_spans": 0,
                }
                self._synthetic.append(doc)
                self.kept_total[entry["reason"]] = (
                    self.kept_total.get(entry["reason"], 0) + 1)
            else:
                pend = self._pending.get(trace_id)
                if pend is None:
                    while len(self._pending) >= max(self.pending_traces, 1):
                        self._pending.popitem(last=False)
                        self.dropped_total += 1
                        self._record_dropped()
                    pend = _Pending()
                    self._pending[trace_id] = pend
                if len(pend.notes) < 16:
                    pend.notes.append(entry)
                pend.ts = time.time()
                for s in pend.spans:
                    if s.kind in _ROOT_KINDS:
                        root = s
                        break
        if not trace_id:
            self._record_kept(entry["reason"])
        elif root is not None:
            # the root already completed and may have been judged healthy
            # before this signal arrived — re-run retention (no recount)
            self._complete(trace_id, root, recount=False)

    # -- retention policy ------------------------------------------------

    def _complete(self, trace_id: str, root: Span,
                  recount: bool = True) -> None:
        with self._lock:
            pend = self._pending.get(trace_id)
            spans = list(pend.spans) if pend is not None else [root]
            notes = list(pend.notes) if pend is not None else []
            truncated = pend.truncated if pend is not None else 0
            if recount:
                self.completed_total += 1
            key = "%s:%s" % (root.name, root.method)
            table = self._phase_p50.get(key)
            baseline_p50 = table.p50() if table is not None else {}
        reasons = self._evaluate(root, spans, notes)
        asm = assembly_fields(spans)
        phases = asm.get("phases") or {}
        if reasons:
            doc = self._explain(root, spans, reasons, notes, asm,
                                baseline_p50, truncated)
            with self._lock:
                self._kept[trace_id] = doc
                while len(self._kept) > max(self.keep_cap, 1):
                    self._kept.popitem(last=False)
                self.kept_total[reasons[0]] = (
                    self.kept_total.get(reasons[0], 0) + 1)
            self._record_kept(reasons[0])
        elif recount and self.baseline_k > 0:
            # Algorithm R over healthy completions: exemplar i survives
            # into one of k slots with probability k/i — a small always-
            # fresh healthy baseline to diff anomalies against
            with self._lock:
                self._healthy_n += 1
                n = self._healthy_n
            if len(self._baseline) < self.baseline_k:
                slot: Optional[int] = len(self._baseline)
            else:
                j = self._rng.randrange(n)
                slot = j if j < self.baseline_k else None
            if slot is not None:
                doc = self._explain(root, spans, ["baseline"], notes, asm,
                                    baseline_p50, truncated)
                with self._lock:
                    if slot >= len(self._baseline):
                        self._baseline.append(doc)
                    else:
                        self._baseline[slot] = doc
                self._record_kept("baseline")
        if recount and phases:
            # the rolling "expected" fold happens AFTER judgement so an
            # exemplar's excess is measured against its predecessors, not
            # softened by its own contribution
            with self._lock:
                table = self._phase_p50.get(key)
                if table is None:
                    table = self._phase_p50[key] = _PhaseP50()
                else:
                    self._phase_p50.move_to_end(key)
                while len(self._phase_p50) > self._phase_keys_cap:
                    self._phase_p50.popitem(last=False)
                table.observe(phases)

    def _slo_budget_ms(self, tier: Any) -> float:
        if self.slo_ms <= 0:
            return 0.0
        return self.slo_ms * _TIER_SLO_X.get(str(tier or "interactive"), 1.0)

    def _evaluate(self, root: Span, spans: List[Span],
                  notes: List[Dict[str, Any]]) -> List[str]:
        """The retention verdict: ordered anomaly reasons, [] = drop."""
        reasons: List[str] = []
        attrs = root.attrs or {}
        status: Optional[int] = None
        try:
            raw = attrs.get("status")
            status = int(raw) if raw is not None else None
        except (TypeError, ValueError):
            status = None
        if attrs.get("shed"):
            reasons.append("shed")
        elif attrs.get("error") or (status is not None and status >= 500):
            reasons.append("error")
        budget = self._slo_budget_ms(attrs.get("tier"))
        if budget and root.duration_ms > budget:
            reasons.append("slo")
        for s in spans:
            pred = (s.attrs or {}).get("autopilot_predicted_ms")
            try:
                pred_f = float(pred) if pred is not None else 0.0
            except (TypeError, ValueError):
                pred_f = 0.0
            if pred_f > 0 and s.duration_ms > self.excess_x * pred_f:
                reasons.append("autopilot_excess")
                break
        names = set()
        for s in spans:
            for ev in s.events or ():
                names.add(ev.get("name"))
        if "preempt" in names:
            reasons.append("preemption")
        if "breaker_open" in names and "breaker" not in reasons:
            reasons.append("breaker")
        for n in notes:
            r = str(n.get("reason") or "")
            r = r if r in _NOTE_REASONS else (r or "note")
            if r not in reasons:
                reasons.append(r)
        return reasons

    # -- the explainer ---------------------------------------------------

    def _explain(self, root: Span, spans: List[Span], reasons: List[str],
                 notes: List[Dict[str, Any]], asm: Dict[str, Any],
                 baseline_p50: Dict[str, float],
                 truncated: int) -> Dict[str, Any]:
        """Build the immutable postmortem document: copied-out spans, the
        assembled tree/critical path, and the guilty-phase diff against
        the rolling per-key p50."""
        phases = dict(asm.get("phases") or {})
        excess: Dict[str, float] = {}
        for ph, ms in phases.items():
            if ph == "total_ms":
                continue
            excess[ph] = round(float(ms) - baseline_p50.get(ph, 0.0), 3)
        guilty: Optional[str] = None
        if excess:
            worst = max(excess, key=lambda p: excess[p])
            if excess[worst] > 0:
                guilty = worst
            else:
                # nothing exceeds expectation (errors/sheds fail fast) —
                # name the biggest phase so the document still points
                guilty = max(phases, key=lambda p: (
                    phases[p] if p != "total_ms" else -1.0))
        autopilot: List[Dict[str, Any]] = []
        for s in spans:
            pred = (s.attrs or {}).get("autopilot_predicted_ms")
            try:
                pred_f = float(pred) if pred is not None else 0.0
            except (TypeError, ValueError):
                pred_f = 0.0
            if pred_f > 0:
                autopilot.append({
                    "name": s.name,
                    "kind": s.kind,
                    "predicted_ms": round(pred_f, 3),
                    "actual_ms": round(s.duration_ms, 3),
                    "ratio": round(s.duration_ms / pred_f, 2),
                })
        p2c: Optional[Dict[str, Any]] = None
        for s in spans:
            a = s.attrs or {}
            if "p2c_candidates" in a or "replica" in a:
                p2c = {k: a[k] for k in
                       ("replica", "p2c_candidates", "p2c_scores")
                       if k in a}
                break
        gen_ledger = [
            {
                "name": s.name,
                "method": s.method,
                "duration_ms": round(s.duration_ms, 3),
                "events": list(s.events or ()),
            }
            for s in spans if s.kind == "gen_seq"
        ]
        cost_row = None
        tenant = next(
            (str((s.attrs or {}).get("tenant"))
             for s in spans if (s.attrs or {}).get("tenant")), "")
        if tenant:
            try:
                from seldon_core_tpu.utils.costledger import LEDGER

                for row in LEDGER.document().get("tenants") or ():
                    if row.get("tenant") == tenant:
                        cost_row = row
                        break
            except Exception:  # noqa: BLE001 - attribution is best-effort
                cost_row = None
        doc: Dict[str, Any] = {
            "puid": root.puid,
            "trace_id": root.trace_id,
            "kept_at_s": round(time.time(), 6),
            "reason": reasons[0],
            "reasons": list(reasons),
            "root": {
                "name": root.name,
                "kind": root.kind,
                "method": root.method,
                "duration_ms": round(root.duration_ms, 3),
                "start_s": round(root.start_s, 6),
                "attrs": dict(root.attrs or {}),
            },
            # copy-out AT KEEP TIME: ring eviction can never degrade a
            # kept exemplar into a partial tree after the fact
            "spans": [s.to_json_dict() for s in spans],
            "pinned_spans": len(spans),
            "truncated_spans": truncated,
            "tree": asm.get("tree"),
            "critical_path": asm.get("critical_path"),
            "phases": phases,
            "partial": asm.get("partial", False),
            "missing": asm.get("missing", []),
            "explain": {
                "guilty_phase": guilty,
                "excess_ms": excess.get(guilty, 0.0) if guilty else 0.0,
                "phase_excess_ms": excess,
                "baseline_p50_ms": baseline_p50,
                "autopilot": autopilot,
                "p2c": p2c,
                "gen_ledger": gen_ledger,
                "cost_row": cost_row,
                "notes": list(notes),
            },
        }
        return doc

    # -- housekeeping ----------------------------------------------------

    def _sweep(self) -> None:
        """TTL-evict idle pending traces (requests that never completed:
        crashed workers, abandoned streams) — counted as drops."""
        deadline = time.time() - self.ttl_s
        with self._lock:
            stale = [tid for tid, p in self._pending.items()
                     if p.ts < deadline]
            for tid in stale:
                del self._pending[tid]
                self.dropped_total += 1
        for _ in stale:
            self._record_dropped()

    def _record_kept(self, reason: str) -> None:
        try:
            from seldon_core_tpu.utils.telemetry import RECORDER

            RECORDER.record_postmortem_kept(reason)
        except Exception:  # noqa: BLE001 - metrics must not fail capture
            pass

    def _record_dropped(self) -> None:
        try:
            from seldon_core_tpu.utils.telemetry import RECORDER

            RECORDER.record_postmortem_dropped()
        except Exception:  # noqa: BLE001
            pass

    def publish_gauges(self) -> None:
        """Pinned-span accounting, refreshed from the spine's throttled
        gauge pass (utils/hotrecord.py), never per-keep."""
        if not self.enabled:
            return
        with self._lock:
            pinned = sum(d.get("pinned_spans", 0)
                         for d in self._kept.values())
            pinned += sum(d.get("pinned_spans", 0) for d in self._baseline)
        try:
            from seldon_core_tpu.utils.telemetry import RECORDER

            RECORDER.set_postmortem_pinned(pinned)
        except Exception:  # noqa: BLE001
            pass

    # -- query surfaces --------------------------------------------------

    @staticmethod
    def _summary(doc: Dict[str, Any]) -> Dict[str, Any]:
        explain = doc.get("explain") or {}
        root = doc.get("root") or {}
        return {
            "puid": doc.get("puid", ""),
            "trace_id": doc.get("trace_id", ""),
            "reason": doc.get("reason", ""),
            "reasons": list(doc.get("reasons") or ()),
            "duration_ms": root.get("duration_ms"),
            "guilty_phase": explain.get("guilty_phase"),
            "excess_ms": explain.get("excess_ms"),
            "kept_at_s": doc.get("kept_at_s"),
            "pinned_spans": doc.get("pinned_spans", 0),
            "synthetic": bool(doc.get("synthetic")),
        }

    def _find(self, puid: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            for store in (list(self._kept.values()), list(self._baseline),
                          list(self._synthetic)):
                for doc in store:
                    if doc.get("puid") == puid or doc.get("trace_id") == puid:
                        return doc
        return None

    def document(self, puid: str = "") -> Dict[str, Any]:
        """The ``GET /postmortems`` body.  Without ``puid``: config,
        counters, and worst-first exemplar summaries.  With ``puid`` (or
        a trace_id): the full immutable postmortem document."""
        if TRACER.drain_hook is not None:
            try:
                TRACER.drain_hook()  # fold pending spine records first
            except Exception:  # noqa: BLE001
                pass
        if puid:
            doc = self._find(puid)
            return {"found": doc is not None, "puid": puid,
                    "postmortem": doc}
        with self._lock:
            kept = [self._summary(d) for d in self._kept.values()]
            baseline = [self._summary(d) for d in self._baseline]
            synthetic = [self._summary(d) for d in self._synthetic]
            counters = {
                "completed": self.completed_total,
                "kept": dict(self.kept_total),
                "dropped": self.dropped_total,
                "noted": self.noted_total,
                "offers": self.offer_total,
                "truncated_spans": self.truncated_spans,
            }
            pending = {
                "traces": len(self._pending),
                "spans": sum(len(p.spans) for p in self._pending.values()),
            }
        kept.sort(key=lambda s: (-(s.get("excess_ms") or 0.0),
                                 -(s.get("kept_at_s") or 0.0)))
        return {
            "enabled": self.enabled,
            "config": {
                "excess_x": self.excess_x,
                "slo_ms": self.slo_ms,
                "ttl_s": self.ttl_s,
                "pending_traces": self.pending_traces,
                "pending_spans": self.pending_spans,
                "keep": self.keep_cap,
                "baseline": self.baseline_k,
            },
            "counters": counters,
            "pending": pending,
            "capture_overhead_ms": self._offer_p50(),
            "kept": kept,
            "baseline": baseline,
            "synthetic": synthetic,
        }

    def _offer_p50(self) -> Optional[float]:
        vals = sorted(self._offer_ms)
        if not vals:
            return None
        return round(vals[len(vals) // 2], 4)

    def exemplar_puids(self, deployment: str = "",
                       limit: int = 4) -> List[str]:
        """Recent anomalous exemplar puids — the evidence a rollout
        rollback cites.  Prefers exemplars whose root carries the named
        deployment; falls back to the most recent anomalies when none
        match (an engine-rooted exemplar may not carry the attr)."""
        with self._lock:
            docs = list(self._kept.values())
        docs.reverse()  # most recent first
        if deployment:
            matched = [d for d in docs
                       if (d.get("root") or {}).get("attrs", {})
                       .get("deployment") == deployment]
            if matched:
                docs = matched
        out: List[str] = []
        for d in docs:
            p = d.get("puid") or d.get("trace_id") or ""
            if p and p not in out:
                out.append(p)
            if len(out) >= limit:
                break
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Compact health view (bench axes + /stats-adjacent probes)."""
        with self._lock:
            kept = sum(self.kept_total.values())
        return {
            "enabled": self.enabled,
            "completed_total": self.completed_total,
            "kept_total": kept,
            "dropped_total": self.dropped_total,
            "offer_p50_ms": self._offer_p50(),
        }

    def reset(self) -> None:
        with self._lock:
            self._pending.clear()
            self._kept.clear()
            self._baseline = []
            self._healthy_n = 0
            self._synthetic.clear()
            self._phase_p50.clear()
            self.kept_total = {}
            self.dropped_total = 0
            self.completed_total = 0
            self.noted_total = 0
            self.offer_total = 0
            self.truncated_spans = 0
            self._offer_ms.clear()


POSTMORTEM = PostmortemRecorder()
