"""Serving flight recorder — TPU-serving telemetry hub.

The reference's observability is per-hop HTTP latencies plus a Kafka
request firehose; both are deployment-level.  The TPU-native internals
that actually govern throughput — micro-batch occupancy, queue wait,
in-flight dispatch slots, time-to-first-token, decode rate, speculative
acceptance, compile-cache traffic, KV-cache occupancy — are PROCESS-level
(one TPU runtime per process), so they live in one process-global hub
instead of the per-predictor ``MetricsRegistry``:

  * ``FlightRecorder`` (module global ``RECORDER``, the ``TRACER``
    pattern) keeps every family twice: a Prometheus metric in its own
    ``CollectorRegistry`` (merged into every ``MetricsRegistry``
    exposition, so existing ``/prometheus`` scrape targets pick the new
    families up with zero config) and a plain-Python mirror — bounded
    reservoirs for distributions, ints for gauges/counters — so the
    ``/stats`` JSON snapshot needs no dependency at all.
  * ``AuditLog`` is the engine-side analogue of the gateway firehose
    (gateway/firehose.py): an async bounded-queue JSONL request-audit
    stream (puid, graph path, batch size, latency breakdown, token
    counts).  ``record()`` never blocks — a full queue counts a drop,
    the same trade the reference's Kafka producer makes with
    MAX_BLOCK_MS=20.

Everything here must stay safe to call from jit-traced code paths'
EAGER surroundings only; model code guards with
``isinstance(x, jax.core.Tracer)`` before recording (a traced call would
record trace-time constants, not serving behaviour).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover
    HAVE_PROMETHEUS = False

__all__ = [
    "Reservoir",
    "FlightRecorder",
    "AuditLog",
    "RECORDER",
    "TPU_METRIC_FAMILIES",
    "install_compile_cache_listener",
]

#: every TPU-serving metric family the recorder exports, base name ->
#: (kind, label names).  The single source of truth: the Prometheus
#: constructions below and the dashboard-honesty test
#: (tests/test_monitoring_configs.py) both read it.
TPU_METRIC_FAMILIES: Dict[str, tuple] = {
    "seldon_tpu_batch_occupancy": ("histogram", ()),
    "seldon_tpu_batch_queue_wait_seconds": ("histogram", ()),
    "seldon_tpu_inflight_dispatches": ("gauge", ()),
    "seldon_tpu_ttft_seconds": ("histogram", ()),
    "seldon_tpu_decode_tokens_per_second": ("histogram", ()),
    "seldon_tpu_speculative_accept_ratio": ("histogram", ()),
    "seldon_tpu_compile_cache_events_total": ("counter", ("outcome",)),
    "seldon_tpu_kv_cache_slots": ("gauge", ("state",)),
    "seldon_tpu_audit_events_total": ("counter", ("outcome",)),
    # resilience layer (runtime/resilience.py): breaker state machine,
    # unified retry policy, deadline propagation, graceful degradation
    "seldon_tpu_breaker_state": ("gauge", ("node",)),
    "seldon_tpu_breaker_transitions_total": ("counter", ("node", "to")),
    "seldon_tpu_retry_attempts_total": ("counter", ("method", "outcome")),
    "seldon_tpu_retry_budget_exhausted_total": ("counter", ()),
    "seldon_tpu_deadline_exceeded_total": ("counter", ("where",)),
    "seldon_tpu_degraded_requests_total": ("counter", ("mode",)),
    # causal tracer (utils/tracing.py): spans recorded per kind — the
    # signal that says whether sampling keeps trace volume sane under load
    "seldon_tpu_trace_spans_total": ("counter", ("kind",)),
    # performance observatory (utils/perf.py): per-executable dispatch
    # latency (bucket observations carry trace_id exemplars in the
    # OpenMetrics exposition), achieved MFU, roofline-drift anomalies,
    # HBM watermarks, XLA compile durations, and the per-service request
    # latency promoted from the /stats reservoir to a real histogram
    "seldon_tpu_dispatch_seconds": ("histogram", ("executable",)),
    "seldon_tpu_mfu": ("gauge", ("executable",)),
    "seldon_tpu_perf_anomaly_total": ("counter", ("kind",)),
    "seldon_tpu_hbm_bytes_in_use": ("gauge", ("device",)),
    "seldon_tpu_hbm_peak_bytes_in_use": ("gauge", ("device",)),
    "seldon_tpu_hbm_bytes_limit": ("gauge", ("device",)),
    "seldon_tpu_compile_seconds": ("histogram", ()),
    "seldon_tpu_request_latency_seconds": ("histogram", ("service",)),
    # prediction-quality observatory (utils/quality.py): live-vs-reference
    # input/prediction drift, feedback reward + truth-agreement
    # accounting, the Mahalanobis outlier-score bridge, and multi-window
    # SLO burn rates
    "seldon_tpu_drift_score": ("gauge", ("node", "method")),
    "seldon_tpu_prediction_quantile": ("gauge", ("node", "q")),
    "seldon_tpu_feedback_reward": ("histogram", ()),
    "seldon_tpu_feedback_total": ("counter", ("outcome",)),
    "seldon_tpu_outlier_score": ("histogram", ()),
    "seldon_tpu_outlier_exceedances_total": ("counter", ()),
    "seldon_tpu_slo_burn_rate": ("gauge", ("window",)),
    "seldon_tpu_quality_sampled_total": ("counter", ("node",)),
    # fused telemetry spine (utils/hotrecord.py): hot-path ring health and
    # the self-observed per-subsystem overhead budget behind GET /overhead
    "seldon_tpu_telemetry_ring_dropped_total": ("counter", ()),
    "seldon_tpu_telemetry_records_total": ("counter", ("hop",)),
    "seldon_tpu_framework_overhead_ms": ("gauge", ("subsystem",)),
    # continuous-batching generation scheduler (runtime/genserver.py):
    # in-flight/waiting sequence counts, paged-KV-pool occupancy
    # (state=used|total|high_water — the SeldonTPUKVPoolPressure alert
    # compares used against total), admission/retirement flow, and
    # scheduler steps by kind (prefill|decode|spec|mixed)
    "seldon_tpu_gen_inflight_sequences": ("gauge", ()),
    "seldon_tpu_gen_waiting_sequences": ("gauge", ()),
    "seldon_tpu_gen_kv_blocks": ("gauge", ("state",)),
    "seldon_tpu_gen_admitted_total": ("counter", ()),
    "seldon_tpu_gen_retired_total": ("counter", ("reason",)),
    "seldon_tpu_gen_steps_total": ("counter", ("kind",)),
    # generation-lane flight recorder (utils/genperf.py): per-tick
    # host/device time by kind and phase (admit / prefill / decode /
    # retire / host_other, with a "_device" suffix for the fenced device
    # wall inside a phase), the bubble ledger by cause (host /
    # admission_stall / pool_exhaustion / idle — the
    # SeldonTPUDecodeBubbles alert's axis), served decode MFU over REAL
    # tokens, KV-block residency at release, and scheduler tick-loop
    # errors (a silently-erroring scheduler must be visible)
    "seldon_tpu_gen_step_seconds": ("histogram", ("kind", "phase")),
    "seldon_tpu_gen_bubble_seconds_total": ("counter", ("cause",)),
    "seldon_tpu_gen_served_mfu": ("gauge", ()),
    "seldon_tpu_gen_kv_block_age_seconds": ("histogram", ()),
    "seldon_tpu_gen_tick_errors_total": ("counter", ()),
    # serving-mesh data plane (gateway/balancer.py): per-replica gateway-
    # side inflight and pick counts (the power-of-two-choices signal and
    # its outcome — max/mean of the inflight gauge is the imbalance the
    # SeldonTPUReplicaImbalance alert watches), hindsight mispicks (the
    # chosen replica finished slower than the losing candidate's EWMA at
    # decision time), and per-lane relay counters (uds vs tcp vs
    # inprocess — says which transport the gateway->engine hop actually
    # rode)
    # the ``set`` label is the replica-set identity (deployment/predictor
    # at the gateway): imbalance is only meaningful WITHIN one set — a
    # 95/5 canary's idle second set would otherwise drag a cross-set
    # average down and page the imbalance alert forever
    "seldon_tpu_replica_inflight": ("gauge", ("set", "replica")),
    "seldon_tpu_replica_picks_total": ("counter", ("set", "replica")),
    "seldon_tpu_replica_mispicks_total": ("counter", ()),
    "seldon_tpu_relay_lane_requests_total": ("counter", ("lane",)),
    # binary tensor wire contract (runtime/wire.py): predict traffic per
    # lane split by wire format (json vs binary — says which contract
    # the bytes actually rode), host-side bytes copied by the codec and
    # its feeding lanes (the bench's bytes_copied_per_request axis), and
    # requests that rode a gateway-coalesced multi-tensor engine frame
    "seldon_tpu_wire_requests_total": ("counter", ("lane", "format")),
    "seldon_tpu_wire_bytes_copied_total": ("counter", ()),
    "seldon_tpu_wire_coalesced_total": ("counter", ()),
    # traffic lifecycle (gateway/shadow.py + operator/rollouts.py):
    # shadow-mirror outcomes and live-vs-shadow divergence, the shadow
    # hop's own latency (never on the live response path), canary
    # auto-rollbacks by breached gate, and the active rollout's candidate
    # traffic percent per deployment
    "seldon_tpu_shadow_requests_total": ("counter", ("outcome",)),
    "seldon_tpu_shadow_disagreement": ("histogram", ()),
    "seldon_tpu_shadow_latency_seconds": ("histogram", ()),
    "seldon_tpu_rollbacks_total": ("counter", ("reason",)),
    "seldon_tpu_rollout_stage": ("gauge", ("deployment",)),
    # learned cost-model autopilot (runtime/autopilot.py): predictive
    # decisions taken (site = flush pad-bucket choice / p2c shape
    # blending / router branch demotion), deadline-aware admission sheds
    # (requests refused with a typed 503 BEFORE burning device time),
    # the rolling |measured-predicted|/predicted p50 that audits the
    # model (the SeldonTPUAutopilotMispredict alert's axis), and the
    # model-table size
    "seldon_tpu_autopilot_decisions_total": ("counter", ("site",)),
    "seldon_tpu_autopilot_shed_total": ("counter", ("where",)),
    "seldon_tpu_autopilot_mispredict_pct": ("gauge", ()),
    "seldon_tpu_autopilot_keys": ("gauge", ()),
    # multi-tenant QoS (runtime/qos.py + gateway/apife.py): per-tenant
    # admission flow and token-bucket refusals (the
    # SeldonTPUTenantThrottled alert's axis).  Tenant label cardinality
    # is bounded at the source: the governor LRU-caps tenant rows at 256
    # and the recorder folds everything beyond its own cap into an
    # "overflow" label, so an id-spraying client cannot balloon the
    # exposition
    "seldon_tpu_tenant_requests_total": ("counter", ("tenant",)),
    "seldon_tpu_tenant_throttled_total": ("counter", ("tenant",)),
    # brownout ladder (runtime/brownout.py): the current degradation
    # stage (0 = normal; SeldonTPUBrownoutActive pages on sustained > 0),
    # stage transitions, and requests shed by tier while degraded
    "seldon_tpu_brownout_stage": ("gauge", ()),
    "seldon_tpu_brownout_transitions_total": ("counter", ("stage",)),
    "seldon_tpu_brownout_shed_total": ("counter", ("tier",)),
    # disaggregated prefill/decode serving mesh (runtime/servingmesh.py
    # + runtime/kvstream.py): KV-block handoff outcomes (prefill side:
    # ok|refused|torn|error; decode side: imported|reclaimed), the
    # handoff wall-clock distribution, streamed bytes, and in-flight
    # handoffs — the SeldonTPUKVHandoffStall alert pages when handoffs
    # sit in flight with no completion for minutes
    "seldon_tpu_kv_handoff_total": ("counter", ("outcome",)),
    "seldon_tpu_kv_handoff_seconds": ("histogram", ()),
    "seldon_tpu_kv_handoff_bytes_total": ("counter", ()),
    "seldon_tpu_kv_handoff_inflight": ("gauge", ()),
    # fleet observability plane (gateway/fleet.py): per-replica
    # worse-than-set-median ratio (the worst metric's ratio — 2.0 reads
    # "this replica is 2x worse than its siblings"; the
    # SeldonTPUReplicaOutlier alert pages on it), replica count per set,
    # and how stale each replica's scraped fleet documents are
    "seldon_tpu_fleet_outlier_ratio": ("gauge", ("set", "replica")),
    "seldon_tpu_fleet_replicas": ("gauge", ("set",)),
    "seldon_tpu_fleet_staleness_seconds": ("gauge", ("set", "replica")),
    # mesh fault recovery (gateway/federation.py + apife.py failover
    # paths): work re-homed after a process death — kind=unary (hedged
    # re-dispatch of an idempotent predict to a peer replica) or
    # kind=stream (an SSE decode stream resumed on a peer by re-prefill)
    # — and coordinator/engine lease tenure changes by kind (acquired /
    # lost / released / store_error).  A lease_transitions spike reads
    # "the fleet is re-electing"; failover_total says the recovery
    # machinery actually fired
    "seldon_tpu_failover_total": ("counter", ("kind",)),
    "seldon_tpu_lease_transitions_total": ("counter", ("kind",)),
    # durable perf corpus (utils/perfcorpus.py): dispatch rows appended
    # this process, total on-disk footprint (segments + compacted
    # sketches — rotation bounds it), and autopilot keys warm-started
    # from a prior process's corpus at boot
    "seldon_tpu_corpus_rows": ("gauge", ()),
    "seldon_tpu_corpus_bytes": ("gauge", ()),
    "seldon_tpu_corpus_warm_keys": ("gauge", ()),
    # fleet-truth SLO burn (gateway/federation.py folding peer deltas
    # from the shared store): the aggregate burn rate per window that
    # the brownout ladder and rollout gates actually judge — the
    # SeldonTPUFleetBurn alert's axis (local slice: slo_burn_rate)
    "seldon_tpu_fleet_burn_rate": ("gauge", ("window",)),
    # resource-attribution ledger (utils/costledger.py): per-tenant x
    # deployment x phase fenced device-seconds, KV-block residency
    # integrated at release, the pad tax (padded-remainder seconds a
    # tenant's batch shape caused), and the accounting identity's
    # honesty gauge — the SeldonTPUUnattributedDeviceTime alert pages
    # when attributed_fraction sits below 0.97 (a lane is burning chip
    # time the ledger cannot put a name on).  Tenant cardinality is
    # bounded by the same overflow fold as the QoS families
    "seldon_tpu_cost_device_seconds_total":
        ("counter", ("tenant", "deployment", "phase")),
    "seldon_tpu_cost_kv_block_seconds_total":
        ("counter", ("tenant", "deployment")),
    "seldon_tpu_cost_pad_tax_seconds_total":
        ("counter", ("tenant", "deployment")),
    "seldon_tpu_cost_attributed_fraction": ("gauge", ()),
    # tail-sampled postmortem recorder (utils/postmortem.py): exemplars
    # kept by retention reason (error / shed / slo / autopilot_excess /
    # preemption / breaker / failover / lease / baseline), pending
    # traces evicted without a keep verdict (buffer overflow or TTL),
    # and spans currently pinned inside kept exemplar documents.  The
    # SeldonTPUPostmortemFlood alert pages on a sustained kept rate —
    # the anomaly detector itself saying most traffic is anomalous
    "seldon_tpu_postmortem_kept_total": ("counter", ("reason",)),
    "seldon_tpu_postmortem_dropped_total": ("counter", ()),
    "seldon_tpu_postmortem_pinned_spans": ("gauge", ()),
}

_OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
_WAIT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)
_TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0, 10.0, 30.0)
_RATE_BUCKETS = (1, 10, 50, 100, 250, 500, 1000, 2500, 5000, 10000,
                 50000, 100000)
_RATIO_BUCKETS = (0.0, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)
# device dispatch spans ~1ms (tiny graphs) to tens of seconds (cold
# compile riding a dispatch); request latency matches metrics.py _BUCKETS
_DISPATCH_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
_COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0,
                    40.0, 80.0, 160.0)
_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# rewards are nominally [0,1] (models/mab.py) but the wire allows any
# scalar; outlier scores are Mahalanobis distances (chi2-ish tails)
_REWARD_BUCKETS = (0.0, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0,
                   2.5, 10.0)
_OUTLIER_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                    1000.0)
# scheduler tick phases span tens of µs (CPU host bookkeeping) to whole
# seconds (a cold-compile prefill chunk); KV-block residency spans one
# short generation (~100 ms) to pinned-prefix lifetimes (minutes+)
_GEN_STEP_BUCKETS = (0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                     0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)
_KV_AGE_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 30.0, 60.0, 300.0, 1800.0)


class Reservoir:
    """Bounded sample ring with percentile snapshots — the zero-dependency
    distribution store behind ``/stats``.  A plain deque keeps the LAST
    ``capacity`` observations (serving wants "recent behaviour", and a
    sliding window is cheaper and more legible than decaying reservoirs);
    thread-safe because observations arrive from the event loop and from
    device-dispatch executor threads."""

    def __init__(self, capacity: int = 2048):
        self._samples: deque = deque(maxlen=int(capacity))
        self._count = 0
        self._total = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._samples.append(float(value))
            self._count += 1
            self._total += float(value)

    def observe_many(self, values) -> None:
        """Batch observe under ONE lock acquisition — per-row call sites
        on the dispatch path (outlier-score bridging) must not pay a
        lock per row."""
        vals = [float(v) for v in values]
        if not vals:
            return
        with self._lock:
            self._samples.extend(vals)
            self._count += len(vals)
            self._total += sum(vals)

    def __len__(self) -> int:
        return len(self._samples)

    def snapshot(self) -> Dict[str, Any]:
        """{count, mean, p50, p95, p99, max} over the retained window;
        count/mean are lifetime (count is what rate() needs, the window
        is what percentiles need)."""
        with self._lock:
            vals = sorted(self._samples)
            count, total = self._count, self._total
        if not vals:
            return {"count": count, "mean": 0.0, "p50": 0.0, "p95": 0.0,
                    "p99": 0.0, "max": 0.0}

        def pct(p: float) -> float:
            return vals[min(len(vals) - 1, int(p * len(vals)))]

        return {
            "count": count,
            "mean": total / max(count, 1),
            "p50": pct(0.50),
            "p95": pct(0.95),
            "p99": pct(0.99),
            "max": vals[-1],
        }


class FlightRecorder:
    """Process-global TPU-serving telemetry: Prometheus families plus
    plain-Python mirrors (see module docstring).  All observe/set methods
    are cheap (a deque append + a child .observe) and never raise — the
    hot path must not grow failure modes from its own instrumentation."""

    def __init__(self):
        self._lock = threading.Lock()
        self.batch_occupancy = Reservoir()
        self.batch_queue_wait = Reservoir()
        self.ttft = Reservoir()
        self.decode_rate = Reservoir()
        self.accept_ratio = Reservoir()
        self.inflight = 0
        self.kv_slots: Dict[str, int] = {}
        self.compile_cache_events: Dict[str, int] = {}
        # resilience mirrors (runtime/resilience.py feeds these)
        self.breaker_states: Dict[str, str] = {}
        self.breaker_transitions: Dict[str, int] = {}  # "node:to" -> n
        self.retry_attempts: Dict[str, int] = {}  # "method:outcome" -> n
        self.retry_budget_exhausted = 0
        self.deadline_exceeded: Dict[str, int] = {}
        self.degraded_requests: Dict[str, int] = {}
        self.trace_spans: Dict[str, int] = {}  # causal tracer, by span kind
        # performance observatory mirrors (utils/perf.py feeds these; the
        # per-executable tables live in OBSERVATORY, not here)
        self.perf_anomalies: Dict[str, int] = {}
        self.compile_seconds = Reservoir()
        self.hbm: Dict[str, Dict[str, int]] = {}
        #: per-service rolling request latencies feeding /stats percentiles;
        #: bounded — an exploding label set must not grow memory
        self._latency: Dict[str, Reservoir] = {}
        self._latency_cap = 64
        # prediction-quality observatory mirrors (utils/quality.py feeds
        # these; the per-node windows live in QUALITY, not here)
        self.drift_scores: Dict[str, float] = {}       # "node:method" -> v
        self.prediction_quantiles: Dict[str, float] = {}  # "node:q" -> v
        self.feedback_count = 0
        self.feedback_reward = Reservoir()
        self.feedback_truth = 0
        self.feedback_agree = 0
        self.feedback_disagree = 0
        self.outlier_scores = Reservoir()
        self.outlier_exceeded = 0
        self.slo_burn: Dict[str, float] = {}           # window -> rate
        self.quality_sampled: Dict[str, int] = {}      # node -> batches
        # telemetry-spine mirrors (utils/hotrecord.py feeds these from the
        # drainer: ring drops, folded records per hop, per-subsystem
        # framework-overhead p50s behind GET /overhead)
        self.telemetry_ring_dropped = 0
        self.telemetry_records: Dict[str, int] = {}    # hop -> folded
        # continuous-batching generation scheduler mirrors
        # (runtime/genserver.py feeds these once per scheduler step)
        self.gen_scheduler: Dict[str, int] = {}
        self.gen_admitted = 0
        self.gen_retired: Dict[str, int] = {}
        self.gen_steps: Dict[str, int] = {}
        # generation flight-recorder mirrors (utils/genperf.py feeds
        # these off-path from the spine fold): per-kind/phase tick time,
        # the bubble ledger by cause, KV-block residency at release,
        # served decode MFU (throttled gauge) and tick-loop errors
        self.gen_step_seconds: Dict[str, Reservoir] = {}   # "kind/phase"
        self.gen_bubble_s: Dict[str, float] = {}           # cause -> s
        self.gen_kv_block_age = Reservoir()
        self.gen_served_mfu: Optional[float] = None
        self.gen_tick_errors = 0
        # disaggregated serving-mesh mirrors (runtime/servingmesh.py
        # coordinator + runtime/genserver.py import path): handoff
        # outcomes, latency reservoir, streamed bytes, in-flight gauge
        self.kv_handoffs: Dict[str, int] = {}          # outcome -> n
        self.kv_handoff_latency = Reservoir()
        self.kv_handoff_bytes = 0
        self.kv_handoff_inflight = 0
        # serving-mesh mirrors (gateway/balancer.py feeds these): per-
        # set per-replica gateway-side inflight + lifetime picks,
        # hindsight mispicks, and gateway->engine requests by relay lane
        self.replica_inflight: Dict[str, Dict[str, int]] = {}
        self.replica_picks: Dict[str, Dict[str, int]] = {}
        self.replica_mispicks = 0
        self.lane_requests: Dict[str, int] = {}
        # binary wire mirrors (runtime/wire.py): "lane/format" -> n,
        # codec copy accounting, coalesced-request count
        self.wire_requests: Dict[str, int] = {}
        self.wire_bytes_copied = 0
        self.wire_copies = 0
        self.wire_coalesced = 0
        # fleet observability mirrors (gateway/fleet.py): per-replica
        # worst worse-than-median ratio + replica counts per set
        self.fleet_outliers: Dict[str, Dict[str, float]] = {}
        self.fleet_replicas: Dict[str, int] = {}
        # mesh fault recovery (gateway/federation.py coordinator
        # election + apife.py hedged-unary / stream-resume paths)
        self.failovers: Dict[str, int] = {}            # kind -> n
        self.lease_transitions: Dict[str, int] = {}    # kind -> n
        # durable perf corpus (utils/perfcorpus.py publish_gauges) +
        # fleet-truth burn (gateway/federation.py burn folds)
        self.corpus_rows = 0
        self.corpus_bytes = 0
        self.corpus_warm_keys = 0
        self.fleet_burn: Dict[str, float] = {}         # window -> rate
        # tail-sampled postmortem mirrors (utils/postmortem.py: keeps by
        # retention reason, pending-buffer drops, pinned exemplar spans)
        self.postmortem_kept: Dict[str, int] = {}      # reason -> n
        self.postmortem_dropped = 0
        self.postmortem_pinned = 0
        # traffic-lifecycle mirrors (gateway/shadow.py mirror outcomes +
        # divergence, operator/rollouts.py rollbacks and stage weights)
        self.shadow_requests: Dict[str, int] = {}      # outcome -> n
        self.shadow_disagreement = Reservoir()
        self.shadow_latency = Reservoir()
        self.rollbacks: Dict[str, int] = {}            # reason -> n
        self.rollout_stage: Dict[str, float] = {}      # deployment -> pct
        # learned cost-model autopilot mirrors (runtime/autopilot.py
        # feeds these: decision counters from the spine folds, shed
        # counters from the admission gate, model gauges from the
        # throttled gauge refresh)
        self.autopilot_decisions: Dict[str, int] = {}  # site -> n
        self.autopilot_sheds: Dict[str, int] = {}      # where -> n
        self.autopilot_mispredict_p50_pct: Optional[float] = None
        self.autopilot_keys = 0
        # multi-tenant QoS mirrors (runtime/qos.py governor feeds these)
        # + the brownout ladder's stage/transition/shed accounting
        # (runtime/brownout.py).  Tenant label sets are capped here too
        # (_TENANT_LABEL_CAP) independently of the governor's LRU — the
        # recorder must stay bounded even if a future caller feeds it
        # raw ids
        self.tenant_requests: Dict[str, int] = {}      # tenant -> n
        self.tenant_throttled: Dict[str, int] = {}     # tenant -> n
        self.brownout_stage = 0
        self.brownout_transitions: Dict[str, int] = {}  # stage -> n
        self.brownout_sheds: Dict[str, int] = {}       # tier -> n
        # resource-attribution mirrors (utils/costledger.py pushes
        # deltas from the spine's throttled gauge refresh — the
        # hot-path writers never touch these)
        self.cost_device_s: Dict[Tuple[str, str, str], float] = {}
        self.cost_kv_block_s: Dict[Tuple[str, str], float] = {}
        self.cost_pad_tax_s: Dict[Tuple[str, str], float] = {}
        self.cost_attributed_fraction: Optional[float] = None
        # Prometheus high-water mark per hop: the counter is advanced by
        # deltas against THIS, not the snapshot mirror above — reset()
        # clears the mirror but must not rewind the monotone counter's
        # baseline (it would re-add the whole lifetime total on next fold)
        self._telemetry_records_published: Dict[str, int] = {}
        self.framework_overhead: Dict[str, float] = {}  # subsystem -> ms
        #: set on the process singleton by utils/hotrecord.py — snapshots
        #: and expositions fold pending ring records before reading
        self.drain_hook = None
        #: mutation generation — bumped by state-ish recording methods
        #: (breakers, drift, kv, hbm, feedback, spine mirrors...) so
        #: Engine.stats() can serve its cached document while nothing
        #: underneath it moved.  Pure per-request reservoir observes
        #: (latency, occupancy, ttft...) deliberately do NOT bump it:
        #: under traffic the telemetry-spine fold generation invalidates
        #: the cache anyway, and the kill-switched case is bounded by
        #: SELDON_TPU_STATS_TTL_S — bumping here would defeat the cache
        #: under exactly the load it exists for
        self._gen = 0
        self.registry = None
        if HAVE_PROMETHEUS:
            self.registry = CollectorRegistry()
            self._p_occupancy = Histogram(
                "seldon_tpu_batch_occupancy",
                "Rows per stacked device dispatch",
                registry=self.registry, buckets=_OCCUPANCY_BUCKETS)
            self._p_queue_wait = Histogram(
                "seldon_tpu_batch_queue_wait_seconds",
                "Submit-to-dispatch wait in the micro-batch queue",
                registry=self.registry, buckets=_WAIT_BUCKETS)
            self._p_inflight = Gauge(
                "seldon_tpu_inflight_dispatches",
                "Stacked dispatches currently riding the device",
                registry=self.registry)
            self._p_ttft = Histogram(
                "seldon_tpu_ttft_seconds",
                "Time to first generated token (prefill + first sample)",
                registry=self.registry, buckets=_TTFT_BUCKETS)
            self._p_decode_rate = Histogram(
                "seldon_tpu_decode_tokens_per_second",
                "Generated tokens per second per request (batch x length / "
                "wall)", registry=self.registry, buckets=_RATE_BUCKETS)
            self._p_accept = Histogram(
                "seldon_tpu_speculative_accept_ratio",
                "Per-request mean accepted-draft fraction per verify round",
                registry=self.registry, buckets=_RATIO_BUCKETS)
            self._p_compile = Counter(
                "seldon_tpu_compile_cache_events_total",
                "Persistent XLA compile cache events", ["outcome"],
                registry=self.registry)
            self._p_kv = Gauge(
                "seldon_tpu_kv_cache_slots",
                "KV cache slots by state (most recent generation dispatch)",
                ["state"], registry=self.registry)
            self._p_audit = Counter(
                "seldon_tpu_audit_events_total",
                "Request-audit firehose events", ["outcome"],
                registry=self.registry)
            self._p_breaker_state = Gauge(
                "seldon_tpu_breaker_state",
                "Per-remote-node circuit breaker state "
                "(0=closed, 0.5=half-open, 1=open)", ["node"],
                registry=self.registry)
            self._p_breaker_transitions = Counter(
                "seldon_tpu_breaker_transitions_total",
                "Circuit breaker state transitions", ["node", "to"],
                registry=self.registry)
            self._p_retry = Counter(
                "seldon_tpu_retry_attempts_total",
                "Node-client retry events by graph method",
                ["method", "outcome"], registry=self.registry)
            self._p_retry_budget = Counter(
                "seldon_tpu_retry_budget_exhausted_total",
                "Retries refused because the global retry budget was empty",
                registry=self.registry)
            self._p_deadline = Counter(
                "seldon_tpu_deadline_exceeded_total",
                "Calls abandoned because the request deadline budget ran "
                "out", ["where"], registry=self.registry)
            self._p_degraded = Counter(
                "seldon_tpu_degraded_requests_total",
                "Requests served degraded (combiner quorum / router "
                "fallback)", ["mode"], registry=self.registry)
            self._p_trace_spans = Counter(
                "seldon_tpu_trace_spans_total",
                "Causal-tracer spans recorded, by span kind",
                ["kind"], registry=self.registry)
            self._p_dispatch = Histogram(
                "seldon_tpu_dispatch_seconds",
                "Measured device-dispatch wall time per compiled "
                "executable (bucket observations carry trace_id exemplars "
                "in the OpenMetrics exposition)",
                ["executable"], registry=self.registry,
                buckets=_DISPATCH_BUCKETS)
            self._p_mfu = Gauge(
                "seldon_tpu_mfu",
                "Most recent achieved MFU per executable (fraction of the "
                "device-kind-matched advertised bf16 peak, utils/chips.py)",
                ["executable"], registry=self.registry)
            self._p_perf_anomaly = Counter(
                "seldon_tpu_perf_anomaly_total",
                "Dispatches drifting past the per-executable baseline "
                "(slow_dispatch: vs rolling p50; ratio_drift: vs rolling "
                "measured/predicted)",
                ["kind"], registry=self.registry)
            self._p_hbm = {
                "bytes_in_use": Gauge(
                    "seldon_tpu_hbm_bytes_in_use",
                    "Device HBM bytes currently in use "
                    "(device.memory_stats)", ["device"],
                    registry=self.registry),
                "peak_bytes_in_use": Gauge(
                    "seldon_tpu_hbm_peak_bytes_in_use",
                    "Device HBM high-watermark bytes "
                    "(device.memory_stats)", ["device"],
                    registry=self.registry),
                "bytes_limit": Gauge(
                    "seldon_tpu_hbm_bytes_limit",
                    "Device HBM capacity bytes (device.memory_stats)",
                    ["device"], registry=self.registry),
            }
            self._p_compile_seconds = Histogram(
                "seldon_tpu_compile_seconds",
                "XLA compile wall time per compiled executable "
                "(AOT captures + jax.monitoring backend_compile events)",
                registry=self.registry, buckets=_COMPILE_BUCKETS)
            self._p_request_latency = Histogram(
                "seldon_tpu_request_latency_seconds",
                "Per-service request latency (the Prometheus face of the "
                "/stats request_latency_s reservoirs)",
                ["service"], registry=self.registry,
                buckets=_LATENCY_BUCKETS)
            self._p_drift = Gauge(
                "seldon_tpu_drift_score",
                "Live-vs-reference drift per graph node (method=psi: max "
                "per-feature PSI; ks: max per-feature KS distance; "
                "prediction: PSI of the prediction distribution — "
                "utils/quality.py)",
                ["node", "method"], registry=self.registry)
            self._p_pred_quantile = Gauge(
                "seldon_tpu_prediction_quantile",
                "Approximate live prediction-distribution quantiles per "
                "graph node (binned sketch over reference edges)",
                ["node", "q"], registry=self.registry)
            self._p_feedback_reward = Histogram(
                "seldon_tpu_feedback_reward",
                "Reward value per send_feedback call",
                registry=self.registry, buckets=_REWARD_BUCKETS)
            self._p_feedback = Counter(
                "seldon_tpu_feedback_total",
                "Feedback calls by outcome (received / truth_provided / "
                "agree / disagree)", ["outcome"], registry=self.registry)
            self._p_outlier = Histogram(
                "seldon_tpu_outlier_score",
                "Mahalanobis outlier scores bridged out of "
                "meta.tags['outlierScore'] (models/outlier.py)",
                registry=self.registry, buckets=_OUTLIER_BUCKETS)
            self._p_outlier_exceeded = Counter(
                "seldon_tpu_outlier_exceedances_total",
                "Rows whose outlier score exceeded "
                "SELDON_TPU_OUTLIER_THRESHOLD",
                registry=self.registry)
            self._p_slo_burn = Gauge(
                "seldon_tpu_slo_burn_rate",
                "SLO error-budget burn rate per window (1.0 = burning "
                "exactly at budget; 14.4x/5m and 6x/1h are the classic "
                "page thresholds — utils/quality.py SloTracker)",
                ["window"], registry=self.registry)
            self._p_quality_sampled = Counter(
                "seldon_tpu_quality_sampled_total",
                "Dispatch batches sampled into the quality observatory "
                "(SELDON_TPU_QUALITY_SAMPLE gates the rate)",
                ["node"], registry=self.registry)
            self._p_ring_dropped = Counter(
                "seldon_tpu_telemetry_ring_dropped_total",
                "Hot-path telemetry records dropped because a per-thread "
                "ring was full (utils/hotrecord.py — raise "
                "SELDON_TPU_TELEMETRY_RING or lower the drain interval)",
                registry=self.registry)
            self._p_telemetry_records = Counter(
                "seldon_tpu_telemetry_records_total",
                "Telemetry-spine records folded off-path, by hop kind",
                ["hop"], registry=self.registry)
            self._p_framework_overhead = Gauge(
                "seldon_tpu_framework_overhead_ms",
                "Self-observed framework overhead, milliseconds p50: "
                "per-record off-path fold cost by consumer subsystem "
                "(tracer/perf/quality/recorder), the on-path ring write "
                "(ring), and the per-request framework estimate (total) "
                "judged against SELDON_TPU_OVERHEAD_BUDGET_MS",
                ["subsystem"], registry=self.registry)
            self._p_gen_inflight = Gauge(
                "seldon_tpu_gen_inflight_sequences",
                "Sequences riding the continuous-batching generation "
                "scheduler (prefilling + decoding — runtime/genserver.py)",
                registry=self.registry)
            self._p_gen_waiting = Gauge(
                "seldon_tpu_gen_waiting_sequences",
                "Sequences queued for admission into the generation "
                "scheduler (free slot or free KV blocks pending)",
                registry=self.registry)
            self._p_gen_kv_blocks = Gauge(
                "seldon_tpu_gen_kv_blocks",
                "Paged KV-pool blocks by state (used / total / "
                "high_water); used/total is the pool pressure the "
                "SeldonTPUKVPoolPressure alert watches",
                ["state"], registry=self.registry)
            self._p_gen_admitted = Counter(
                "seldon_tpu_gen_admitted_total",
                "Sequences admitted into the in-flight decode batch",
                registry=self.registry)
            self._p_gen_retired = Counter(
                "seldon_tpu_gen_retired_total",
                "Sequences retired from the scheduler, by reason "
                "(eos / length / cancelled / preempted / error)",
                ["reason"], registry=self.registry)
            self._p_gen_steps = Counter(
                "seldon_tpu_gen_steps_total",
                "Scheduler steps executed, by kind (prefill / decode / "
                "spec / mixed / idle)",
                ["kind"], registry=self.registry)
            self._p_gen_step_seconds = Histogram(
                "seldon_tpu_gen_step_seconds",
                "Generation-tick time by kind and phase (flight "
                "recorder): host phases admit / prefill / decode / "
                "retire / host_other, plus fenced device wall under "
                "the *_device phases",
                ["kind", "phase"], registry=self.registry,
                buckets=_GEN_STEP_BUCKETS)
            self._p_gen_bubble = Counter(
                "seldon_tpu_gen_bubble_seconds_total",
                "Device-idle seconds between consecutive scheduler "
                "ticks, by cause (host / admission_stall / "
                "pool_exhaustion / idle) — the SeldonTPUDecodeBubbles "
                "alert's axis",
                ["cause"], registry=self.registry)
            self._p_gen_served_mfu = Gauge(
                "seldon_tpu_gen_served_mfu",
                "Served decode MFU as a 0..1 fraction: real (unpadded) "
                "token FLOPs over fenced decode device time against "
                "the chip's peak — the figure the decode megastep is "
                "judged by",
                registry=self.registry)
            self._p_gen_kv_block_age = Histogram(
                "seldon_tpu_gen_kv_block_age_seconds",
                "Residency of paged KV blocks at release (seconds from "
                "sequence admission to block free)",
                registry=self.registry, buckets=_KV_AGE_BUCKETS)
            self._p_gen_tick_errors = Counter(
                "seldon_tpu_gen_tick_errors_total",
                "Generation scheduler tick-loop exceptions (each one "
                "fails the whole in-flight batch — should be zero)",
                registry=self.registry)
            self._p_kv_handoff = Counter(
                "seldon_tpu_kv_handoff_total",
                "Disaggregated KV-block handoffs by outcome (prefill "
                "side: ok / refused / torn / error; decode side: "
                "imported / reclaimed — runtime/servingmesh.py)",
                ["outcome"], registry=self.registry)
            self._p_kv_handoff_seconds = Histogram(
                "seldon_tpu_kv_handoff_seconds",
                "Wall-clock of one prefill->decode handoff (export + "
                "chunked block stream + remote decode admission)",
                registry=self.registry, buckets=_DISPATCH_BUCKETS)
            self._p_kv_handoff_bytes = Counter(
                "seldon_tpu_kv_handoff_bytes_total",
                "KV bytes streamed over the relay's OP_KVSTREAM frames",
                registry=self.registry)
            self._p_kv_handoff_inflight = Gauge(
                "seldon_tpu_kv_handoff_inflight",
                "Handoffs currently in flight on this prefill replica "
                "(the SeldonTPUKVHandoffStall axis)",
                registry=self.registry)
            self._p_replica_inflight = Gauge(
                "seldon_tpu_replica_inflight",
                "Gateway-side in-flight requests per engine replica "
                "(the power-of-two-choices load signal — "
                "gateway/balancer.py; `set` = deployment/predictor)",
                ["set", "replica"], registry=self.registry)
            self._p_replica_picks = Counter(
                "seldon_tpu_replica_picks_total",
                "Requests routed to each engine replica by the gateway "
                "balancer (`set` = deployment/predictor)",
                ["set", "replica"], registry=self.registry)
            self._p_replica_mispicks = Counter(
                "seldon_tpu_replica_mispicks_total",
                "p2c picks that finished slower than the losing "
                "candidate's EWMA latency at decision time (ratio vs "
                "seldon_tpu_replica_picks_total audits the balancer)",
                registry=self.registry)
            self._p_fleet_outlier = Gauge(
                "seldon_tpu_fleet_outlier_ratio",
                "Worst worse-than-set-median ratio of one replica "
                "across the fleet outlier metrics (dispatch p99, "
                "gateway EWMA, drift, MFU, free KV blocks — "
                "gateway/fleet.py; 2.0 = this replica is 2x worse "
                "than its siblings)",
                ["set", "replica"], registry=self.registry)
            self._p_fleet_replicas = Gauge(
                "seldon_tpu_fleet_replicas",
                "Replicas participating in one set's fleet rollup "
                "(GET /fleet)",
                ["set"], registry=self.registry)
            self._p_fleet_staleness = Gauge(
                "seldon_tpu_fleet_staleness_seconds",
                "Age of one replica's scraped fleet documents at the "
                "last rollup (how far behind the /fleet view may be)",
                ["set", "replica"], registry=self.registry)
            self._p_failovers = Counter(
                "seldon_tpu_failover_total",
                "Inflight work re-homed after a process death: "
                "kind=unary (idempotent predict hedge-re-dispatched to "
                "a peer replica) or kind=stream (SSE decode stream "
                "resumed on a peer by re-prefill — gateway/apife.py)",
                ["kind"], registry=self.registry)
            self._p_lease_transitions = Counter(
                "seldon_tpu_lease_transitions_total",
                "Coordinator-lease tenure changes observed by this "
                "gateway replica (acquired / lost / released / "
                "store_error — gateway/federation.py)",
                ["kind"], registry=self.registry)
            self._p_corpus_rows = Gauge(
                "seldon_tpu_corpus_rows",
                "Dispatch rows appended to the durable perf corpus by "
                "this process (utils/perfcorpus.py — the autopilot "
                "warm-start / learned-cost-model training substrate)",
                registry=self.registry)
            self._p_corpus_bytes = Gauge(
                "seldon_tpu_corpus_bytes",
                "On-disk footprint of the perf corpus (raw segments + "
                "compacted sketches; segment rotation bounds it at "
                "~max_segments x segment_bytes)",
                registry=self.registry)
            self._p_corpus_warm_keys = Gauge(
                "seldon_tpu_corpus_warm_keys",
                "Autopilot keys warm-started from a prior process's "
                "corpus at boot — priced before their first dispatch",
                registry=self.registry)
            self._p_postmortem_kept = Counter(
                "seldon_tpu_postmortem_kept_total",
                "Postmortem exemplars kept by retention reason (error / "
                "shed / slo / autopilot_excess / preemption / breaker / "
                "failover / lease / baseline — utils/postmortem.py); the "
                "SeldonTPUPostmortemFlood alert pages on a sustained "
                "kept rate",
                ["reason"], registry=self.registry)
            self._p_postmortem_dropped = Counter(
                "seldon_tpu_postmortem_dropped_total",
                "Pending postmortem traces evicted without a keep "
                "verdict (buffer overflow or TTL — requests that never "
                "completed, or capture outrunning the bounded buffer)",
                registry=self.registry)
            self._p_postmortem_pinned = Gauge(
                "seldon_tpu_postmortem_pinned_spans",
                "Spans currently pinned inside kept postmortem exemplar "
                "documents (copied out of the trace ring at keep time)",
                registry=self.registry)
            self._p_fleet_burn = Gauge(
                "seldon_tpu_fleet_burn_rate",
                "Fleet-truth SLO burn rate per window: every gateway "
                "replica's published counts folded through the shared "
                "store (gateway/federation.py) — what the brownout "
                "ladder and rollout gates judge; compare against the "
                "per-replica seldon_tpu_slo_burn_rate slice",
                ["window"], registry=self.registry)
            self._p_lane_requests = Counter(
                "seldon_tpu_relay_lane_requests_total",
                "Gateway->engine dispatches by relay lane "
                "(uds / tcp / inprocess — runtime/udsrelay.py)",
                ["lane"], registry=self.registry)
            self._p_wire_requests = Counter(
                "seldon_tpu_wire_requests_total",
                "Predict traffic by lane and wire format (json vs "
                "binary application/x-seldon-tensor — runtime/wire.py)",
                ["lane", "format"], registry=self.registry)
            self._p_wire_bytes_copied = Counter(
                "seldon_tpu_wire_bytes_copied_total",
                "Host-side bytes copied by the binary wire codec and "
                "the lanes feeding it (the bytes_copied_per_request "
                "bench axis — docs/benchmarking.md)",
                registry=self.registry)
            self._p_wire_coalesced = Counter(
                "seldon_tpu_wire_coalesced_total",
                "Requests that rode a gateway-coalesced multi-tensor "
                "engine frame (SELDON_TPU_WIRE_COALESCE_US window)",
                registry=self.registry)
            self._p_shadow_requests = Counter(
                "seldon_tpu_shadow_requests_total",
                "Shadow-mirror outcomes (gateway/shadow.py): mirrored / "
                "sampled_out / capped (concurrency or budget) / "
                "shadow_error — live traffic never appears here",
                ["outcome"], registry=self.registry)
            self._p_shadow_disagreement = Histogram(
                "seldon_tpu_shadow_disagreement",
                "Per-mirrored-request prediction disagreement between "
                "the live and shadow predictors (0 = identical, 1 = "
                "every row differs)",
                registry=self.registry, buckets=_RATIO_BUCKETS)
            self._p_shadow_latency = Histogram(
                "seldon_tpu_shadow_latency_seconds",
                "Shadow-hop wall time (off the live response path by "
                "construction; compare against "
                "seldon_tpu_request_latency_seconds for the delta)",
                registry=self.registry, buckets=_LATENCY_BUCKETS)
            self._p_rollbacks = Counter(
                "seldon_tpu_rollbacks_total",
                "Canary auto-rollbacks by breached gate "
                "(drift / burn_rate / error_rate / shadow / manual — "
                "operator/rollouts.py)",
                ["reason"], registry=self.registry)
            self._p_rollout_stage = Gauge(
                "seldon_tpu_rollout_stage",
                "Candidate traffic percent of the active rollout per "
                "deployment (0 before stage 1 and after a rollback; "
                "100 = fully promoted)",
                ["deployment"], registry=self.registry)
            self._p_autopilot_decisions = Counter(
                "seldon_tpu_autopilot_decisions_total",
                "Predictive decisions taken by the learned cost-model "
                "autopilot, by site (flush = goodput-optimal pad-bucket "
                "choice, p2c = shape-aware replica score, route = "
                "deadline-driven branch demotion — runtime/autopilot.py)",
                ["site"], registry=self.registry)
            self._p_autopilot_shed = Counter(
                "seldon_tpu_autopilot_shed_total",
                "Requests shed with a typed 503 because predicted "
                "queue+dispatch latency exceeded the remaining deadline "
                "budget — refused BEFORE burning device time",
                ["where"], registry=self.registry)
            self._p_autopilot_mispredict = Gauge(
                "seldon_tpu_autopilot_mispredict_pct",
                "Rolling p50 of |measured - predicted| / predicted "
                "dispatch wall, percent — the autopilot's honesty figure "
                "(SeldonTPUAutopilotMispredict alerts on it)",
                registry=self.registry)
            self._p_autopilot_keys = Gauge(
                "seldon_tpu_autopilot_keys",
                "Per-executable/pad-bucket latency models in the "
                "autopilot table (GET /autopilot lists them)",
                registry=self.registry)
            self._p_tenant_requests = Counter(
                "seldon_tpu_tenant_requests_total",
                "Admission attempts per tenant at the gateway "
                "(runtime/qos.py governor; label cardinality bounded "
                "at the source)",
                ["tenant"], registry=self.registry)
            self._p_tenant_throttled = Counter(
                "seldon_tpu_tenant_throttled_total",
                "Requests refused with a typed 429 because the tenant's "
                "token bucket ran dry — a hog's excess, refused before "
                "it queues anywhere (SeldonTPUTenantThrottled alerts "
                "on it)",
                ["tenant"], registry=self.registry)
            self._p_brownout_stage = Gauge(
                "seldon_tpu_brownout_stage",
                "Current brownout degradation stage (0 = normal, 1 = "
                "offline tier shed, 2 = generation degraded, 3 = batch "
                "tier shed — runtime/brownout.py; "
                "SeldonTPUBrownoutActive pages on sustained > 0)",
                registry=self.registry)
            self._p_brownout_transitions = Counter(
                "seldon_tpu_brownout_transitions_total",
                "Brownout stage transitions, labelled by the stage "
                "ENTERED — escalations and reverts both count",
                ["stage"], registry=self.registry)
            self._p_brownout_shed = Counter(
                "seldon_tpu_brownout_shed_total",
                "Requests shed by the brownout ladder, by latency tier "
                "— typed retryable 503s, never silent drops",
                ["tier"], registry=self.registry)
            self._p_cost_device_seconds = Counter(
                "seldon_tpu_cost_device_seconds_total",
                "Fenced device wall attributed to a tenant x deployment "
                "x phase, proportional to real units in each shared "
                "dispatch (utils/costledger.py; GET /costs)",
                ["tenant", "deployment", "phase"], registry=self.registry)
            self._p_cost_kv_block_seconds = Counter(
                "seldon_tpu_cost_kv_block_seconds_total",
                "Per-sequence KV-block residency (blocks x held-time), "
                "integrated at retire/preempt, by tenant x deployment",
                ["tenant", "deployment"], registry=self.registry)
            self._p_cost_pad_tax_seconds = Counter(
                "seldon_tpu_cost_pad_tax_seconds_total",
                "Device wall spent on pow-2 padding, billed to the "
                "tenants whose real units shared the dispatch",
                ["tenant", "deployment"], registry=self.registry)
            self._p_cost_attributed_fraction = Gauge(
                "seldon_tpu_cost_attributed_fraction",
                "(attributed + pad_tax + idle) / fenced device wall — "
                "1.0 when every fold carried attribution; "
                "SeldonTPUUnattributedDeviceTime alerts below 0.97",
                registry=self.registry)

    # -- batcher ---------------------------------------------------------

    def observe_batch(self, rows: int,
                      queue_wait_s: Optional[float] = None) -> None:
        self.batch_occupancy.observe(rows)
        if self.registry is not None:
            self._p_occupancy.observe(rows)
        if queue_wait_s is not None:
            self.observe_queue_wait(queue_wait_s)

    def observe_queue_wait(self, seconds: float) -> None:
        self.batch_queue_wait.observe(seconds)
        if self.registry is not None:
            self._p_queue_wait.observe(seconds)

    def set_inflight(self, n: int) -> None:
        self.inflight = int(n)
        if self.registry is not None:
            self._p_inflight.set(n)

    # -- generation ------------------------------------------------------

    def observe_ttft(self, seconds: float) -> None:
        self.ttft.observe(seconds)
        if self.registry is not None:
            self._p_ttft.observe(seconds)

    def observe_decode_rate(self, tokens_per_s: float) -> None:
        self.decode_rate.observe(tokens_per_s)
        if self.registry is not None:
            self._p_decode_rate.observe(tokens_per_s)

    def observe_accept_ratio(self, ratio: float) -> None:
        self.accept_ratio.observe(ratio)
        if self.registry is not None:
            self._p_accept.observe(ratio)

    def set_kv_slots(self, **states: int) -> None:
        """e.g. set_kv_slots(active=1040, reserved=256) — slot counts of
        the most recent generation dispatch (a point-in-time gauge, not an
        aggregate: TPU HBM pressure is about the current resident cache)."""
        self._gen += 1
        with self._lock:
            self.kv_slots.update({k: int(v) for k, v in states.items()})
        if self.registry is not None:
            for k, v in states.items():
                self._p_kv.labels(state=k).set(v)

    # -- continuous-batching generation scheduler (runtime/genserver.py) -

    def set_gen_scheduler(self, *, inflight: int, waiting: int,
                          blocks_used: int, blocks_total: int,
                          blocks_high_water: int) -> None:
        """Point-in-time scheduler picture, refreshed once per scheduler
        step: in-flight/waiting sequences + paged-KV-pool occupancy."""
        self._gen += 1
        with self._lock:
            self.gen_scheduler.update({
                "inflight": int(inflight), "waiting": int(waiting),
                "blocks_used": int(blocks_used),
                "blocks_total": int(blocks_total),
                "blocks_high_water": int(blocks_high_water),
            })
        if self.registry is not None:
            self._p_gen_inflight.set(inflight)
            self._p_gen_waiting.set(waiting)
            self._p_gen_kv_blocks.labels(state="used").set(blocks_used)
            self._p_gen_kv_blocks.labels(state="total").set(blocks_total)
            self._p_gen_kv_blocks.labels(state="high_water").set(
                blocks_high_water)

    def record_gen_admitted(self, n: int = 1) -> None:
        self._gen += 1
        with self._lock:
            self.gen_admitted += int(n)
        if self.registry is not None:
            self._p_gen_admitted.inc(n)

    def record_gen_retired(self, reason: str, n: int = 1) -> None:
        self._gen += 1
        with self._lock:
            self.gen_retired[reason] = self.gen_retired.get(reason, 0) + n
        if self.registry is not None:
            self._p_gen_retired.labels(reason=reason).inc(n)

    def record_gen_step(self, kind: str, n: int = 1) -> None:
        self._gen += 1
        with self._lock:
            self.gen_steps[kind] = self.gen_steps.get(kind, 0) + n
        if self.registry is not None:
            self._p_gen_steps.labels(kind=kind).inc(n)

    # -- generation flight recorder (utils/genperf.py, fed off-path) -----

    def record_gen_step_seconds(self, kind: str, phase: str,
                                seconds: float) -> None:
        """One tick's time in one phase; host phases carry the plain
        phase name, fenced device wall arrives as ``<phase>_device``."""
        self._gen += 1
        key = f"{kind}/{phase}"
        with self._lock:
            res = self.gen_step_seconds.get(key)
            if res is None:
                res = self.gen_step_seconds[key] = Reservoir()
        res.observe(seconds)
        if self.registry is not None:
            self._p_gen_step_seconds.labels(
                kind=kind, phase=phase).observe(seconds)

    def record_gen_bubble(self, cause: str, seconds: float) -> None:
        self._gen += 1
        with self._lock:
            self.gen_bubble_s[cause] = \
                self.gen_bubble_s.get(cause, 0.0) + float(seconds)
        if self.registry is not None:
            self._p_gen_bubble.labels(cause=cause).inc(seconds)

    def record_gen_kv_block_age(self, seconds: float) -> None:
        self._gen += 1
        self.gen_kv_block_age.observe(seconds)
        if self.registry is not None:
            self._p_gen_kv_block_age.observe(seconds)

    def set_gen_served_mfu(self, frac: float) -> None:
        self._gen += 1
        with self._lock:
            self.gen_served_mfu = float(frac)
        if self.registry is not None:
            self._p_gen_served_mfu.set(frac)

    def record_gen_tick_error(self, n: int = 1) -> None:
        self._gen += 1
        with self._lock:
            self.gen_tick_errors += int(n)
        if self.registry is not None:
            self._p_gen_tick_errors.inc(n)

    # -- disaggregated serving mesh (runtime/servingmesh.py) -------------

    def record_kv_handoff(self, outcome: str, n: int = 1) -> None:
        self._gen += 1
        with self._lock:
            self.kv_handoffs[outcome] = \
                self.kv_handoffs.get(outcome, 0) + n
        if self.registry is not None:
            self._p_kv_handoff.labels(outcome=outcome).inc(n)

    def observe_kv_handoff(self, seconds: float, nbytes: int) -> None:
        self._gen += 1
        with self._lock:
            self.kv_handoff_latency.observe(seconds * 1e3)
            self.kv_handoff_bytes += int(nbytes)
        if self.registry is not None:
            self._p_kv_handoff_seconds.observe(seconds)
            self._p_kv_handoff_bytes.inc(nbytes)

    def set_kv_handoff_inflight(self, n: int) -> None:
        with self._lock:
            self.kv_handoff_inflight = int(n)
        if self.registry is not None:
            self._p_kv_handoff_inflight.set(n)

    # -- serving-mesh balancer (gateway/balancer.py feeds these) ---------

    def set_replica_inflight(self, set_name: str, replica: str,
                             n: int) -> None:
        """Gateway-side outstanding requests on one replica of one
        replica set (``set_name`` = deployment/predictor).  Deliberately
        does NOT bump the stats-cache generation: it moves per request
        under traffic, exactly when the cache exists to help."""
        with self._lock:
            self.replica_inflight.setdefault(set_name, {})[replica] = int(n)
        if self.registry is not None:
            self._p_replica_inflight.labels(
                set=set_name, replica=replica
            ).set(n)

    def record_replica_pick(self, set_name: str, replica: str) -> None:
        with self._lock:
            picks = self.replica_picks.setdefault(set_name, {})
            picks[replica] = picks.get(replica, 0) + 1
        if self.registry is not None:
            self._p_replica_picks.labels(
                set=set_name, replica=replica
            ).inc()

    def set_fleet_outlier(self, set_name: str, replica: str,
                          ratio: float) -> None:
        """The replica's WORST worse-than-median ratio across the fleet
        outlier metrics (gateway/fleet.py) — refreshed on the existing
        scrape tick and on every /fleet query, never per request."""
        with self._lock:
            self.fleet_outliers.setdefault(set_name, {})[replica] = \
                float(ratio)
        if self.registry is not None:
            self._p_fleet_outlier.labels(
                set=set_name, replica=replica).set(ratio)

    def set_fleet_replicas(self, set_name: str, n: int) -> None:
        with self._lock:
            self.fleet_replicas[set_name] = int(n)
        if self.registry is not None:
            self._p_fleet_replicas.labels(set=set_name).set(n)

    def set_fleet_staleness(self, set_name: str, replica: str,
                            seconds: float) -> None:
        if self.registry is not None:
            self._p_fleet_staleness.labels(
                set=set_name, replica=replica).set(seconds)

    def record_replica_mispick(self) -> None:
        with self._lock:
            self.replica_mispicks += 1
        if self.registry is not None:
            self._p_replica_mispicks.inc()

    def record_lane_request(self, lane: str) -> None:
        with self._lock:
            self.lane_requests[lane] = self.lane_requests.get(lane, 0) + 1
        if self.registry is not None:
            self._p_lane_requests.labels(lane=lane).inc()

    # -- binary wire contract (runtime/wire.py feeds these) --------------

    def record_wire_request(self, lane: str, format: str) -> None:
        """One predict served/dispatched on ``lane`` in ``format`` (json
        or binary) — the A/B visibility for the wire rollout."""
        key = f"{lane}/{format}"
        with self._lock:
            self.wire_requests[key] = self.wire_requests.get(key, 0) + 1
        if self.registry is not None:
            self._p_wire_requests.labels(lane=lane, format=format).inc()

    def record_wire_copy(self, nbytes: int) -> None:
        """One host-side byte copy made by the wire codec or a lane
        feeding it (wire.account_copy) — deliberately does NOT bump the
        stats-cache generation: it moves per request under traffic."""
        with self._lock:
            self.wire_bytes_copied += int(nbytes)
            self.wire_copies += 1
        if self.registry is not None:
            self._p_wire_bytes_copied.inc(nbytes)

    def record_wire_coalesced(self, n: int) -> None:
        """``n`` requests rode one coalesced multi-tensor engine frame
        (gateway/apife.py WireCoalescer)."""
        with self._lock:
            self.wire_coalesced += int(n)
        if self.registry is not None:
            self._p_wire_coalesced.inc(n)

    # -- traffic lifecycle (gateway/shadow.py / operator/rollouts.py) ----

    def record_shadow(self, outcome: str, n: int = 1) -> None:
        """Shadow-mirror decision accounting: ``mirrored`` (a copy was
        dispatched), ``sampled_out``, ``capped`` (concurrency/budget
        guard dropped it), ``shadow_error`` (the shadow hop failed —
        never a live failure by construction)."""
        with self._lock:
            self.shadow_requests[outcome] = (
                self.shadow_requests.get(outcome, 0) + n)
        if self.registry is not None:
            self._p_shadow_requests.labels(outcome=outcome).inc(n)

    def observe_shadow(self, disagreement: Optional[float],
                       latency_s: float) -> None:
        """One completed mirror: live-vs-shadow prediction disagreement
        (None when the pair wasn't comparable — e.g. the shadow errored)
        and the shadow hop's own wall time."""
        self.shadow_latency.observe(latency_s)
        if self.registry is not None:
            self._p_shadow_latency.observe(latency_s)
        if disagreement is not None:
            self.shadow_disagreement.observe(float(disagreement))
            if self.registry is not None:
                self._p_shadow_disagreement.observe(float(disagreement))

    def record_failover(self, kind: str) -> None:
        """One piece of inflight work re-homed after a process death
        (kind=unary|stream) — bumped by the gateway's recovery paths,
        never on the happy path."""
        self._gen += 1
        with self._lock:
            self.failovers[kind] = self.failovers.get(kind, 0) + 1
        if self.registry is not None:
            self._p_failovers.labels(kind=kind).inc()

    def record_lease_transition(self, kind: str) -> None:
        """One coordinator/engine lease tenure change as seen by this
        process (acquired / lost / released / store_error)."""
        self._gen += 1
        with self._lock:
            self.lease_transitions[kind] = (
                self.lease_transitions.get(kind, 0) + 1)
        if self.registry is not None:
            self._p_lease_transitions.labels(kind=kind).inc()

    def record_postmortem_kept(self, reason: str) -> None:
        """One postmortem exemplar kept (utils/postmortem.py retention
        verdict at request completion) — labelled by the FIRST reason,
        so the rate per reason reads as 'what kind of anomaly is the
        fleet producing right now'."""
        self._gen += 1
        with self._lock:
            self.postmortem_kept[reason] = (
                self.postmortem_kept.get(reason, 0) + 1)
        if self.registry is not None:
            self._p_postmortem_kept.labels(reason=reason).inc()

    def record_postmortem_dropped(self, n: int = 1) -> None:
        """Pending postmortem traces evicted without a keep verdict
        (buffer overflow / TTL sweep) — bumped fold-side, never on the
        request path."""
        self._gen += 1
        with self._lock:
            self.postmortem_dropped += n
        if self.registry is not None:
            self._p_postmortem_dropped.inc(n)

    def set_postmortem_pinned(self, n: int) -> None:
        """Spans pinned inside kept exemplar documents — refreshed from
        the spine's throttled gauge pass, never per keep."""
        self._gen += 1
        with self._lock:
            self.postmortem_pinned = int(n)
        if self.registry is not None:
            self._p_postmortem_pinned.set(n)

    def set_corpus(self, rows: int, disk_bytes: int,
                   warm_keys: int) -> None:
        """Perf-corpus accounting, refreshed from the spine's throttled
        gauge pass (utils/hotrecord.py), never per-row."""
        self._gen += 1
        with self._lock:
            self.corpus_rows = int(rows)
            self.corpus_bytes = int(disk_bytes)
            self.corpus_warm_keys = int(warm_keys)
        if self.registry is not None:
            self._p_corpus_rows.set(rows)
            self._p_corpus_bytes.set(disk_bytes)
            self._p_corpus_warm_keys.set(warm_keys)

    def set_fleet_burn(self, window: str, rate: float) -> None:
        """One window of the federated fleet-truth burn aggregate —
        set by the gateway federation's burn fold, never per-request."""
        self._gen += 1
        with self._lock:
            self.fleet_burn[window] = float(rate)
        if self.registry is not None:
            self._p_fleet_burn.labels(window=window).set(rate)

    def record_rollback(self, reason: str) -> None:
        self._gen += 1
        with self._lock:
            self.rollbacks[reason] = self.rollbacks.get(reason, 0) + 1
        if self.registry is not None:
            self._p_rollbacks.labels(reason=reason).inc()

    def set_rollout_stage(self, deployment: str, percent: float) -> None:
        self._gen += 1
        with self._lock:
            self.rollout_stage[deployment] = float(percent)
        if self.registry is not None:
            self._p_rollout_stage.labels(deployment=deployment).set(percent)

    # -- learned cost-model autopilot (runtime/autopilot.py) -------------

    def record_autopilot_decision(self, site: str, n: int = 1) -> None:
        """One predictive decision taken (flush / p2c / route) — bumped
        off-path (spine folds) or at low-rate decision sites, never per
        hot-path dispatch."""
        with self._lock:
            self.autopilot_decisions[site] = (
                self.autopilot_decisions.get(site, 0) + n)
        if self.registry is not None:
            self._p_autopilot_decisions.labels(site=site).inc(n)

    def record_autopilot_shed(self, where: str) -> None:
        self._gen += 1
        with self._lock:
            self.autopilot_sheds[where] = (
                self.autopilot_sheds.get(where, 0) + 1)
        if self.registry is not None:
            self._p_autopilot_shed.labels(where=where).inc()

    def autopilot_counters(self) -> "tuple[Dict[str, int], Dict[str, int]]":
        """(sheds, decisions) copied under the lock — the /autopilot
        page reads these concurrently with request threads writing."""
        with self._lock:
            return dict(self.autopilot_sheds), dict(self.autopilot_decisions)

    # -- multi-tenant QoS + brownout (runtime/qos.py / brownout.py) ------

    #: hard cap on distinct tenant labels the recorder itself will hold;
    #: the governor's 256-row LRU is the primary bound, this is the
    #: belt-and-braces one (everything beyond folds into "overflow")
    _TENANT_LABEL_CAP = 512

    def _tenant_label(self, table: Dict[str, int], tenant: str) -> str:
        if tenant in table or len(table) < self._TENANT_LABEL_CAP:
            return tenant
        return "overflow"

    def record_tenant_request(self, tenant: str) -> None:
        with self._lock:
            label = self._tenant_label(self.tenant_requests, tenant)
            self.tenant_requests[label] = (
                self.tenant_requests.get(label, 0) + 1)
        if self.registry is not None:
            self._p_tenant_requests.labels(tenant=label).inc()

    def record_tenant_throttled(self, tenant: str) -> None:
        self._gen += 1
        with self._lock:
            label = self._tenant_label(self.tenant_throttled, tenant)
            self.tenant_throttled[label] = (
                self.tenant_throttled.get(label, 0) + 1)
        if self.registry is not None:
            self._p_tenant_throttled.labels(tenant=label).inc()

    def set_brownout_stage(self, stage: int) -> None:
        self._gen += 1
        with self._lock:
            self.brownout_stage = int(stage)
        if self.registry is not None:
            self._p_brownout_stage.set(stage)

    def record_brownout_transition(self, stage: int) -> None:
        self._gen += 1
        with self._lock:
            key = str(int(stage))
            self.brownout_transitions[key] = (
                self.brownout_transitions.get(key, 0) + 1)
        if self.registry is not None:
            self._p_brownout_transitions.labels(stage=str(int(stage))).inc()

    def record_brownout_shed(self, tier: str) -> None:
        self._gen += 1
        with self._lock:
            self.brownout_sheds[tier] = (
                self.brownout_sheds.get(tier, 0) + 1)
        if self.registry is not None:
            self._p_brownout_shed.labels(tier=tier).inc()

    # -- resource-attribution ledger (utils/costledger.py) --------------
    # All four are delta-fed from the spine's throttled gauge refresh
    # (~1/s) — never per request.  The tenant label cap reuses the QoS
    # overflow rule so the label set stays bounded.

    def record_cost_device_seconds(self, tenant: str, deployment: str,
                                   phase: str, seconds: float) -> None:
        with self._lock:
            label = self._tenant_label(
                {t: 1 for (t, _d, _p) in self.cost_device_s}, tenant)
            key = (label, deployment, phase)
            self.cost_device_s[key] = (
                self.cost_device_s.get(key, 0.0) + seconds)
        if self.registry is not None:
            self._p_cost_device_seconds.labels(
                tenant=label, deployment=deployment, phase=phase,
            ).inc(seconds)

    def record_cost_kv_block_seconds(self, tenant: str, deployment: str,
                                     block_seconds: float) -> None:
        with self._lock:
            label = self._tenant_label(
                {t: 1 for (t, _d) in self.cost_kv_block_s}, tenant)
            key = (label, deployment)
            self.cost_kv_block_s[key] = (
                self.cost_kv_block_s.get(key, 0.0) + block_seconds)
        if self.registry is not None:
            self._p_cost_kv_block_seconds.labels(
                tenant=label, deployment=deployment,
            ).inc(block_seconds)

    def record_cost_pad_tax_seconds(self, tenant: str, deployment: str,
                                    seconds: float) -> None:
        with self._lock:
            label = self._tenant_label(
                {t: 1 for (t, _d) in self.cost_pad_tax_s}, tenant)
            key = (label, deployment)
            self.cost_pad_tax_s[key] = (
                self.cost_pad_tax_s.get(key, 0.0) + seconds)
        if self.registry is not None:
            self._p_cost_pad_tax_seconds.labels(
                tenant=label, deployment=deployment,
            ).inc(seconds)

    def record_cost_attributed_fraction(self, fraction: float) -> None:
        with self._lock:
            self.cost_attributed_fraction = float(fraction)
        if self.registry is not None:
            self._p_cost_attributed_fraction.set(fraction)

    def set_autopilot_model(self, mispredict_p50_pct: Optional[float],
                            keys: int) -> None:
        """Model-health gauges, refreshed from the spine's throttled
        gauge pass (utils/hotrecord.py), not per observation."""
        with self._lock:
            self.autopilot_mispredict_p50_pct = mispredict_p50_pct
            self.autopilot_keys = int(keys)
        if self.registry is not None:
            if mispredict_p50_pct is not None:
                self._p_autopilot_mispredict.set(mispredict_p50_pct)
            self._p_autopilot_keys.set(keys)

    # -- compile cache / audit accounting -------------------------------

    def record_compile_cache(self, outcome: str, n: int = 1) -> None:
        self._gen += 1
        with self._lock:
            self.compile_cache_events[outcome] = (
                self.compile_cache_events.get(outcome, 0) + n)
        if self.registry is not None:
            self._p_compile.labels(outcome=outcome).inc(n)

    def record_audit(self, outcome: str) -> None:
        if self.registry is not None:
            self._p_audit.labels(outcome=outcome).inc()

    # -- resilience layer (runtime/resilience.py) ------------------------

    def set_breaker_state(self, node: str, state: str, gauge: float) -> None:
        self._gen += 1
        with self._lock:
            self.breaker_states[node] = state
        if self.registry is not None:
            self._p_breaker_state.labels(node=node).set(gauge)

    def record_breaker_transition(self, node: str, to: str) -> None:
        self._gen += 1
        key = f"{node}:{to}"
        with self._lock:
            self.breaker_transitions[key] = self.breaker_transitions.get(key, 0) + 1
        if self.registry is not None:
            self._p_breaker_transitions.labels(node=node, to=to).inc()

    def record_retry(self, method: str, outcome: str) -> None:
        """outcome: 'retry' (another attempt is being made) or 'exhausted'
        (attempts/budget ran out and the failure surfaced)."""
        self._gen += 1
        key = f"{method}:{outcome}"
        with self._lock:
            self.retry_attempts[key] = self.retry_attempts.get(key, 0) + 1
        if self.registry is not None:
            self._p_retry.labels(method=method, outcome=outcome).inc()

    def record_retry_budget_exhausted(self) -> None:
        self._gen += 1
        with self._lock:
            self.retry_budget_exhausted += 1
        if self.registry is not None:
            self._p_retry_budget.inc()

    def record_deadline_exceeded(self, where: str) -> None:
        self._gen += 1
        with self._lock:
            self.deadline_exceeded[where] = self.deadline_exceeded.get(where, 0) + 1
        if self.registry is not None:
            self._p_deadline.labels(where=where).inc()

    def record_trace_span(self, kind: str) -> None:
        self._gen += 1
        with self._lock:
            self.trace_spans[kind] = self.trace_spans.get(kind, 0) + 1
        if self.registry is not None:
            self._p_trace_spans.labels(kind=kind).inc()

    def record_degraded(self, mode: str) -> None:
        """mode: 'quorum' (combiner served a subset) or 'fallback' (router
        served the fallback branch)."""
        self._gen += 1
        with self._lock:
            self.degraded_requests[mode] = self.degraded_requests.get(mode, 0) + 1
        if self.registry is not None:
            self._p_degraded.labels(mode=mode).inc()

    # -- performance observatory (utils/perf.py) --------------------------

    def observe_dispatch(self, executable: str, seconds: float,
                         mfu: Optional[float] = None,
                         trace_id: Optional[str] = None) -> None:
        """Per-executable dispatch latency (+ most recent MFU).  A sampled
        trace id rides the histogram observation as an OpenMetrics
        exemplar so a slow bucket links straight to its trace."""
        if self.registry is None:
            return
        child = self._p_dispatch.labels(executable=executable)
        try:
            child.observe(
                seconds,
                exemplar={"trace_id": trace_id} if trace_id else None,
            )
        except (TypeError, ValueError):  # pragma: no cover - old client
            child.observe(seconds)
        if mfu is not None:
            self._p_mfu.labels(executable=executable).set(mfu)

    def record_perf_anomaly(self, kind: str) -> None:
        self._gen += 1
        with self._lock:
            self.perf_anomalies[kind] = self.perf_anomalies.get(kind, 0) + 1
        if self.registry is not None:
            self._p_perf_anomaly.labels(kind=kind).inc()

    def set_hbm(self, device: str, **stats: int) -> None:
        """HBM watermark gauges for one device (bytes_in_use /
        peak_bytes_in_use / bytes_limit — utils/perf.py polls
        ``device.memory_stats()``)."""
        self._gen += 1
        with self._lock:
            self.hbm.setdefault(device, {}).update(
                {k: int(v) for k, v in stats.items()}
            )
        if self.registry is not None:
            for k, v in stats.items():
                gauge = self._p_hbm.get(k)
                if gauge is not None:
                    gauge.labels(device=device).set(v)

    def record_compile_seconds(self, seconds: float) -> None:
        """One XLA compile's wall time — fed by the AOT capture
        (graph/compiled.py) and the jax.monitoring duration listener."""
        self.compile_seconds.observe(seconds)
        if self.registry is not None:
            self._p_compile_seconds.observe(seconds)

    # -- prediction-quality observatory (utils/quality.py) ----------------

    def set_drift(self, node: str, method: str, score: float) -> None:
        """Aggregate drift score for one node (method: psi|ks|prediction)."""
        self._gen += 1
        with self._lock:
            self.drift_scores[f"{node}:{method}"] = float(score)
        if self.registry is not None:
            self._p_drift.labels(node=node, method=method).set(score)

    def set_prediction_quantile(self, node: str, q: str,
                                value: float) -> None:
        self._gen += 1
        with self._lock:
            self.prediction_quantiles[f"{node}:{q}"] = float(value)
        if self.registry is not None:
            self._p_pred_quantile.labels(node=node, q=q).set(value)

    def clear_drift(self, node: str) -> None:
        """Drop one node's published drift scores + prediction quantiles
        — called when its reference window is reset/refrozen, so a stale
        score can't keep an alert firing through the recollection."""
        self._gen += 1
        with self._lock:
            for method in ("psi", "ks", "prediction"):
                self.drift_scores.pop(f"{node}:{method}", None)
            for q in ("0.5", "0.9", "0.99"):
                self.prediction_quantiles.pop(f"{node}:{q}", None)
        if self.registry is not None:
            for method in ("psi", "ks", "prediction"):
                try:
                    self._p_drift.remove(node, method)
                except KeyError:
                    pass
            for q in ("0.5", "0.9", "0.99"):
                try:
                    self._p_pred_quantile.remove(node, q)
                except KeyError:
                    pass

    def record_feedback_event(self, reward: float,
                              truth_provided: bool = False,
                              agreement: Optional[float] = None) -> None:
        """One send_feedback call: reward into the histogram, outcome
        counters (agree/disagree judged by majority row agreement when
        truth was comparable to the served prediction)."""
        self._gen += 1
        self.feedback_reward.observe(reward)
        with self._lock:
            self.feedback_count += 1
            if truth_provided:
                self.feedback_truth += 1
            if agreement is not None:
                if agreement >= 0.5:
                    self.feedback_agree += 1
                else:
                    self.feedback_disagree += 1
        if self.registry is not None:
            self._p_feedback_reward.observe(reward)
            self._p_feedback.labels(outcome="received").inc()
            if truth_provided:
                self._p_feedback.labels(outcome="truth_provided").inc()
            if agreement is not None:
                self._p_feedback.labels(
                    outcome="agree" if agreement >= 0.5 else "disagree"
                ).inc()

    def record_outlier_scores(self, scores) -> None:
        self._gen += 1
        self.outlier_scores.observe_many(scores)
        if self.registry is not None:
            # prometheus_client has no batch observe; this remaining
            # per-value loop is lock-light (histogram child increments)
            for v in scores:
                self._p_outlier.observe(float(v))

    def record_outlier_exceeded(self, n: int = 1) -> None:
        self._gen += 1
        with self._lock:
            self.outlier_exceeded += int(n)
        if self.registry is not None:
            self._p_outlier_exceeded.inc(n)

    def set_slo_burn(self, window: str, rate: float) -> None:
        self._gen += 1
        with self._lock:
            self.slo_burn[window] = float(rate)
        if self.registry is not None:
            self._p_slo_burn.labels(window=window).set(rate)

    def record_quality_sampled(self, node: str) -> None:
        self._gen += 1
        with self._lock:
            self.quality_sampled[node] = self.quality_sampled.get(node, 0) + 1
        if self.registry is not None:
            self._p_quality_sampled.labels(node=node).inc()

    # -- telemetry spine (utils/hotrecord.py drainer feeds these) ---------

    def record_ring_dropped(self, n: int = 1) -> None:
        self._gen += 1
        with self._lock:
            self.telemetry_ring_dropped += int(n)
        if self.registry is not None:
            self._p_ring_dropped.inc(n)

    def set_telemetry_records(self, hop: str, total: int) -> None:
        """Lifetime folded-record count per hop kind; the Prometheus
        counter is advanced by the delta so it stays monotone."""
        self._gen += 1
        with self._lock:
            self.telemetry_records[hop] = int(total)
            prev = self._telemetry_records_published.get(hop, 0)
            if total > prev:
                self._telemetry_records_published[hop] = int(total)
        if self.registry is not None and total > prev:
            self._p_telemetry_records.labels(hop=hop).inc(total - prev)

    def set_framework_overhead(self, subsystem: str, ms: float) -> None:
        self._gen += 1
        with self._lock:
            self.framework_overhead[subsystem] = round(float(ms), 4)
        if self.registry is not None:
            self._p_framework_overhead.labels(subsystem=subsystem).set(ms)

    # -- request latencies (feeds /stats percentiles + the
    # -- seldon_tpu_request_latency_seconds histogram) --------------------

    def request_latency(self, service: str, seconds: float) -> None:
        res = self._latency.get(service)
        if res is None:
            with self._lock:
                res = self._latency.get(service)
                if res is None:
                    if len(self._latency) >= self._latency_cap:
                        return  # bounded label space; drop novel keys
                    res = self._latency[service] = Reservoir()
        res.observe(seconds)
        if self.registry is not None:
            self._p_request_latency.labels(service=service).observe(seconds)

    # -- snapshots -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The zero-dependency JSON body behind ``GET /stats``."""
        if self.drain_hook is not None:
            # fold pending telemetry-spine records first so the snapshot
            # reflects every hop that already served
            self.drain_hook()
        with self._lock:
            kv = dict(self.kv_slots)
            gen_sched = {
                "scheduler": dict(self.gen_scheduler),
                "admitted": self.gen_admitted,
                "retired": dict(self.gen_retired),
                "steps": dict(self.gen_steps),
                "bubble_seconds": dict(self.gen_bubble_s),
                "tick_errors": self.gen_tick_errors,
                "served_mfu": self.gen_served_mfu,
            }
            cc = dict(self.compile_cache_events)
            latency_keys = list(self._latency)
            resilience = {
                "breaker_states": dict(self.breaker_states),
                "breaker_transitions": dict(self.breaker_transitions),
                "retry_attempts": dict(self.retry_attempts),
                "retry_budget_exhausted": self.retry_budget_exhausted,
                "deadline_exceeded": dict(self.deadline_exceeded),
                "degraded_requests": dict(self.degraded_requests),
            }
            trace_spans = dict(self.trace_spans)
            spine = {
                "ring_dropped": self.telemetry_ring_dropped,
                "records": dict(self.telemetry_records),
                "overhead_ms": dict(self.framework_overhead),
            }
            perf = {
                "anomalies": dict(self.perf_anomalies),
                "hbm": {d: dict(v) for d, v in self.hbm.items()},
            }
            feedback = {
                "count": self.feedback_count,
                "truth_provided": self.feedback_truth,
                "agree": self.feedback_agree,
                "disagree": self.feedback_disagree,
            }
            replicas = {
                "inflight": {
                    s: dict(d) for s, d in self.replica_inflight.items()
                },
                "picks": {
                    s: dict(d) for s, d in self.replica_picks.items()
                },
                "mispicks": self.replica_mispicks,
                "lanes": dict(self.lane_requests),
                "fleet_outliers": {
                    s: dict(d) for s, d in self.fleet_outliers.items()
                },
                "failovers": dict(self.failovers),
                "lease_transitions": dict(self.lease_transitions),
                "fleet_burn": dict(self.fleet_burn),
            }
            corpus = {
                "rows": self.corpus_rows,
                "bytes": self.corpus_bytes,
                "warm_keys": self.corpus_warm_keys,
            }
            wire = {
                "requests": dict(self.wire_requests),
                "bytes_copied": self.wire_bytes_copied,
                "copies": self.wire_copies,
                "coalesced": self.wire_coalesced,
            }
            lifecycle = {
                "shadow": dict(self.shadow_requests),
                "rollbacks": dict(self.rollbacks),
                "rollout_stage": dict(self.rollout_stage),
            }
            postmortem = {
                "kept": dict(self.postmortem_kept),
                "dropped": self.postmortem_dropped,
                "pinned_spans": self.postmortem_pinned,
            }
            autopilot = {
                "decisions": dict(self.autopilot_decisions),
                "sheds": dict(self.autopilot_sheds),
                "mispredict_p50_pct": self.autopilot_mispredict_p50_pct,
                "keys": self.autopilot_keys,
            }
            qos = {
                "tenant_requests": dict(self.tenant_requests),
                "tenant_throttled": dict(self.tenant_throttled),
                "brownout_stage": self.brownout_stage,
                "brownout_transitions": dict(self.brownout_transitions),
                "brownout_sheds": dict(self.brownout_sheds),
            }
            cost = {
                "device_s": {
                    "/".join(k): round(v, 6)
                    for k, v in self.cost_device_s.items()
                },
                "kv_block_s": {
                    "/".join(k): round(v, 3)
                    for k, v in self.cost_kv_block_s.items()
                },
                "pad_tax_s": {
                    "/".join(k): round(v, 6)
                    for k, v in self.cost_pad_tax_s.items()
                },
                "attributed_fraction": self.cost_attributed_fraction,
            }
            quality = {
                "drift": dict(self.drift_scores),
                "slo_burn": dict(self.slo_burn),
                "sampled": dict(self.quality_sampled),
                "outliers": {
                    "count": self.outlier_scores.snapshot()["count"],
                    "exceeded": self.outlier_exceeded,
                },
            }
        lifecycle["shadow_disagreement"] = self.shadow_disagreement.snapshot()
        lifecycle["shadow_latency_s"] = self.shadow_latency.snapshot()
        perf["compile_s"] = self.compile_seconds.snapshot()
        feedback["mean_reward"] = round(
            self.feedback_reward.snapshot()["mean"], 6
        )
        return {
            "resilience": resilience,
            "perf": perf,
            "feedback": feedback,
            "quality": quality,
            "replicas": replicas,
            "wire": wire,
            "traffic_lifecycle": lifecycle,
            "autopilot": autopilot,
            "qos": qos,
            "cost": cost,
            "corpus": corpus,
            "postmortem": postmortem,
            "batch": {
                "occupancy": self.batch_occupancy.snapshot(),
                "queue_wait_s": self.batch_queue_wait.snapshot(),
                "inflight_dispatches": self.inflight,
            },
            "generation": {
                "ttft_s": self.ttft.snapshot(),
                "decode_tokens_per_s": self.decode_rate.snapshot(),
                "speculative_accept_ratio": self.accept_ratio.snapshot(),
                "kv_cache_slots": kv,
                "continuous": gen_sched,
                "kv_handoffs": dict(self.kv_handoffs),
                "kv_handoff_ms": self.kv_handoff_latency.snapshot(),
                "kv_handoff_bytes": self.kv_handoff_bytes,
                "kv_handoff_inflight": self.kv_handoff_inflight,
            },
            "compile_cache_events": cc,
            "trace_spans": trace_spans,
            "telemetry_spine": spine,
            "request_latency_s": {
                k: self._latency[k].snapshot() for k in latency_keys
            },
        }

    def exposition(self, openmetrics: bool = False) -> bytes:
        """Prometheus text exposition.  ``openmetrics=True`` renders the
        OpenMetrics format instead — the only exposition that carries the
        trace_id exemplars on ``seldon_tpu_dispatch_seconds`` buckets.

        Scrapes are the natural HBM-watermark poll point: refresh the
        ``seldon_tpu_hbm_*`` gauges (throttled inside the observatory) so
        a Prometheus-only deployment — nobody polling ``/perf`` — still
        sees live watermarks and the HBM-pressure alert can fire."""
        if self.drain_hook is not None:
            # scrape-only deployments must see every folded hop too —
            # the exposition is a query surface like /stats
            self.drain_hook()
        if self.registry is None:
            return b""
        try:
            from seldon_core_tpu.utils.perf import OBSERVATORY

            OBSERVATORY.hbm_watermarks()
        except Exception:  # noqa: BLE001 - scrape must never fail on polling
            pass
        try:
            # same rationale for the SLO burn gauges: a Prometheus-only
            # deployment must see live burn rates at scrape time
            from seldon_core_tpu.utils.quality import QUALITY

            QUALITY.refresh_gauges()
        except Exception:  # noqa: BLE001
            pass
        if openmetrics:
            from prometheus_client.openmetrics.exposition import (
                generate_latest as om_generate_latest,
            )

            return om_generate_latest(self.registry)
        return generate_latest(self.registry)

    def reset(self) -> None:
        """Fresh distributions/counters — tests only (Prometheus counters
        are monotone by design and are left alone)."""
        if self.drain_hook is not None:
            # stale ring records from earlier traffic must fold BEFORE the
            # reset, not leak into the fresh state afterwards
            self.drain_hook()
        self._gen += 1
        self.batch_occupancy = Reservoir()
        self.batch_queue_wait = Reservoir()
        self.ttft = Reservoir()
        self.decode_rate = Reservoir()
        self.accept_ratio = Reservoir()
        self.compile_seconds = Reservoir()
        self.inflight = 0
        with self._lock:
            self.kv_slots = {}
            self.compile_cache_events = {}
            self._latency = {}
            self.breaker_states = {}
            self.breaker_transitions = {}
            self.retry_attempts = {}
            self.retry_budget_exhausted = 0
            self.deadline_exceeded = {}
            self.degraded_requests = {}
            self.trace_spans = {}
            self.perf_anomalies = {}
            self.hbm = {}
            self.drift_scores = {}
            self.prediction_quantiles = {}
            self.feedback_count = 0
            self.feedback_reward = Reservoir()
            self.feedback_truth = 0
            self.feedback_agree = 0
            self.feedback_disagree = 0
            self.cost_device_s = {}
            self.cost_kv_block_s = {}
            self.cost_pad_tax_s = {}
            self.cost_attributed_fraction = None
            self.outlier_scores = Reservoir()
            self.outlier_exceeded = 0
            self.slo_burn = {}
            self.quality_sampled = {}
            self.telemetry_ring_dropped = 0
            self.telemetry_records = {}
            self.framework_overhead = {}
            self.gen_scheduler = {}
            self.gen_admitted = 0
            self.gen_retired = {}
            self.gen_steps = {}
            self.gen_step_seconds = {}
            self.gen_bubble_s = {}
            self.gen_kv_block_age = Reservoir()
            self.gen_served_mfu = None
            self.gen_tick_errors = 0
            self.kv_handoffs = {}
            self.kv_handoff_latency = Reservoir()
            self.kv_handoff_bytes = 0
            self.kv_handoff_inflight = 0
            self.replica_inflight = {}
            self.replica_picks = {}
            self.replica_mispicks = 0
            self.lane_requests = {}
            self.wire_requests = {}
            self.wire_bytes_copied = 0
            self.wire_copies = 0
            self.wire_coalesced = 0
            self.fleet_outliers = {}
            self.fleet_replicas = {}
            self.failovers = {}
            self.lease_transitions = {}
            self.corpus_rows = 0
            self.corpus_bytes = 0
            self.corpus_warm_keys = 0
            self.fleet_burn = {}
            self.shadow_requests = {}
            self.shadow_disagreement = Reservoir()
            self.shadow_latency = Reservoir()
            self.rollbacks = {}
            self.rollout_stage = {}
            self.autopilot_decisions = {}
            self.autopilot_sheds = {}
            self.autopilot_mispredict_p50_pct = None
            self.autopilot_keys = 0
            self.tenant_requests = {}
            self.tenant_throttled = {}
            self.brownout_stage = 0
            self.brownout_transitions = {}
            self.brownout_sheds = {}
            self.postmortem_kept = {}
            self.postmortem_dropped = 0
            self.postmortem_pinned = 0


RECORDER = FlightRecorder()


# ---------------------------------------------------------------------------
# Request-audit firehose (engine side)
# ---------------------------------------------------------------------------


def _default_audit_dir() -> str:
    return os.environ.get(
        "SELDON_TPU_AUDIT_DIR", os.path.expanduser("~/.seldon_tpu_audit")
    )


class AuditLog:
    """Async bounded-queue JSONL request-audit logger — the Kafka-firehose
    analogue at the ENGINE edge (the gateway's firehose logs request/
    response bodies; this logs the SERVING TELEMETRY of each request:
    puid, graph path, batch rows, latency breakdown, token counts).

    ``record()`` is non-blocking by construction: ``put_nowait`` into a
    bounded queue; a full queue increments ``dropped`` and the event is
    gone (matching the reference's fire-and-forget Kafka producer).  The
    drain task writes JSONL lines off the hot path; it is started lazily
    on the first ``record()`` made with a running event loop, so no lane
    needs boot wiring.

    Disabled (``enabled=False``, the default unless ``SELDON_TPU_AUDIT=1``
    or a path/sink is given) the logger is a null object: ``record()``
    returns False at the cost of one attribute load."""

    def __init__(
        self,
        path: Optional[str] = None,
        sink: Optional[Callable[[dict], None]] = None,
        max_queue: int = 4096,
        enabled: Optional[bool] = None,
    ):
        if enabled is None:
            enabled = (
                path is not None
                or sink is not None
                or os.environ.get("SELDON_TPU_AUDIT", "") not in ("", "0")
            )
        self.enabled = bool(enabled)
        self.path = path or os.path.join(_default_audit_dir(), "audit.jsonl")
        self.sink = sink
        self.max_queue = int(max_queue)
        self.recorded = 0
        self.dropped = 0
        self.written = 0
        self._queue: deque = deque()
        self._wakeup: Optional[Any] = None  # asyncio.Event, loop-bound
        self._task = None
        self._loop = None  # the loop the drain task currently runs on

    def record(self, **event: Any) -> bool:
        """Enqueue one audit event; returns False when disabled or
        dropped.  Never blocks, never raises."""
        if not self.enabled:
            return False
        if len(self._queue) >= self.max_queue:
            self.dropped += 1
            RECORDER.record_audit("dropped")
            return False
        event.setdefault("ts", time.time())
        self._queue.append(event)
        self.recorded += 1
        RECORDER.record_audit("recorded")
        self._ensure_drain()
        return True

    def _ensure_drain(self) -> None:
        import asyncio

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop: events wait in the bounded deque
        # the drain task binds to the loop that first recorded — which
        # may be a SIDE loop (the disagg coordinator's thread records
        # kv_handoff lines) or one a test already tore down.  Re-home
        # ONLY when the bound task/loop is actually dead: two LIVE loops
        # recording concurrently (serving + coordinator) must share one
        # drain task, not cancel-and-recreate it per alternation
        if (self._task is None or self._task.done()
                or self._loop is None or self._loop.is_closed()):
            self._wakeup = asyncio.Event()
            self._loop = loop
            self._task = loop.create_task(self._drain())
        if self._wakeup is not None:
            if self._loop is loop:
                self._wakeup.set()
            else:
                # asyncio primitives are not thread-safe: wake the
                # owning loop's drain from ITS thread
                try:
                    self._loop.call_soon_threadsafe(self._wakeup.set)
                except RuntimeError:
                    pass  # owner died between the check and the wake;
                    # the next record re-homes the drain

    async def _drain(self) -> None:
        import asyncio

        while True:
            if not self._queue:
                self._wakeup.clear()
                await self._wakeup.wait()
            batch: List[dict] = []
            while self._queue and len(batch) < 256:
                batch.append(self._queue.popleft())
            if not batch:
                continue
            try:
                if self.sink is not None:
                    for ev in batch:
                        self.sink(ev)
                else:
                    # one writev-sized append per batch, built off-queue
                    lines = "".join(
                        json.dumps(ev, separators=(",", ":"), default=str)
                        + "\n"
                        for ev in batch
                    )
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._append, lines
                    )
                self.written += len(batch)
            except Exception:
                self.dropped += len(batch)
                RECORDER.record_audit("write_error")

    def _append(self, lines: str) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "a") as f:
            f.write(lines)

    async def flush(self, timeout_s: float = 5.0) -> None:
        """Wait until everything recorded so far is written (tests and
        graceful shutdown; serving never calls this)."""
        import asyncio

        self._ensure_drain()
        deadline = time.monotonic() + timeout_s
        while self._queue and time.monotonic() < deadline:
            await asyncio.sleep(0.005)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "path": None if self.sink is not None else self.path,
            "queued": len(self._queue),
            "max_queue": self.max_queue,
            "recorded": self.recorded,
            "written": self.written,
            "dropped": self.dropped,
        }

    async def stop(self) -> None:
        if self._task is not None:
            await self.flush()
            self._task.cancel()
            self._task = None


# ---------------------------------------------------------------------------
# Compile-cache event listener
# ---------------------------------------------------------------------------

_compile_listener_installed = False
#: set only when the jax.monitoring DURATION listener registered — older
#: jax builds have the count-event API but not the duration one, and the
#: AOT compile capture (utils/perf.py) must keep recording durations
#: itself in that case
_compile_duration_listener_installed = False


def install_compile_cache_listener() -> bool:
    """Map jax.monitoring compilation events onto the flight recorder:
    compilation-cache events become
    ``seldon_tpu_compile_cache_events_total{outcome=hit|miss}`` counts,
    and backend-compile durations (``/jax/core/compile/
    backend_compile_duration``-shaped events) land in the
    ``seldon_tpu_compile_seconds`` histogram — hit/miss says WHETHER a
    restart re-pays XLA compiles, the durations say how much each one
    cost.  Event names vary across jax versions; classification is by
    substring, everything else ignored.  Degrades cleanly (returns False,
    nothing registered) when jax.monitoring is absent.  Idempotent;
    returns True when listeners are registered."""
    global _compile_listener_installed, _compile_duration_listener_installed
    if _compile_listener_installed:
        return True
    try:
        import jax.monitoring as _mon

        def _on_event(name: str, **kw) -> None:
            if "compilation_cache" not in name:
                return
            if "hit" in name:
                RECORDER.record_compile_cache("hit")
            elif "miss" in name:
                RECORDER.record_compile_cache("miss")

        def _on_duration(name: str, duration_secs: float, **kw) -> None:
            if "backend_compile" in name:
                RECORDER.record_compile_seconds(float(duration_secs))

        _mon.register_event_listener(_on_event)
        # older jax builds may lack the duration-listener API; the count
        # listener alone is still worth keeping
        register_duration = getattr(
            _mon, "register_event_duration_secs_listener", None
        )
        if register_duration is not None:
            register_duration(_on_duration)
            _compile_duration_listener_installed = True
        _compile_listener_installed = True
        return True
    except Exception:
        return False
