"""Prediction-quality observatory — on-device drift detection, feedback/
reward accounting, and SLO burn-rate tracking.

The flight recorder (utils/telemetry.py) says how many requests flow, the
causal tracer (utils/tracing.py) says where time goes, and the perf
observatory (utils/perf.py) says whether the TPU is used well — but none
of them watches whether the PREDICTIONS themselves are still good.
Drifting inputs and silently degrading models are the dominant production
failure mode a serving mesh must surface (the reference platform's
signature concern: outlier TRANSFORMERs, ``/api/v0.1/feedback`` reward
routing, per-predictor metrics).  This module closes that loop with three
instruments:

  * **Drift detection**: per graph node, a frozen **reference window**
    plus a rolling **live window** of sampled inputs and predictions.
    The per-batch reservoir update (per-feature bin counts against
    reference-quantile edges, mean/var accumulators, a prediction
    histogram) is computed as ONE batched ``jnp`` program riding the
    dispatch batch — ``engine._batched_predict_sync`` and the native
    plane's dispatch loop hand the already-stacked batch over, so quality
    monitoring costs one small fused kernel per sampled batch, never a
    per-row Python loop.  Live-vs-reference distance is scored as **PSI**
    and a **KS statistic** per feature plus a prediction-distribution
    shift score.  The learned-cost-model literature (TpuGraphs, arxiv
    2308.13490; A Learned Performance Model for TPUs, arxiv 2008.01040)
    shows cheap static graph features predict runtime well; the dual
    insight here is that cheap batched statistics piggybacked on dispatch
    predict model-quality decay without a separate monitoring fleet.
  * **Feedback accounting**: ``send_feedback`` rewards and
    truth-vs-prediction agreement fold into rolling per-predictor
    reward/accuracy; MAB ROUTER pytree state (success/tries counters)
    is read back out into per-branch reward, routing share, and regret
    (``router_quality``) instead of staying opaque on device.
  * **SLO engine**: per-graph latency/error objectives
    (``SELDON_TPU_SLO_P99_MS``, ``SELDON_TPU_SLO_ERROR_RATE``) tracked
    as multi-window (5m/1h) burn rates over the request stream the
    existing latency histograms already observe.

Surfaces: ``GET /quality`` (engine rest + fast + native misc lanes, unit
pods), ``POST /quality/reference`` (freeze/reset the reference window),
the ``seldon_tpu_drift_score`` / ``seldon_tpu_prediction_quantile`` /
``seldon_tpu_feedback_*`` / ``seldon_tpu_outlier_*`` /
``seldon_tpu_slo_burn_rate`` / ``seldon_tpu_quality_sampled_total``
Prometheus families, drift stamped onto dispatch spans and audit-firehose
lines.

Everything is process-global (module global ``QUALITY``, the
``OBSERVATORY``/``TRACER``/``RECORDER`` pattern) and never raises into
the hot path.  ``SELDON_TPU_QUALITY=0`` disables the subsystem entirely;
``SELDON_TPU_QUALITY_SAMPLE`` (0..1, decided once per batch) bounds its
cost under load.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

import numpy as np

from seldon_core_tpu.utils.telemetry import RECORDER, Reservoir

__all__ = [
    "QualityObservatory",
    "QUALITY",
    "SloTracker",
    "FleetBurnView",
    "FLEET_BURN",
    "fleet_burn_enabled",
    "effective_burn_rate",
    "router_quality",
    "psi",
    "ks_statistic",
    "parse_reference_action",
]

logger = logging.getLogger(__name__)

#: proportion floor for PSI's log ratio — the standard smoothing that
#: keeps an empty bin from yielding an infinite score
_EPS_P = 1e-6


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name, "")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# score math (plain numpy on the small aggregated count vectors — the
# per-batch heavy lifting happened on device already)
# ---------------------------------------------------------------------------


def _proportions(counts) -> np.ndarray:
    counts = np.asarray(counts, dtype=np.float64)
    total = counts.sum(axis=-1, keepdims=True)
    return counts / np.maximum(total, 1.0)


def psi(ref_counts, live_counts) -> np.ndarray:
    """Population Stability Index between binned distributions (last axis
    = bins; leading axes broadcast, e.g. per-feature rows).  Proportions
    are floored at 1e-6 so empty bins score finitely — the convention the
    hand-computed tests and the docs runbook both state."""
    p = np.clip(_proportions(ref_counts), _EPS_P, None)
    q = np.clip(_proportions(live_counts), _EPS_P, None)
    return ((q - p) * np.log(q / p)).sum(axis=-1)


def ks_statistic(ref_counts, live_counts) -> np.ndarray:
    """Kolmogorov–Smirnov distance between binned distributions: the max
    absolute CDF gap across bin boundaries (exact proportions, no
    smoothing needed)."""
    p = _proportions(ref_counts).cumsum(axis=-1)
    q = _proportions(live_counts).cumsum(axis=-1)
    return np.abs(q - p).max(axis=-1)


# ---------------------------------------------------------------------------
# batched summarizer — the one fused kernel riding the dispatch batch
# ---------------------------------------------------------------------------

_jit_summarize = None
_jit_failed = False


def _get_jit_summarizer():
    """Lazily built jitted summarizer shared by every node (shapes trace
    per (batch, width, bins) combination — bounded on the engine lane by
    the batcher's power-of-two buckets).  None when jax is unavailable;
    the numpy fallback then owns the math with identical outputs."""
    global _jit_summarize, _jit_failed
    if _jit_summarize is not None or _jit_failed:
        return _jit_summarize
    try:
        import jax
        import jax.numpy as jnp

        def summarize(X, Y, x_thr, y_thr, n):
            # X [N,F] f32, Y [N,C] f32, x_thr [F,Bx-1], y_thr [By-1],
            # n = real (unpadded) rows.  Bin counts come from cumulative
            # >=-threshold counts (bin b = count(>=thr[b-1]) -
            # count(>=thr[b])) — no [N,F,B] one-hot materialization.
            w = (jnp.arange(X.shape[0]) < n).astype(jnp.float32)
            n_eff = w.sum()
            geq = (X[:, :, None] >= x_thr[None, :, :]).astype(jnp.float32)
            gcounts = (geq * w[:, None, None]).sum(0)  # [F, Bx-1]
            full = jnp.full((X.shape[1], 1), 0.0) + n_eff
            x_counts = jnp.concatenate([full, gcounts], axis=1) - \
                jnp.concatenate([gcounts, jnp.zeros((X.shape[1], 1))], axis=1)
            x_sum = (X * w[:, None]).sum(0)
            x_sumsq = (X * X * w[:, None]).sum(0)
            ygeq = (Y[:, :, None] >= y_thr[None, None, :]).astype(jnp.float32)
            ygc = (ygeq * w[:, None, None]).sum((0, 1))  # [By-1]
            ny = n_eff * Y.shape[1]
            y_counts = jnp.concatenate([ny[None], ygc]) - \
                jnp.concatenate([ygc, jnp.zeros((1,))])
            y_sum = (Y * w[:, None]).sum()
            y_sumsq = (Y * Y * w[:, None]).sum()
            return x_counts, x_sum, x_sumsq, y_counts, y_sum, y_sumsq

        _jit_summarize = jax.jit(summarize)
    except Exception:  # noqa: BLE001 - no jax backend: numpy fallback
        _jit_failed = True
        _jit_summarize = None
    return _jit_summarize


def _summarize_np(X, Y, x_thr, y_thr, n):
    """Numpy twin of the jitted summarizer — CPU degradation path and the
    cross-check oracle in tests.  Identical outputs by construction."""
    X = np.asarray(X, dtype=np.float32)[:n]
    Y = np.asarray(Y, dtype=np.float32).reshape(len(Y), -1)[:n]
    F = X.shape[1]
    gcounts = (X[:, :, None] >= x_thr[None, :, :]).sum(0).astype(np.float64)
    lower = np.concatenate([np.full((F, 1), float(len(X))), gcounts], axis=1)
    upper = np.concatenate([gcounts, np.zeros((F, 1))], axis=1)
    x_counts = lower - upper
    ygc = (Y[:, :, None] >= y_thr[None, None, :]).sum((0, 1)).astype(np.float64)
    ny = float(len(Y) * Y.shape[1])
    y_counts = np.concatenate([[ny], ygc]) - np.concatenate([ygc, [0.0]])
    return (
        x_counts, X.sum(0), (X * X).sum(0),
        y_counts, float(Y.sum()), float((Y * Y).sum()),
    )


# ---------------------------------------------------------------------------
# per-node windows
# ---------------------------------------------------------------------------


class _NodeQuality:
    """Reference + rolling live window for one graph node."""

    def __init__(self, node: str, n_bins: int, ref_target: int,
                 live_window: int, score_interval_s: float = 0.25):
        self.node = node
        self.n_bins = int(n_bins)
        self.ref_target = int(ref_target)
        self.live_window = int(live_window)  # live batches retained
        #: PSI/KS rescore throttle: scoring walks (F, B) count arrays and
        #: publishes six gauges — per-BATCH that dominated the fold cost,
        #: while the scores are only read at human timescales.  The first
        #: live batch always scores (alerts must not wait), then at most
        #: once per interval; every read surface (document/quality page)
        #: forces a fresh score.
        self.score_interval_s = float(score_interval_s)
        self._scored_at = 0.0
        self.lock = threading.Lock()
        #: bumped on every clear/freeze — an in-flight observation that
        #: summarized against superseded thresholds must not land in the
        #: new window (the summarize happens outside the lock by design)
        self.generation = 0
        self._clear()

    def _clear(self) -> None:
        self.generation += 1
        self.frozen = False
        self._ref_x: List[np.ndarray] = []
        self._ref_y: List[np.ndarray] = []
        self._ref_width: Optional[int] = None
        self._ref_y_width: Optional[int] = None
        self.ref_rows = 0
        self.x_thr: Optional[np.ndarray] = None   # [F, B-1]
        self.y_thr: Optional[np.ndarray] = None   # [B-1]
        self.ref_x_counts: Optional[np.ndarray] = None  # [F, B]
        self.ref_y_counts: Optional[np.ndarray] = None  # [B]
        self.ref_x_mean: Optional[np.ndarray] = None
        self.ref_x_std: Optional[np.ndarray] = None
        self.sampled_batches = 0
        self.sampled_rows = 0
        self.width_mismatches = 0
        self._blocks: deque = deque()
        self.live_x_counts: Optional[np.ndarray] = None
        self.live_x_sum: Optional[np.ndarray] = None
        self.live_x_sumsq: Optional[np.ndarray] = None
        self.live_y_counts: Optional[np.ndarray] = None
        self.live_rows = 0
        self.last_scores: Dict[str, float] = {}

    # -- reference ---------------------------------------------------------

    def _collect_reference(self, X: np.ndarray, Y: np.ndarray) -> None:
        # a node serving several feature widths can only reference ONE of
        # them (the windows are per-feature arrays): first width seen
        # wins, others are counted and skipped — without this guard a
        # mixed-width node would hoard raw rows forever and never freeze
        if self._ref_width is None:
            self._ref_width = X.shape[1]
            self._ref_y_width = Y.shape[1]
        elif (X.shape[1] != self._ref_width
              or Y.shape[1] != self._ref_y_width):
            self.width_mismatches += 1
            return
        self._ref_x.append(np.asarray(X, dtype=np.float64))
        self._ref_y.append(np.asarray(Y, dtype=np.float64).reshape(len(Y), -1))
        self.ref_rows += len(X)
        if self.ref_rows >= self.ref_target:
            self._freeze()

    def _freeze(self) -> bool:
        """Fix the collected rows as the reference: per-feature bin edges
        at reference quantiles (the classic PSI construction), reference
        counts/mean/std, empty live window.  False when nothing was
        collected yet."""
        if not self._ref_x:
            return False
        self.generation += 1
        ref = np.concatenate(self._ref_x, axis=0)
        ref_y = np.concatenate(self._ref_y, axis=0).reshape(-1)
        B = self.n_bins
        qs = np.arange(1, B) / B
        # inner thresholds: bin index of x = #(x >= thr) in [0, B-1]
        self.x_thr = np.quantile(ref, qs, axis=0).T.astype(np.float32)
        self.y_thr = np.quantile(ref_y, qs).astype(np.float32)
        F = ref.shape[1]
        counts, _, _, yc, _, _ = _summarize_np(
            ref, np.concatenate(self._ref_y, axis=0),
            self.x_thr, self.y_thr, len(ref),
        )
        self.ref_x_counts = counts
        self.ref_y_counts = yc
        self.ref_x_mean = ref.mean(axis=0)
        self.ref_x_std = ref.std(axis=0) + 1e-12
        self.ref_rows = len(ref)
        self._ref_x = []
        self._ref_y = []
        self.frozen = True
        self._blocks = deque()
        self.live_x_counts = np.zeros((F, self.n_bins))
        self.live_x_sum = np.zeros(F)
        self.live_x_sumsq = np.zeros(F)
        self.live_y_counts = np.zeros(self.n_bins)
        self.live_rows = 0
        self.last_scores = {}
        return True

    # -- live --------------------------------------------------------------

    def _push_block(self, x_counts, x_sum, x_sumsq, y_counts, rows) -> None:
        block = (x_counts, x_sum, x_sumsq, y_counts, rows)
        self._blocks.append(block)
        self.live_x_counts += x_counts
        self.live_x_sum += x_sum
        self.live_x_sumsq += x_sumsq
        self.live_y_counts += y_counts
        self.live_rows += rows
        while len(self._blocks) > self.live_window:
            oc, osum, osq, oyc, orows = self._blocks.popleft()
            self.live_x_counts -= oc
            self.live_x_sum -= osum
            self.live_x_sumsq -= osq
            self.live_y_counts -= oyc
            self.live_rows -= orows

    def _maybe_score(self) -> Dict[str, float]:
        """Throttled rescore for the per-batch fold path: {} when the
        current scores are still fresh (callers then reuse
        ``last_scores``)."""
        now = time.monotonic()
        if self.last_scores and now - self._scored_at < self.score_interval_s:
            return {}
        self._scored_at = now
        return self._score()

    def _score(self) -> Dict[str, float]:
        if not self.frozen or self.live_rows <= 0:
            return {}
        x_psi = psi(self.ref_x_counts, self.live_x_counts)
        x_ks = ks_statistic(self.ref_x_counts, self.live_x_counts)
        y_psi = float(psi(self.ref_y_counts, self.live_y_counts))
        self._x_psi = x_psi
        self._x_ks = x_ks
        self.last_scores = {
            "psi_max": float(x_psi.max()),
            "psi_mean": float(x_psi.mean()),
            "ks_max": float(x_ks.max()),
            "prediction_psi": y_psi,
        }
        return self.last_scores

    def prediction_quantiles(self) -> Dict[str, float]:
        """Approximate live prediction quantiles off the binned CDF —
        quantile value = the upper bin threshold where the CDF crosses q
        (a B-bin sketch, not an exact order statistic)."""
        if not self.frozen or self.live_rows <= 0 or self.y_thr is None \
                or len(self.y_thr) == 0:
            return {}
        cdf = _proportions(self.live_y_counts).cumsum()
        out = {}
        for q in (0.5, 0.9, 0.99):
            j = int(np.searchsorted(cdf, q))
            out[str(q)] = float(self.y_thr[min(j, len(self.y_thr) - 1)])
        return out

    def document_row(self, top_k: int = 16) -> Dict[str, Any]:
        if self.frozen and self.live_rows > 0:
            # read surfaces always serve a fresh score, whatever the
            # per-batch throttle last left behind
            self._scored_at = time.monotonic()
            self._score()
        row: Dict[str, Any] = {
            "node": self.node,
            "status": "live" if self.frozen else "collecting_reference",
            "sampled_batches": self.sampled_batches,
            "sampled_rows": self.sampled_rows,
            "ref_rows": self.ref_rows,
            "live_rows": int(self.live_rows),
        }
        if self.width_mismatches:
            row["width_mismatches"] = self.width_mismatches
        if self.frozen and self.last_scores:
            row["drift"] = {
                k: round(v, 6) for k, v in self.last_scores.items()
            }
            live_n = max(self.live_rows, 1)
            live_mean = self.live_x_sum / live_n
            order = np.argsort(self._x_psi)[::-1][:top_k]
            row["top_features"] = [
                {
                    "feature": int(i),
                    "psi": round(float(self._x_psi[i]), 6),
                    "ks": round(float(self._x_ks[i]), 6),
                    "ref_mean": round(float(self.ref_x_mean[i]), 6),
                    "live_mean": round(float(live_mean[i]), 6),
                }
                for i in order
            ]
            pq = self.prediction_quantiles()
            if pq:
                row["prediction_quantiles"] = {
                    k: round(v, 6) for k, v in pq.items()
                }
        return row


# ---------------------------------------------------------------------------
# SLO burn-rate engine
# ---------------------------------------------------------------------------


class SloTracker:
    """Multi-window SLO burn rates over the request stream.

    Objectives come from ``SELDON_TPU_SLO_P99_MS`` (latency: at most 1% of
    requests may exceed the target — the definition of a p99 objective, so
    the latency error budget is 0.01) and ``SELDON_TPU_SLO_ERROR_RATE``
    (allowed 5xx fraction).  Burn rate per window = observed bad fraction
    over the budget: 1.0 burns the budget exactly as fast as allowed,
    14.4x over 5m / 6x over 1h are the classic fast/slow-burn page
    thresholds.  Events land in per-second slots of a fixed one-hour
    ring — ``record()`` is O(1); window sums happen on read."""

    WINDOWS = (("5m", 300), ("1h", 3600))
    HORIZON = 3600
    LATENCY_BUDGET = 0.01
    #: finite stand-in for "infinite burn" (a zero error budget with any
    #: error flowing) — JSON-safe where float('inf') is not
    BURN_CAP = 1e6

    def __init__(self, p99_ms: Optional[float] = None,
                 error_rate: Optional[float] = None,
                 horizon: Optional[int] = None):
        self.p99_ms = (
            p99_ms if p99_ms is not None
            else _env_float("SELDON_TPU_SLO_P99_MS")
        )
        self.error_rate = (
            error_rate if error_rate is not None
            else _env_float("SELDON_TPU_SLO_ERROR_RATE")
        )
        # a smaller horizon shrinks the per-second ring (and drops the
        # windows it can't cover) — the per-tenant trackers use 300 s so
        # 256 tenants cost ~2.5 MB instead of ~30 MB
        self.horizon = int(horizon) if horizon else self.HORIZON
        self.windows = tuple(
            (name, w) for name, w in self.WINDOWS if w <= self.horizon
        ) or (self.WINDOWS[0],)
        self._lock = threading.Lock()
        self._sec = np.zeros(self.horizon, dtype=np.int64)
        self._counts = np.zeros((self.horizon, 3), dtype=np.int64)

    @property
    def configured(self) -> bool:
        return self.p99_ms is not None or self.error_rate is not None

    def record(self, latency_s: float, error: bool = False,
               now: Optional[float] = None) -> None:
        ts = int(now if now is not None else time.time())
        i = ts % self.horizon
        with self._lock:
            if self._sec[i] != ts:
                self._sec[i] = ts
                self._counts[i] = 0
            self._counts[i, 0] += 1
            if self.p99_ms is not None and latency_s * 1e3 > self.p99_ms:
                self._counts[i, 1] += 1
            if error:
                self._counts[i, 2] += 1

    def window_counts(
            self, now: Optional[float] = None
    ) -> Dict[str, Dict[str, int]]:
        """Raw ``{window: {total, slow, errors}}`` sums — the compact
        delta a gateway replica publishes into the shared store for
        fleet-truth burn (counts sum across replicas; rates do not)."""
        ts = int(now if now is not None else time.time())
        with self._lock:
            sec = self._sec.copy()
            counts = self._counts.copy()
        out: Dict[str, Dict[str, int]] = {}
        for name, w in self.windows:
            mask = (sec > ts - w) & (sec <= ts)
            total, slow, errors = (int(v) for v in counts[mask].sum(axis=0))
            out[name] = {"total": total, "slow": slow, "errors": errors}
        return out

    @classmethod
    def burn_entry(cls, total: int, slow: int, errors: int,
                   p99_ms: Optional[float],
                   error_rate: Optional[float]) -> Dict[str, Any]:
        """Burn math over one window's counts — THE shared rule behind
        both the local ``burn_rates`` read and the gateway's fleet-truth
        fold of summed peer counts, so the two views cannot diverge."""
        entry: Dict[str, Any] = {"requests": total}
        burns = []
        if p99_ms is not None:
            lb = (slow / total) / cls.LATENCY_BUDGET if total else 0.0
            entry["latency_burn"] = round(lb, 4)
            burns.append(lb)
        if error_rate is not None:
            # an explicit zero budget means zero tolerance: any error
            # at all burns at the cap, not "error tracking disabled"
            if not total:
                eb = 0.0
            elif error_rate > 0:
                eb = min((errors / total) / error_rate, cls.BURN_CAP)
            else:
                eb = 0.0 if errors == 0 else cls.BURN_CAP
            entry["error_burn"] = round(eb, 4)
            burns.append(eb)
        rate = max(burns) if burns else 0.0
        entry["burn_rate"] = round(rate, 4)
        entry["budget_remaining"] = round(max(0.0, 1.0 - rate), 4)
        return entry

    def burn_rates(self, now: Optional[float] = None) -> Dict[str, Any]:
        return {
            name: self.burn_entry(
                c["total"], c["slow"], c["errors"],
                self.p99_ms, self.error_rate,
            )
            for name, c in self.window_counts(now).items()
        }

    def snapshot(self) -> Dict[str, Any]:
        return {
            "p99_ms": self.p99_ms,
            "error_rate": self.error_rate,
            "configured": self.configured,
            "windows": self.burn_rates(),
        }

    def reset_events(self) -> None:
        with self._lock:
            self._sec[:] = 0
            self._counts[:] = 0


# ---------------------------------------------------------------------------
# Fleet-truth burn (federated gateway replicas)
# ---------------------------------------------------------------------------


def fleet_burn_enabled() -> bool:
    """``SELDON_TPU_FLEET_BURN=0`` is the kill switch: no burn deltas
    publish, no peer folds land, and every consumer reads its own
    per-replica burn — PR-17-and-earlier behaviour bit-for-bit."""
    return os.environ.get("SELDON_TPU_FLEET_BURN", "1") != "0"


def _fleet_burn_stale_s() -> float:
    return _env_float("SELDON_TPU_FLEET_BURN_STALE_S") or 15.0


class FleetBurnView:
    """Process-global holder for the fleet-truth burn aggregate.

    The gateway federation tick (gateway/federation.py) folds every
    replica's published window counts into one document and parks it
    here; consumers (brownout ladder, rollout burn gates, ``/quality``,
    ``/fleet``) read through :func:`effective_burn_rate`.  The view is
    deliberately dumb — publish/read under a lock with a freshness
    bound — so a wedged federation loop degrades to the per-replica
    fallback instead of freezing a stale fleet number into the ladder
    (fail-closed toward existing behaviour)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._doc: Optional[Dict[str, Any]] = None
        self._set_at = 0.0

    def publish(self, doc: Dict[str, Any]) -> None:
        with self._lock:
            self._doc = doc
            self._set_at = time.monotonic()

    def clear(self) -> None:
        with self._lock:
            self._doc = None
            self._set_at = 0.0

    def age_s(self) -> Optional[float]:
        with self._lock:
            if self._doc is None:
                return None
            return time.monotonic() - self._set_at

    def fresh(self) -> bool:
        age = self.age_s()
        return age is not None and age <= _fleet_burn_stale_s()

    def burn_rate(self, window: str = "5m") -> Optional[float]:
        """The fleet aggregate burn for one window — None when the kill
        switch is thrown, nothing was ever folded, or the last fold is
        stale (consumers then fall back to their local ring)."""
        if not fleet_burn_enabled() or not self.fresh():
            return None
        with self._lock:
            doc = self._doc
        try:
            entry = (doc or {}).get("windows", {}).get(window)
            if entry is None:
                return None
            return float(entry["burn_rate"])
        except (KeyError, TypeError, ValueError):
            return None

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            doc = dict(self._doc) if self._doc else None
        age = self.age_s()
        return {
            "enabled": fleet_burn_enabled(),
            "fresh": self.fresh(),
            "age_s": None if age is None else round(age, 3),
            "stale_after_s": _fleet_burn_stale_s(),
            "view": doc,
        }


FLEET_BURN = FleetBurnView()


def effective_burn_rate(window: str = "5m") -> Optional[float]:
    """THE burn number decision sites act on: the fleet-truth aggregate
    when federation publishes a fresh one, the local per-replica ring
    otherwise, and the max of both when both exist (a replica burning
    alone must not be talked down by a calm fleet).  None when neither
    view has a signal — burn then simply isn't a signal, exactly the
    pre-fleet contract of brownout's ``_default_burn``."""
    local: Optional[float] = None
    if QUALITY.slo.configured:
        entry = QUALITY.slo.burn_rates().get(window)
        if entry is not None:
            local = float(entry["burn_rate"])
    fleet = FLEET_BURN.burn_rate(window)
    if fleet is None:
        return local
    if local is None:
        return fleet
    return max(local, fleet)


# ---------------------------------------------------------------------------
# MAB router read-back
# ---------------------------------------------------------------------------


def router_quality(states: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-branch reward/share/regret read out of bandit pytree state.

    Any node state shaped like the MAB router's (``success``/``tries``
    1-D arrays, models/mab.py) yields a row; reward rate uses the same
    Laplace smoothing as the router's own ``_best`` so the reported best
    branch matches what route() exploits.  Regret per branch = tries x
    (best rate − branch rate): the reward given up by the exploration
    that landed there."""
    out: Dict[str, Any] = {}
    for name, st in (states or {}).items():
        try:
            if not isinstance(st, dict) or "success" not in st \
                    or "tries" not in st:
                continue
            s = np.asarray(st["success"], dtype=np.float64)
            t = np.asarray(st["tries"], dtype=np.float64)
            if s.shape != t.shape or s.ndim != 1:
                continue
        except Exception:  # noqa: BLE001 - odd pytree leaf: not a bandit
            continue
        ratio = (s + 1.0) / (t + 1.0)
        best = float(ratio.max())
        total = float(t.sum())
        out[name] = {
            "best_branch": int(np.argmax(ratio)),
            "total_tries": total,
            "total_regret": round(float((t * (best - ratio)).sum()), 4),
            "branches": [
                {
                    "branch": i,
                    "tries": float(t[i]),
                    "success": float(s[i]),
                    "reward_rate": round(float(ratio[i]), 4),
                    "share": round(float(t[i] / total), 4) if total else 0.0,
                    "regret": round(float(t[i] * (best - ratio[i])), 4),
                }
                for i in range(len(t))
            ],
        }
    return out


# ---------------------------------------------------------------------------
# feedback accounting helpers
# ---------------------------------------------------------------------------


def _agreement(prediction, truth) -> Optional[float]:
    """Truth-vs-prediction agreement fraction.  Multi-column outputs
    compare per-row argmax (classification); everything else compares
    values within a relative tolerance.  None when the shapes cannot be
    compared."""
    if prediction is None or truth is None:
        return None
    try:
        p = np.atleast_2d(np.asarray(prediction, dtype=np.float64))
        t = np.atleast_2d(np.asarray(truth, dtype=np.float64))
        if p.ndim == 2 and t.ndim == 2 and p.shape == t.shape \
                and p.shape[-1] > 1:
            return float((p.argmax(axis=-1) == t.argmax(axis=-1)).mean())
        pf, tf = p.reshape(-1), t.reshape(-1)
        if pf.size != tf.size or pf.size == 0:
            return None
        return float((np.abs(pf - tf) <= 1e-6 + 1e-3 * np.abs(tf)).mean())
    except Exception:  # noqa: BLE001 - uncomparable payloads
        return None


class _FeedbackStats:
    __slots__ = ("count", "reward", "truth_count", "agree_rows",
                 "truth_rows")

    def __init__(self):
        self.count = 0
        self.reward = Reservoir(2048)
        self.truth_count = 0
        self.agree_rows = 0.0
        self.truth_rows = 0.0

    def snapshot(self) -> Dict[str, Any]:
        r = self.reward.snapshot()
        out = {
            "count": self.count,
            "mean_reward": round(r["mean"], 6),
            "truth_provided": self.truth_count,
        }
        if self.truth_rows > 0:
            out["accuracy"] = round(self.agree_rows / self.truth_rows, 6)
        return out


# ---------------------------------------------------------------------------
# the observatory
# ---------------------------------------------------------------------------


class QualityObservatory:
    """Process-global prediction-quality accounting.  All record methods
    are cheap and never raise — quality instrumentation must not grow
    failure modes on the dispatch hot path."""

    #: bounded node table — an exploding node-name set must not grow memory
    MAX_NODES = 64

    def __init__(
        self,
        enabled: Optional[bool] = None,
        sample: Optional[float] = None,
        n_bins: int = 10,
        ref_target: Optional[int] = None,
        live_window: int = 64,
        outlier_threshold: Optional[float] = None,
        use_numpy: bool = False,
    ):
        if enabled is None:
            enabled = os.environ.get("SELDON_TPU_QUALITY", "1") != "0"
        self.enabled = bool(enabled)
        if sample is None:
            sample = _env_float("SELDON_TPU_QUALITY_SAMPLE")
            sample = 1.0 if sample is None else sample
        self.sample = min(max(float(sample), 0.0), 1.0)
        self.n_bins = int(n_bins)
        if ref_target is None:
            rt = _env_float("SELDON_TPU_QUALITY_REF_ROWS")
            ref_target = 256 if rt is None else int(rt)
        self.ref_target = max(int(ref_target), 2)
        self.live_window = int(live_window)
        self.outlier_threshold = (
            outlier_threshold if outlier_threshold is not None
            else _env_float("SELDON_TPU_OUTLIER_THRESHOLD")
        )
        self.use_numpy = bool(use_numpy)
        interval_ms = _env_float("SELDON_TPU_QUALITY_SCORE_MS")
        self.score_interval_s = (
            0.25 if interval_ms is None else max(interval_ms, 0.0) / 1e3
        )
        jit_min = _env_float("SELDON_TPU_QUALITY_JIT_MIN_ROWS")
        self.jit_min_rows = 32 if jit_min is None else int(jit_min)
        self._lock = threading.Lock()
        self._nodes: Dict[str, _NodeQuality] = {}
        self._feedback: Dict[str, _FeedbackStats] = {}
        # summarizer shapes whose XLA executable is compiled and safe to
        # call from the dispatch path; until a shape is warm the numpy
        # twin serves it (a synchronous jit compile inside the dispatch
        # span — possibly under the engine's device lock — would stall
        # every concurrent request and misattribute the cost to the
        # device)
        self._jit_ready: set = set()
        self._jit_warming: set = set()
        self._rng = random.Random(0xC0FFEE)
        self.slo = SloTracker()
        # per-tenant SLO rings (runtime/qos.py tenancy): same objectives
        # as the global tracker, 5m-horizon rings, LRU-bounded so an
        # id-spraying client can't balloon the observatory
        self._tenant_slo: "OrderedDict[str, SloTracker]" = OrderedDict()
        self.outlier = Reservoir(2048)
        self.outlier_total = 0
        self.outlier_exceeded = 0
        self.errors = 0
        #: telemetry-spine wiring (utils/hotrecord.py), set on the global
        #: QUALITY only: query/control surfaces fold pending dispatch
        #: records before reading, so deferred (off-path) quality folds
        #: are always current by the time anyone looks
        self.drain_hook = None

    def _drain(self) -> None:
        if self.drain_hook is not None:
            self.drain_hook()

    def _bump_errors(self) -> None:
        with self._lock:
            self.errors += 1

    # -- node windows ------------------------------------------------------

    def _node(self, name: str) -> Optional[_NodeQuality]:
        ent = self._nodes.get(name)
        if ent is None:
            with self._lock:
                ent = self._nodes.get(name)
                if ent is None:
                    if len(self._nodes) >= self.MAX_NODES:
                        return None
                    ent = self._nodes[name] = _NodeQuality(
                        name, self.n_bins, self.ref_target,
                        self.live_window,
                        score_interval_s=self.score_interval_s,
                    )
        return ent

    def observe_batch(self, node: str, X, Y,
                      real_rows: Optional[int] = None) -> Optional[float]:
        """One dispatched batch's inputs + predictions.  ``real_rows``
        masks batcher pad rows out of every statistic (pad rows are
        compiler fodder, not traffic).  Returns the node's current PSI
        max for span stamping, or None when nothing was recorded.

        The per-batch decision (``SELDON_TPU_QUALITY_SAMPLE``) happens
        here, once; a sampled batch costs one fused summarize kernel."""
        if not self.enabled or self.sample <= 0.0:
            return None
        if self.sample < 1.0 and self._rng.random() >= self.sample:
            return None
        try:
            return self._observe(node, X, Y, real_rows)
        except Exception:  # noqa: BLE001 - never raise into dispatch
            self._bump_errors()
            logger.debug("quality observe failed", exc_info=True)
            return None

    def fold_batch(self, node: str, X, Y,
                   real_rows: Optional[int] = None) -> Optional[float]:
        """Pre-sampled observe — the telemetry-spine drainer's entry
        point (utils/hotrecord.py).  The unified per-batch sample verdict
        was already decided at the dispatch site and carried in the
        record, so no second coin flip happens here; everything else is
        identical to :meth:`observe_batch`.  Off the hot path by
        construction: the fused summarize runs on the drainer thread."""
        if not self.enabled:
            return None
        try:
            return self._observe(node, X, Y, real_rows)
        except Exception:  # noqa: BLE001 - never raise into the drainer
            self._bump_errors()
            logger.debug("quality fold failed", exc_info=True)
            return None

    def _observe(self, node: str, X, Y,
                 real_rows: Optional[int]) -> Optional[float]:
        ent = self._node(node)
        if ent is None:
            return None
        n = int(real_rows) if real_rows is not None else int(np.shape(X)[0])
        if n <= 0:
            return None
        RECORDER.record_quality_sampled(node)
        with ent.lock:
            ent.sampled_batches += 1
            ent.sampled_rows += n
            if not ent.frozen:
                Xn = np.asarray(X, dtype=np.float64)[:n]
                Xn = Xn.reshape(n, -1)
                Yn = np.asarray(Y, dtype=np.float64)[:n].reshape(n, -1)
                ent._collect_reference(Xn, Yn)
                return None
            # capture the window's identity + thresholds under the lock:
            # the summarize below runs lock-free and must not mix state
            # from a reference swapped mid-flight
            gen = ent.generation
            x_thr, y_thr = ent.x_thr, ent.y_thr
            F, y_width = x_thr.shape[0], ent._ref_y_width
        # frozen: batched summarize OUTSIDE the lock (pure function of the
        # batch + the captured thresholds)
        Xa = np.asarray(X)
        Xa = Xa.reshape(Xa.shape[0], -1)
        Ya = np.asarray(Y)
        Ya = Ya.reshape(Ya.shape[0], -1)
        # both widths must match the frozen reference — a swapped model
        # emitting a new output width would otherwise silently pollute
        # the prediction histogram against stale edges
        if Xa.shape[1] != F or Ya.shape[1] != y_width:
            with ent.lock:
                ent.width_mismatches += 1
            return None
        # the fused summarize now runs off-path on HOST arrays (the
        # telemetry-spine drainer hands over the batch readback): below
        # jit_min_rows the jax call overhead dwarfs the kernel, so the
        # numpy twin — identical outputs by construction — serves small
        # batches and the jitted kernel serves real stacks
        small = len(Xa) < self.jit_min_rows
        fn = (
            None if (self.use_numpy or small) else _get_jit_summarizer()
        )
        # the batch axis pads to a power of two before the jitted
        # summarize — callers with arbitrary batch sizes (unit pods,
        # host mode) must not retrace per row count; the row mask (n)
        # keeps the pad rows out of every statistic
        target = 1 << max(len(Xa) - 1, 0).bit_length()
        if fn is not None:
            key = (target, Xa.shape[1], Ya.shape[1], self.n_bins)
            if key not in self._jit_ready:
                # not compiled yet: warm in the background, numpy serves
                # this observation (identical outputs by construction)
                self._warm_summarizer(fn, key, ent)
                fn = None
        if fn is not None:
            if target > len(Xa):
                Xa = np.concatenate(
                    [Xa, np.zeros((target - len(Xa), Xa.shape[1]),
                                  dtype=Xa.dtype)], axis=0)
                Ya = np.concatenate(
                    [Ya, np.zeros((target - len(Ya), Ya.shape[1]),
                                  dtype=Ya.dtype)], axis=0)
            import jax.numpy as jnp

            parts = fn(
                jnp.asarray(Xa, jnp.float32), jnp.asarray(Ya, jnp.float32),
                jnp.asarray(x_thr), jnp.asarray(y_thr), n,
            )
            x_counts, x_sum, x_sumsq, y_counts, _, _ = (
                np.asarray(p, dtype=np.float64) for p in parts
            )
        else:
            x_counts, x_sum, x_sumsq, y_counts, _, _ = _summarize_np(
                Xa, Ya, x_thr, y_thr, n
            )
        with ent.lock:
            if not ent.frozen or ent.generation != gen:
                # the reference was reset/refrozen while this batch was
                # being summarized: counts binned against the old edges
                # must not enter the new window
                return None
            ent._push_block(x_counts, x_sum, x_sumsq, y_counts, n)
            # throttled: scoring + gauge publication happen on the first
            # live batch and then at most once per score interval — the
            # per-batch fold cost is the summarize + an O(F*B) window add
            scores = ent._maybe_score()
            pq = ent.prediction_quantiles() if scores else {}
            drift = ent.last_scores.get("psi_max")
        if scores:
            RECORDER.set_drift(node, "psi", scores["psi_max"])
            RECORDER.set_drift(node, "ks", scores["ks_max"])
            RECORDER.set_drift(node, "prediction", scores["prediction_psi"])
        for q, v in pq.items():
            RECORDER.set_prediction_quantile(node, q, v)
        return drift

    def _warm_summarizer(self, fn, key, ent: _NodeQuality) -> None:
        """Compile the summarizer for one (batch, widths, bins) shape on
        a daemon thread; the shape joins ``_jit_ready`` only once its
        executable exists.  Idempotent per shape."""
        with self._lock:
            if key in self._jit_warming or key in self._jit_ready:
                return
            self._jit_warming.add(key)
        x_thr, y_thr = ent.x_thr, ent.y_thr

        def _warm():
            try:
                import jax.numpy as jnp

                n_rows, f, c, _ = key
                parts = fn(
                    jnp.zeros((n_rows, f), jnp.float32),
                    jnp.zeros((n_rows, c), jnp.float32),
                    jnp.asarray(x_thr), jnp.asarray(y_thr), 1,
                )
                for p in parts:  # block until the executable is real
                    np.asarray(p)
                with self._lock:
                    self._jit_ready.add(key)
            except Exception:  # noqa: BLE001 - numpy keeps serving it
                self._bump_errors()
                logger.debug("summarizer warm failed", exc_info=True)
            finally:
                with self._lock:
                    self._jit_warming.discard(key)

        threading.Thread(
            target=_warm, name="quality-jit-warm", daemon=True
        ).start()

    def last_drift(self, node: str) -> Optional[float]:
        """Most recent PSI max for a node — stamped onto audit lines.
        When the named node has no window (host-mode engines record per
        MODEL node, not under the graph root this is usually called
        with), fall back to the worst live node in the process so the
        audit trail still shows drift."""
        self._drain()
        ent = self._nodes.get(node)
        v = ent.last_scores.get("psi_max") if ent is not None else None
        if v is None:
            with self._lock:
                scores = [
                    e.last_scores["psi_max"]
                    for e in self._nodes.values()
                    if "psi_max" in e.last_scores
                ]
            v = max(scores) if scores else None
        return None if v is None else round(v, 4)

    # -- reference control -------------------------------------------------

    def reference_control(self, action: str,
                          node: Optional[str] = None) -> Dict[str, Any]:
        """``freeze``: promote the live/collected window of every (or one)
        node to the new reference; ``reset``: drop reference + live and
        start collecting afresh."""
        if action not in ("freeze", "reset"):
            raise ValueError(f"unknown reference action {action!r} "
                             f"(expected freeze|reset)")
        # fold pending dispatch records first: rows already served must
        # land in the window this control call is about to freeze/reset
        self._drain()
        done: Dict[str, str] = {}
        with self._lock:
            if node:
                # a named node must resolve — falling back to "all nodes"
                # on a typo would silently reset every reference
                targets = [self._nodes[node]] if node in self._nodes else []
            else:
                targets = list(self._nodes.values())
        if node and not targets:
            return {"action": action, "nodes": {node: "unknown_node"},
                    "enabled": self.enabled}
        for ent in targets:
            with ent.lock:
                if action == "reset":
                    ent._clear()
                    done[ent.node] = "reset"
                else:
                    if ent.frozen:
                        # re-freeze onto current traffic requires fresh raw
                        # rows: restart collection (documented semantics)
                        ent._clear()
                        done[ent.node] = "recollecting"
                    else:
                        done[ent.node] = (
                            "frozen" if ent._freeze() else "no_rows"
                        )
            # the published gauges must not outlive the window they
            # scored — a stale PSI would keep SeldonTPUDriftDetected
            # firing through the entire recollection
            if done[ent.node] in ("reset", "recollecting"):
                RECORDER.clear_drift(ent.node)
        return {"action": action, "nodes": done, "enabled": self.enabled}

    # -- feedback ----------------------------------------------------------

    def record_feedback(self, predictor: str, reward: float,
                        truth=None, prediction=None) -> None:
        """Fold one send_feedback into rolling per-predictor reward and
        truth-vs-prediction accuracy (+ the seldon_tpu_feedback_*
        families)."""
        if not self.enabled:
            return
        try:
            agreement = _agreement(prediction, truth)
            with self._lock:
                ent = self._feedback.get(predictor)
                if ent is None:
                    if len(self._feedback) >= self.MAX_NODES:
                        return
                    ent = self._feedback[predictor] = _FeedbackStats()
            rows = (
                max(int(np.atleast_2d(np.asarray(truth)).shape[0]), 1)
                if agreement is not None else 0
            )
            with self._lock:
                ent.count += 1
                if truth is not None:
                    ent.truth_count += 1
                if agreement is not None:
                    ent.truth_rows += rows
                    ent.agree_rows += agreement * rows
            ent.reward.observe(float(reward))
            RECORDER.record_feedback_event(
                float(reward),
                truth_provided=truth is not None,
                agreement=agreement,
            )
        except Exception:  # noqa: BLE001
            self._bump_errors()
            logger.debug("quality feedback failed", exc_info=True)

    # -- outlier bridge ----------------------------------------------------

    def record_outlier_tags(self, tags: Optional[Dict[str, Any]],
                            real_rows: Optional[int] = None) -> None:
        """Bridge MahalanobisOutlier scores out of
        ``meta.tags['outlierScore']`` (models/outlier.py) into the
        ``seldon_tpu_outlier_score`` family + the /quality block — until
        now the scores were per-response tags only, invisible to
        Prometheus.  ``SELDON_TPU_OUTLIER_THRESHOLD`` exceedances count
        separately for alerting."""
        if not self.enabled or not tags or "outlierScore" not in tags:
            return
        try:
            scores = np.asarray(tags["outlierScore"], dtype=np.float64)
            scores = scores.reshape(-1)
            if real_rows is not None:
                scores = scores[: int(real_rows)]
            if scores.size == 0:
                return
            n = (
                int((scores > self.outlier_threshold).sum())
                if self.outlier_threshold is not None else 0
            )
            with self._lock:
                self.outlier_total += int(scores.size)
                self.outlier_exceeded += n
            self.outlier.observe_many(scores)
            RECORDER.record_outlier_scores(scores)
            if n:
                RECORDER.record_outlier_exceeded(n)
        except Exception:  # noqa: BLE001
            self._bump_errors()
            logger.debug("outlier bridge failed", exc_info=True)

    # -- SLO ---------------------------------------------------------------

    #: bound on tracked tenant SLO rings (LRU past it — matches the
    #: gateway governor's row bound)
    MAX_TENANTS = 256
    #: per-tenant ring horizon: covers the 5m fast-burn window only
    TENANT_HORIZON_S = 300

    def record_request(self, latency_s: float, error: bool = False,
                       now: Optional[float] = None) -> None:
        """One served request's latency/outcome into the SLO engine (fed
        by MetricsRegistry.time_server on the predictions services)."""
        if not self.enabled:
            return
        self.slo.record(latency_s, error=error, now=now)

    def record_tenant_request(self, tenant: str, latency_s: float,
                              error: bool = False,
                              now: Optional[float] = None) -> None:
        """Per-tenant SLO accounting (the gateway's predict path feeds
        this) — burn is per-tenant on ``GET /quality`` so one hog's
        burned budget is attributable instead of smeared across the
        global tracker."""
        if not self.enabled or not tenant:
            return
        with self._lock:
            t = self._tenant_slo.get(tenant)
            if t is None:
                while len(self._tenant_slo) >= self.MAX_TENANTS:
                    self._tenant_slo.popitem(last=False)
                t = self._tenant_slo[tenant] = SloTracker(
                    p99_ms=self.slo.p99_ms,
                    error_rate=self.slo.error_rate,
                    horizon=self.TENANT_HORIZON_S,
                )
            else:
                self._tenant_slo.move_to_end(tenant)
        t.record(latency_s, error=error, now=now)

    def tenant_slo_block(self) -> Dict[str, Any]:
        """{tenant: burn windows} — bounded by MAX_TENANTS."""
        with self._lock:
            trackers = list(self._tenant_slo.items())
        return {
            tenant: tracker.burn_rates() for tenant, tracker in trackers
        }

    def tenant_window_counts(self) -> Dict[str, Dict[str, Dict[str, int]]]:
        """{tenant: {window: counts}} raw sums — what a federated
        gateway replica publishes as its per-tenant burn delta."""
        with self._lock:
            trackers = list(self._tenant_slo.items())
        return {
            tenant: tracker.window_counts() for tenant, tracker in trackers
        }

    def refresh_gauges(self) -> None:
        """Recompute the seldon_tpu_slo_burn_rate and drift gauges —
        called from the Prometheus exposition path so a scrape-only
        deployment sees live scores.  Drift is force-rescored here (same
        rule as the /quality page): batches folded inside the last
        throttle window before a traffic pause would otherwise never
        reach the gauges, leaving SeldonTPUDriftDetected reading a
        pre-shift score while /quality shows the drifted one."""
        if not self.enabled:
            return
        try:
            for window, entry in self.slo.burn_rates().items():
                RECORDER.set_slo_burn(window, entry["burn_rate"])
            with self._lock:
                nodes = list(self._nodes.values())
            for ent in nodes:
                with ent.lock:
                    if not ent.frozen or ent.live_rows <= 0:
                        continue
                    ent._scored_at = time.monotonic()
                    scores = ent._score()
                    pq = ent.prediction_quantiles()
                if scores:
                    RECORDER.set_drift(ent.node, "psi", scores["psi_max"])
                    RECORDER.set_drift(ent.node, "ks", scores["ks_max"])
                    RECORDER.set_drift(
                        ent.node, "prediction", scores["prediction_psi"]
                    )
                for q, v in pq.items():
                    RECORDER.set_prediction_quantile(ent.node, q, v)
        except Exception:  # noqa: BLE001 - scrape must never fail here
            self._bump_errors()

    # -- snapshots ---------------------------------------------------------

    def outlier_block(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "scores": self.outlier.snapshot(),
            "total": self.outlier_total,
            "threshold": self.outlier_threshold,
        }
        if self.outlier_threshold is not None:
            out["exceeded"] = self.outlier_exceeded
        return out

    def document(self) -> Dict[str, Any]:
        """The ``GET /quality`` body: per-node drift table, feedback
        reward/accuracy trends, outlier bridge, SLO burn rates."""
        self._drain()
        self.refresh_gauges()
        with self._lock:
            nodes = list(self._nodes.values())
            fb = {k: v.snapshot() for k, v in self._feedback.items()}
        rows = []
        for ent in nodes:
            with ent.lock:
                rows.append(ent.document_row())
        return {
            "enabled": self.enabled,
            "sample": self.sample,
            "n_bins": self.n_bins,
            "ref_target": self.ref_target,
            "nodes": sorted(rows, key=lambda r: r["node"]),
            "feedback": fb,
            "outliers": self.outlier_block(),
            "slo": self.slo.snapshot(),
            # per-tenant burn (5m ring per tenant, LRU-bounded): which
            # tenant is burning the budget, not just that it burns
            "tenant_slo": self.tenant_slo_block(),
            # the federated fleet-truth aggregate the brownout ladder
            # and rollout gates actually judge (gateway/federation.py
            # folds peer deltas here; stale/off -> per-replica fallback)
            "fleet_burn": FLEET_BURN.snapshot(),
        }

    def snapshot(self) -> Dict[str, Any]:
        """Compact health block for ``/stats`` — the full table lives on
        ``/quality``."""
        self._drain()
        with self._lock:
            nodes = {
                name: {
                    "status": (
                        "live" if ent.frozen else "collecting_reference"
                    ),
                    "sampled_rows": ent.sampled_rows,
                    **{k: round(v, 6)
                       for k, v in ent.last_scores.items()},
                }
                for name, ent in self._nodes.items()
            }
            fb_count = sum(v.count for v in self._feedback.values())
        return {
            "enabled": self.enabled,
            "sample": self.sample,
            "nodes": nodes,
            "feedback_count": fb_count,
            "outliers_scored": self.outlier_total,
            "slo_configured": self.slo.configured,
            "tenants_tracked": len(self._tenant_slo),
            "errors": self.errors,
        }

    def reset(self) -> None:
        """Fresh state — tests only (config survives)."""
        self._drain()  # pending records fold into the pre-reset state
        with self._lock:
            self._nodes = {}
            self._feedback = {}
            self._rng = random.Random(0xC0FFEE)
            self.outlier = Reservoir(2048)
            self.outlier_total = 0
            self.outlier_exceeded = 0
            self.errors = 0
            self._tenant_slo = OrderedDict()
        self.slo.reset_events()


def parse_reference_action(body, action: Optional[str] = None,
                           node: Optional[str] = None):
    """POST /quality/reference payload → ``(action, node)``.  Query
    ``?action=`` / ``?node=`` win; else a JSON body ``{"action":
    "freeze"|"reset", "node": "<name>"}``; action defaults to freeze,
    node to all nodes.  Raises ValueError on anything else (the lanes
    answer 400)."""
    candidate = action or None
    if (candidate is None or node is None) and body:
        text = body.decode("utf-8", "replace") \
            if isinstance(body, bytes) else str(body)
        text = text.strip()
        if text:
            try:
                doc = json.loads(text)
            except ValueError:
                raise ValueError("reference body must be JSON")
            if isinstance(doc, dict):
                if candidate is None and "action" in doc:
                    candidate = str(doc["action"])
                if node is None and "node" in doc:
                    node = str(doc["node"])
            elif isinstance(doc, str) and candidate is None:
                candidate = doc
    candidate = candidate or "freeze"
    if candidate not in ("freeze", "reset"):
        raise ValueError(
            f"unknown reference action {candidate!r} (expected freeze|reset)"
        )
    return candidate, node


QUALITY = QualityObservatory()
