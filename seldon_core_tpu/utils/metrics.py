"""Prometheus metrics with the reference's metric families so existing
Grafana dashboards keep working (engine application.properties:24-27,
SeldonRestTemplateExchangeTagsProvider.java:84-161, monitoring/grafana/
configs/predictions-analytics-dashboard.json):

  * seldon_api_engine_server_requests_duration_seconds   (histogram)
  * seldon_api_engine_client_requests_duration_seconds   (per-node histogram)
  * seldon_api_ingress_server_requests_duration_seconds  (gateway histogram)
  * seldon_api_model_feedback_total / seldon_api_model_feedback_reward_total

All tagged with deployment_name / predictor_name / model_name / model_image /
model_version / project_name where applicable.

Beyond the reference families, ``exposition()`` merges in the process-level
``seldon_tpu_*`` TPU-serving families owned by the flight recorder
(utils/telemetry.py) — batch occupancy, queue wait, inflight dispatches,
TTFT, decode rate, speculative acceptance, compile-cache and KV-cache
state — so every existing ``/prometheus`` scrape target picks them up with
zero config.  ``family_names()`` enumerates everything exported; the
dashboard-honesty test (tests/test_monitoring_configs.py) checks
monitoring/ configs against it."""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import FrozenSet, Optional

from seldon_core_tpu.utils.quality import QUALITY
from seldon_core_tpu.utils.telemetry import RECORDER, TPU_METRIC_FAMILIES

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Histogram,
        generate_latest,
        CONTENT_TYPE_LATEST,
    )

    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover
    HAVE_PROMETHEUS = False
    CONTENT_TYPE_LATEST = "text/plain"

#: OpenMetrics exposition content type — the format that carries the
#: trace_id exemplars on seldon_tpu_dispatch_seconds buckets (served by
#: /prometheus under Accept negotiation or ?format=openmetrics)
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

__all__ = [
    "MetricsRegistry",
    "CONTENT_TYPE_LATEST",
    "OPENMETRICS_CONTENT_TYPE",
]

_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0,
)

#: reference-parity families owned by MetricsRegistry itself
_OWN_FAMILIES = (
    "seldon_api_engine_server_requests_duration_seconds",
    "seldon_api_engine_client_requests_duration_seconds",
    "seldon_api_ingress_server_requests_duration_seconds",
    "seldon_api_model_feedback_total",
    "seldon_api_model_feedback_reward_total",
)


class MetricsRegistry:
    """Per-process metric registry; a null object when prometheus_client is
    unavailable so serving never depends on it."""

    def __init__(self, deployment_name: str = "", predictor_name: str = "",
                 project_name: str = ""):
        self.deployment_name = deployment_name
        self.predictor_name = predictor_name
        self.project_name = project_name
        self._server_children: dict = {}
        if not HAVE_PROMETHEUS:
            self.registry = None
            return
        self.registry = CollectorRegistry()
        common = ["deployment_name", "predictor_name", "project_name"]
        self.server_requests = Histogram(
            "seldon_api_engine_server_requests_duration_seconds",
            "Engine request latency",
            common + ["service", "method", "code"],
            registry=self.registry,
            buckets=_BUCKETS,
        )
        self.client_requests = Histogram(
            "seldon_api_engine_client_requests_duration_seconds",
            "Per-node dispatch latency",
            common + ["model_name", "model_image", "model_version", "method"],
            registry=self.registry,
            buckets=_BUCKETS,
        )
        self.ingress_requests = Histogram(
            "seldon_api_ingress_server_requests_duration_seconds",
            "Gateway request latency",
            common + ["service", "method", "code"],
            registry=self.registry,
            buckets=_BUCKETS,
        )
        self.feedback_total = Counter(
            "seldon_api_model_feedback_total",
            "Feedback events",
            common,
            registry=self.registry,
        )
        self.feedback_reward_total = Counter(
            "seldon_api_model_feedback_reward_total",
            "Accumulated feedback reward",
            common,
            registry=self.registry,
        )

    def _common(self):
        return {
            "deployment_name": self.deployment_name,
            "predictor_name": self.predictor_name,
            "project_name": self.project_name,
        }

    def _server_child(self, service: str, method: str, code: str):
        """Memoized labeled child — ``labels(**kwargs)`` costs ~10us per call,
        which matters at 10k+ req/s; the label set per engine is tiny."""
        key = (service, method, code)
        child = self._server_children.get(key)
        if child is None:
            child = self.server_requests.labels(
                **self._common(), service=service, method=method, code=code
            )
            self._server_children[key] = child
        return child

    @contextmanager
    def time_server(self, service: str, method: str):
        start = time.perf_counter()
        code_holder = {"code": "200"}
        try:
            yield code_holder
        except Exception:
            code_holder["code"] = "500"
            raise
        finally:
            dt = time.perf_counter() - start
            # /stats percentile reservoirs run even without prometheus_client
            RECORDER.request_latency(f"server:{service}", dt)
            if service == "predictions":
                # SLO engine (utils/quality.py): burn rates ride the same
                # request stream this histogram observes; 5xx burns the
                # error budget, anything over SELDON_TPU_SLO_P99_MS burns
                # the latency budget.  Policy refusals (code["shed"]:
                # autopilot/brownout LoadShedError 503s) are flow
                # control, not failures — counting them as SLO errors
                # would latch the brownout ladder (shed -> error burn ->
                # stay shed forever) and fail rollout burn gates on
                # deliberate backpressure; they have their own counter
                # families (seldon_tpu_{autopilot,brownout}_shed_total)
                QUALITY.record_request(
                    dt, error=(code_holder["code"].startswith("5")
                               and not code_holder.get("shed"))
                )
            if self.registry is not None:
                self._server_child(service, method, code_holder["code"]).observe(dt)

    @contextmanager
    def time_client(self, model_name: str, method: str, model_image: str = "",
                    model_version: str = ""):
        start = time.perf_counter()
        try:
            yield
        finally:
            if self.registry is not None:
                self.client_requests.labels(
                    **self._common(), model_name=model_name,
                    model_image=model_image, model_version=model_version,
                    method=method,
                ).observe(time.perf_counter() - start)

    @contextmanager
    def time_ingress(self, service: str, method: str):
        start = time.perf_counter()
        code_holder = {"code": "200"}
        try:
            yield code_holder
        except Exception:
            code_holder["code"] = "500"
            raise
        finally:
            dt = time.perf_counter() - start
            RECORDER.request_latency(f"ingress:{service}", dt)
            if self.registry is not None:
                self.ingress_requests.labels(
                    **self._common(), service=service, method=method,
                    code=code_holder["code"],
                ).observe(dt)

    def record_feedback(self, reward: float) -> None:
        if self.registry is not None:
            self.feedback_total.labels(**self._common()).inc()
            self.feedback_reward_total.labels(**self._common()).inc(max(reward, 0.0))

    @classmethod
    def family_names(cls) -> FrozenSet[str]:
        """Every Prometheus family base name this process exports through
        ``exposition()`` — reference-parity families plus the flight
        recorder's ``seldon_tpu_*`` set."""
        return frozenset(_OWN_FAMILIES) | frozenset(TPU_METRIC_FAMILIES)

    def exposition(self, openmetrics: bool = False) -> bytes:
        """Own (deployment-labelled) families + the process-level
        ``seldon_tpu_*`` families — one scrape target per serving process
        carries both layers.  ``openmetrics=True`` renders the OpenMetrics
        format (exemplar-carrying); the two registries' outputs merge with
        a single trailing ``# EOF`` terminator."""
        if self.registry is None:
            return RECORDER.exposition(openmetrics=openmetrics)
        if openmetrics:
            from prometheus_client.openmetrics.exposition import (
                generate_latest as om_generate_latest,
            )

            own = om_generate_latest(self.registry)
            eof = b"# EOF\n"
            if own.endswith(eof):
                own = own[: -len(eof)]
            return own + RECORDER.exposition(openmetrics=True)
        return generate_latest(self.registry) + RECORDER.exposition()
