"""Completion fence for timed relay dispatches.

On the axon relay ``jax.block_until_ready`` can return WITHOUT waiting
(observed after compile-helper restarts): a timing loop built on it then
measures ~0.05 ms for a 100+ ms dispatch.  A host fetch of any output is
a true fence — the program completes as a unit before results transfer —
so every wall-clock measurement in bench.py and scripts/ fences through
``fetch_sync``, which fetches the SMALLEST output leaf to keep the fence
itself cheap.
"""

from __future__ import annotations

__all__ = ["fetch_sync"]


def fetch_sync(out):
    import numpy as np

    import jax

    leaves = jax.tree_util.tree_leaves(out)
    leaf = min(leaves, key=lambda a: getattr(a, "size", 1 << 62))
    np.asarray(leaf)
    return out
