"""Fused hot-path telemetry: one record per hop, off-path observatory
consumers, and an enforced overhead budget.

PRs 1-5 each bolted per-request work onto the dispatch path — a span
append under the tracer lock, a prometheus label lookup per span kind, a
per-executable MFU derivation, a drift summarize + PSI scoring per
sampled batch — and ``span_framework_p50_ms`` crept 1.91 -> 2.21 ms as
the stack learned to see itself.  This module inverts the flow:

  * On the hot path, each hop (gateway ingress, engine request,
    micro-batch queue wait, device dispatch, decode) appends exactly ONE
    fixed-layout :class:`HotRecord` to a lock-free per-thread SPSC ring
    (:class:`ThreadRing`: the owning thread is the only producer, the
    drainer the only consumer; a full ring drops the record and counts
    it — ``seldon_tpu_telemetry_ring_dropped_total`` — instead of ever
    blocking a request).
  * All on-device statistics collapse into the batch readback the
    response needs anyway: the record carries *references* to the
    already-stacked batch and its readback, and the quality
    observatory's ONE fused summarize per sampled batch now runs in the
    drainer, not inside the dispatch span.  OBSERVATORY and QUALITY no
    longer each touch the arrays on-path.
  * TRACER / OBSERVATORY / QUALITY / RECORDER become **off-path
    consumers**: :meth:`TelemetrySpine.drain` folds ring records into
    their existing snapshots and metric families, so ``GET /stats``,
    ``/perf``, ``/quality``, ``/trace`` and every ``seldon_tpu_*``
    Prometheus family are bit-for-bit-compatible surfaces fed from the
    fused record.  Draining happens from a daemon thread on an interval
    AND lazily from every query surface (tracer lookups, recorder
    snapshots, observatory documents), so reads are always current.
  * The **sampling decision is unified**: one uniform draw per
    request/per batch; subsystem S is sampled iff ``u < rate_S``
    (``SELDON_TPU_TRACE_SAMPLE`` / ``SELDON_TPU_QUALITY_SAMPLE`` stay
    the rate inputs).  Because the draws are nested, a record sampled
    for the rarest subsystem is sampled for every cheaper one — sampled
    records are complete across subsystems instead of three independent
    coin flips agreeing only by luck.
  * The overhead budget is a first-class, self-observed SLO:
    ``GET /overhead`` decomposes framework time per subsystem
    (tracer/perf/quality/recorder/ring) from the records themselves,
    ``seldon_tpu_framework_overhead_ms{subsystem}`` feeds the
    ``SeldonTPUTelemetryOverhead`` alert, and ``bench.py
    --overhead-gate`` (``make overhead-gate``) fails when
    ``span_framework_p50_ms`` with every observatory enabled exceeds
    ``SELDON_TPU_OVERHEAD_BUDGET_MS`` (default 1.0).

Kill switches compose independently: ``SELDON_TPU_TELEMETRY=0`` silences
the flight-recorder folds (queue wait / occupancy), ``SELDON_TPU_TRACE``
/ ``SELDON_TPU_PERF`` / ``SELDON_TPU_QUALITY`` keep their PR-3/4/5
semantics.  A hop record is only written when at least one enabled
consumer wants it; with all four off the dispatch path performs ZERO
ring writes and zero observatory calls (tests/test_telemetry_spine.py).

``SELDON_TPU_TELEMETRY_TEST_DELAY_MS`` injects an artificial sleep into
every ring write — the documented way to prove the overhead gate
actually gates (docs/operations.md).
"""

from __future__ import annotations

import atexit
import os
import random
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from seldon_core_tpu.utils.perf import OBSERVATORY
from seldon_core_tpu.utils.quality import QUALITY
from seldon_core_tpu.utils.telemetry import RECORDER, Reservoir
from seldon_core_tpu.utils.tracing import (
    TRACER,
    Span,
    current_trace_context,
    new_span_id,
    new_trace_id,
)

__all__ = ["HotRecord", "ThreadRing", "TelemetrySpine", "SPINE", "Wants"]

# consumer-interest bits carried in HotRecord.flags — captured at record
# time so a consumer toggled between write and fold keeps the write-time
# decision (the same rule head sampling follows)
WANT_RECORDER = 1
WANT_TRACE = 2
WANT_PERF = 4
WANT_QUALITY = 8
WANT_COST = 16    # record carries a cost-ledger attribution payload
WANT_PM = 32      # head-sampled OUT, but under postmortem tail capture:
                  # the reconstructed span is pm_only — pending buffer
                  # only, never the tracer ring (utils/postmortem.py)

#: hop kinds (HotRecord.hop)
HOP_SPAN = "span"          # a finished tracer span (request/client/...)
HOP_QUEUE = "queue"        # per-caller micro-batch queue wait
HOP_FLUSH = "flush"        # one stacked flush (occupancy + flush span)
HOP_DISPATCH = "dispatch"  # one device dispatch (perf + quality + span)
HOP_QUALITY = "quality"    # per-node quality observation (host/unit lanes)
HOP_GEN_STEP = "gen_step"  # one continuous-batching scheduler step


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


_tier_fn = None


def _dispatch_tier() -> str:
    """QoS tier bound to the calling context — lazily bound to
    runtime.qos.current_tier (utils must stay importable without the
    runtime package); '' when unavailable."""
    global _tier_fn
    fn = _tier_fn
    if fn is None:
        try:
            from seldon_core_tpu.runtime.qos import current_tier as fn
        except Exception:  # noqa: BLE001 - tier is best-effort metadata
            def fn() -> str:
                return ""
        _tier_fn = fn
    try:
        return fn() or ""
    except Exception:  # noqa: BLE001
        return ""


class HotRecord:
    """The fixed-layout per-hop record.  Every hop uses a subset of the
    slots; unused slots stay None.  Deliberately a dumb container — all
    interpretation happens in the drainer."""

    __slots__ = (
        "hop",            # HOP_* kind
        "seq",            # perf_counter at append: cross-ring fold order
        "flags",          # WANT_* consumer-interest bits
        "puid", "trace_id", "span_id", "parent_span_id",
        "start_s",        # epoch seconds at hop start
        "duration_s",
        "name", "kind", "method",
        "executable",     # compiled-executable key (dispatch hops)
        "rows", "real_rows",
        "tier",           # QoS tier bound to the dispatch (perf corpus)
        "deadline_remaining_s",
        "compile_cache",  # "hit" | "miss" | None
        "queue_wait_s",
        "requests",       # callers coalesced into a flush
        "predicted_s",    # autopilot-predicted wall of a planned flush
        "quality_node", "batch_x", "batch_y",
        "phases",         # fused-graph per-node phase decomposition
                          # ({node: share}, graph/fuse.py) — one record
                          # still explains a whole-graph dispatch
        "error",          # exception type name of a FAILED dispatch
        "span",           # prebuilt Span (HOP_SPAN only)
        "gen",            # (admitted, retired, blocks_used, blocks_total,
                          # tokens) of one scheduler step (HOP_GEN_STEP)
        "gen_detail",     # flight-recorder per-tick decomposition dict
                          # (host/device/phase splits, bubble ledger,
                          # real rows, KV accounting — utils/genperf.py)
        "cost",           # cost-ledger attribution payload of a flush
                          # (per-tenant real rows + padded capacity —
                          # utils/costledger.py); gen ticks ride
                          # gen_detail["attr"] instead
    )

    def __init__(self, hop: str, flags: int):
        self.hop = hop
        self.flags = flags
        self.seq = 0.0
        self.puid = ""
        self.trace_id = ""
        self.span_id = ""
        self.parent_span_id = ""
        self.start_s = 0.0
        self.duration_s = 0.0
        self.name = ""
        self.kind = ""
        self.method = ""
        self.executable = ""
        self.rows = 0
        self.real_rows = 0
        self.tier = ""
        self.deadline_remaining_s = None
        self.compile_cache = None
        self.queue_wait_s = 0.0
        self.requests = 0
        self.predicted_s = None
        self.quality_node = ""
        self.batch_x = None
        self.batch_y = None
        self.phases = None
        self.error = None
        self.span = None
        self.gen = None
        self.gen_detail = None
        self.cost = None


class ThreadRing:
    """Single-producer single-consumer ring: the owning thread appends,
    the drainer pops.  Plain int head/tail cursors — the GIL makes each
    store atomic and the slot write happens BEFORE the head publish, so
    no lock is ever taken on the hot path.  A full ring drops (counted);
    it never blocks and never grows."""

    __slots__ = ("buf", "cap", "head", "tail", "dropped", "writes",
                 "owner")

    def __init__(self, capacity: int):
        self.cap = int(capacity)
        self.buf: List[Optional[HotRecord]] = [None] * self.cap
        self.head = 0   # producer cursor (owner thread only)
        self.tail = 0   # consumer cursor (drainer only)
        self.dropped = 0
        self.writes = 0
        #: weakref to the owning thread — drain() retires a fully-drained
        #: ring whose thread died, so thread churn can't grow the ring
        #: list (and leak a buffer per dead thread) forever
        self.owner = weakref.ref(threading.current_thread())

    def push(self, rec: HotRecord) -> bool:
        head = self.head
        if head - self.tail >= self.cap:
            self.dropped += 1
            return False
        self.buf[head % self.cap] = rec
        self.head = head + 1  # publish after the slot write
        self.writes += 1
        return True

    def pop_into(self, out: List[HotRecord]) -> None:
        tail, head = self.tail, self.head
        while tail < head:
            i = tail % self.cap
            rec = self.buf[i]
            self.buf[i] = None  # release array refs promptly
            if rec is not None:
                out.append(rec)
            tail += 1
        self.tail = tail


class Wants:
    """One unified sample verdict: a single uniform draw decides every
    subsystem's interest in this hop (nested sampling — see module
    docstring)."""

    __slots__ = ("trace", "quality", "perf", "recorder", "pm", "flags")

    def __init__(self, trace: bool, quality: bool, perf: bool,
                 recorder: bool, pm: bool = False):
        self.trace = trace
        self.quality = quality
        self.perf = perf
        self.recorder = recorder
        self.pm = pm
        self.flags = (
            (WANT_TRACE if trace else 0)
            | (WANT_QUALITY if quality else 0)
            | (WANT_PERF if perf else 0)
            | (WANT_RECORDER if recorder else 0)
            | (WANT_PM if pm else 0)
        )

    @property
    def any(self) -> bool:
        return self.flags != 0


class TelemetrySpine:
    """Process-global ring owner + drainer.  All record_* methods are
    hot-path-safe: no locks, no allocation beyond the record itself, and
    they never raise."""

    def __init__(
        self,
        ring_capacity: Optional[int] = None,
        drain_interval_s: Optional[float] = None,
        telemetry_enabled: Optional[bool] = None,
    ):
        if telemetry_enabled is None:
            telemetry_enabled = (
                os.environ.get("SELDON_TPU_TELEMETRY", "1") != "0"
            )
        self.telemetry_enabled = bool(telemetry_enabled)
        self.ring_capacity = int(
            ring_capacity
            if ring_capacity is not None
            else _env_float("SELDON_TPU_TELEMETRY_RING", 4096)
        )
        self.drain_interval_s = float(
            drain_interval_s
            if drain_interval_s is not None
            else _env_float("SELDON_TPU_TELEMETRY_DRAIN_MS", 50.0) / 1e3
        )
        self.budget_ms = _env_float("SELDON_TPU_OVERHEAD_BUDGET_MS", 1.0)
        #: gate-validation hook: sleep this long inside every ring write
        #: so `make overhead-gate` can be proven to fail on breach
        self.test_delay_s = (
            _env_float("SELDON_TPU_TELEMETRY_TEST_DELAY_MS", 0.0) / 1e3
        )
        self._local = threading.local()
        self._stopped = False
        self._rings: List[ThreadRing] = []
        self._rings_lock = threading.Lock()
        self._drain_lock = threading.RLock()
        self._drainer: Optional[threading.Thread] = None
        self._rng = random.Random()
        #: bumped once per drain that folded >= 1 record — the staleness
        #: key behind Engine.stats() caching
        self.fold_generation = 0
        self._last_drain_s = 0.0
        self._last_gauge_refresh = 0.0
        self._gauges_dirty = False
        self._dropped_folded = 0
        #: accounting carried over from retired dead-thread rings
        self._retired_dropped = 0
        self._retired_writes = 0
        self.records_total: Dict[str, int] = {}
        #: off-path fold cost per consumer, seconds per record
        self.fold_cost = {
            "tracer": Reservoir(1024),
            "perf": Reservoir(1024),
            "quality": Reservoir(1024),
            "recorder": Reservoir(1024),
            "ledger": Reservoir(1024),
        }
        #: on-path ring-write cost, sampled every 32nd write
        self.ring_write_s = Reservoir(1024)
        self._write_probe = 0
        #: folded hop durations — the /overhead page derives the
        #: framework-time estimate (request p50 - dispatch p50) from them
        self.hop_ms = {"request": Reservoir(2048), "dispatch": Reservoir(2048)}

    # -- ring plumbing -----------------------------------------------------

    def _ring(self) -> ThreadRing:
        ring = getattr(self._local, "ring", None)
        if ring is None:
            ring = ThreadRing(self.ring_capacity)
            self._local.ring = ring
            with self._rings_lock:
                self._rings.append(ring)
            self._ensure_drainer()
        return ring

    def _append(self, rec: HotRecord) -> bool:
        if self.test_delay_s > 0.0:
            time.sleep(self.test_delay_s)  # gate-validation hook only
        rec.seq = time.perf_counter()
        ring = self._ring()
        self._write_probe += 1
        if self._write_probe & 31 == 0:
            t0 = time.perf_counter()
            ok = ring.push(rec)
            self.ring_write_s.observe(time.perf_counter() - t0)
            return ok
        return ring.push(rec)

    def _ensure_drainer(self) -> None:
        if self._drainer is not None and self._drainer.is_alive():
            return
        t = threading.Thread(
            target=self._drain_loop, name="telemetry-spine-drain",
            daemon=True,
        )
        self._drainer = t
        t.start()

    def _drain_loop(self) -> None:  # pragma: no cover - timing-dependent
        while not self._stopped:
            time.sleep(self.drain_interval_s)
            # re-check AFTER the sleep: quiesce() flips the flag while
            # this thread is asleep, and a fold entered past that point
            # races interpreter finalization (its C-extension frames
            # keep running while C++ statics destruct -> std::terminate)
            if self._stopped:
                break
            try:
                self.drain()
            except Exception:  # noqa: BLE001 - the drainer must survive
                pass

    def quiesce(self) -> None:
        """Interpreter-exit hook: stop the drainer and wait for any
        in-flight fold.  Daemon threads are not interrupted inside
        C-extension calls at finalization — one still folding when the
        runtime's C++ statics destruct aborts the process instead of
        exiting it.  The fold lock is taken and deliberately KEPT: a
        drainer that passed the _stopped check before it flipped parks
        on the lock (safe to finalize over) instead of entering a fold."""
        self._stopped = True
        self._drain_lock.acquire(timeout=2.0)

    # -- unified sampling --------------------------------------------------

    def dispatch_wants(self) -> Wants:
        """The per-batch sample verdict, decided ONCE with a single
        uniform draw shared by every subsystem.  An active trace context
        (native-plane worker inside its plane span) overrides the trace
        bit with the context's head decision, exactly like a child span
        would."""
        u = self._rng.random()
        ctx = current_trace_context()
        pm = False
        if ctx is not None:
            trace = TRACER.enabled and ctx.sampled
            # a sampled-out context under postmortem tail capture still
            # wants the dispatch span — pm_only, pending buffer only
            pm = (TRACER.enabled and not ctx.sampled and ctx.pm
                  and TRACER.pm_hook is not None)
        else:
            trace = TRACER.enabled and (
                TRACER.sample >= 1.0 or u < TRACER.sample
            )
        quality = QUALITY.enabled and QUALITY.sample > 0.0 and (
            QUALITY.sample >= 1.0 or u < QUALITY.sample
        )
        return Wants(trace, quality, OBSERVATORY.enabled, False, pm=pm)

    # -- hot-path record sites ---------------------------------------------

    def offer_span(self, span: Span) -> None:
        """Tracer sink: a finished span becomes one ring record instead
        of an inline fold under the tracer lock + a prometheus counter
        bump.  Called only for spans the tracer already decided to
        record (enabled + sampled)."""
        rec = HotRecord(HOP_SPAN, WANT_TRACE)
        rec.span = span
        self._append(rec)

    def record_queue(self, wait_s: float, ctx, rows: int,
                     start_s: float) -> bool:
        """One record per caller per stacked flush: the queue-wait
        reservoir AND the per-caller queue span, fused."""
        want_trace = (
            TRACER.enabled and ctx is not None and ctx.sampled
        )
        want_pm = (
            TRACER.enabled and ctx is not None and not ctx.sampled
            and getattr(ctx, "pm", False) and TRACER.pm_hook is not None
        )
        flags = (WANT_RECORDER if self.telemetry_enabled else 0) | (
            WANT_TRACE if want_trace else 0
        ) | (WANT_PM if want_pm else 0)
        if not flags:
            return False
        rec = HotRecord(HOP_QUEUE, flags)
        rec.queue_wait_s = float(wait_s)
        rec.start_s = start_s
        rec.duration_s = float(wait_s)
        rec.rows = int(rows)
        if want_trace or want_pm:
            rec.puid = ctx.puid
            rec.trace_id = ctx.trace_id
            rec.parent_span_id = ctx.span_id
            rec.span_id = new_span_id()
        return self._append(rec)

    def record_flush(self, rows: int, requests: int, start_s: float,
                     duration_s: float,
                     predicted_s: Optional[float] = None,
                     cost: Optional[Dict[str, Any]] = None) -> bool:
        """One record per stacked flush: batch occupancy + the
        standalone flush span (multi-request, so it has no parent).
        ``predicted_s`` carries the autopilot's planned-flush prediction
        so the decision rides the existing write — never a new one.
        ``cost`` is the batcher's attribution payload (per-tenant real
        rows + padded capacity, utils/costledger.py); it keeps the
        record ring-worthy even with telemetry/tracing off, so the
        ledger's own kill switch is the only gate on attribution."""
        want_trace = TRACER.enabled and (
            TRACER.sample >= 1.0 or self._rng.random() < TRACER.sample
        )
        flags = (WANT_RECORDER if self.telemetry_enabled else 0) | (
            WANT_TRACE if want_trace else 0
        ) | (WANT_COST if cost is not None else 0)
        if not flags:
            return False
        rec = HotRecord(HOP_FLUSH, flags)
        rec.rows = int(rows)
        rec.requests = int(requests)
        rec.start_s = start_s
        rec.duration_s = float(duration_s)
        rec.predicted_s = predicted_s
        rec.cost = cost
        return self._append(rec)

    def record_dispatch(
        self,
        wants: Wants,
        *,
        executable: str,
        seconds: float,
        start_s: float,
        rows: int,
        real_rows: int,
        method: str = "predict",
        quality_node: str = "",
        X=None,
        Y=None,
        deadline_remaining_s: Optional[float] = None,
        compile_cache: Optional[str] = None,
        error: Optional[str] = None,
        phases: Optional[Dict[str, float]] = None,
    ) -> bool:
        """THE fused dispatch-hop write: span identity + phase timing +
        executable key + batch references in one append.  The drainer
        derives MFU/roofline (perf), folds the batch into the drift
        windows (quality: the one fused summarize, now off-path), and
        reconstructs the dispatch span carrying both — the same
        trees/tables/families the inline calls used to feed."""
        if not wants.any:
            return False
        rec = HotRecord(HOP_DISPATCH, wants.flags)
        rec.executable = executable
        rec.duration_s = float(seconds)
        rec.start_s = start_s
        rec.rows = int(rows)
        rec.real_rows = int(real_rows)
        rec.method = method
        if wants.perf:
            # the QoS tier is a contextvar on the CALLING thread — the
            # drainer can't read it later, so it rides the record (one
            # contextvar get; the corpus rows bucket by tier)
            rec.tier = _dispatch_tier()
        rec.deadline_remaining_s = deadline_remaining_s
        rec.compile_cache = compile_cache
        rec.error = error
        rec.phases = phases
        if wants.trace or wants.pm:
            ctx = current_trace_context()
            if ctx is not None:
                rec.trace_id = ctx.trace_id
                rec.parent_span_id = ctx.span_id
                rec.puid = ctx.puid
            else:
                rec.trace_id = new_trace_id()
            rec.span_id = new_span_id()
        if wants.quality:
            rec.quality_node = quality_node
            rec.batch_x = X
            rec.batch_y = Y
        return self._append(rec)

    def record_failed_dispatch(
        self,
        *,
        executable: str,
        seconds: float,
        start_s: float,
        rows: int,
        method: str,
        error: str,
    ) -> bool:
        """A FAILED dispatch still gets its span: the trace of an
        incident request must show the device hop that died, with the
        failure named.  Trace-only — perf/quality folds are skipped,
        matching the pre-spine behaviour.  Shared by the engine's
        batched lane and the native plane's dispatch loop so failure
        record semantics cannot diverge between them."""
        return self.record_dispatch(
            Wants(True, False, False, False),
            executable=executable, seconds=seconds, start_s=start_s,
            rows=rows, real_rows=rows, method=method, error=error,
        )

    def record_gen_step(
        self,
        *,
        kind: str,
        duration_s: float,
        active: int,
        waiting: int,
        admitted: int,
        retired: int,
        blocks_used: int,
        blocks_total: int,
        tokens: int,
        executable: str = "",
        trace_id: str = "",
        detail: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """ONE record per continuous-batching scheduler step
        (runtime/genserver.py): the step picture — kind, in-flight/
        waiting sequences, admission/retirement flow, paged-KV-pool
        occupancy, tokens emitted — lands in the ring and folds into a
        ``gen_step`` tracer span off-path.  The scheduler sets its gauges
        directly (one set per step is batcher-precedent cheap); this
        record exists so traces and the hop accounting see the scheduler
        the way they see every other hop.

        ``detail`` is the flight recorder's per-tick decomposition
        (host/device phase splits, bubble ledger entry, real-vs-padded
        rows, KV-block accounting) — folded into ``GENPERF``
        (utils/genperf.py) and the ``seldon_tpu_gen_step_seconds`` /
        ``gen_bubble`` / ``kv_block_age`` families off-path.  The same
        kill-switch contract applies: with flags == 0 the record never
        touches the ring and GENPERF sees nothing."""
        want_trace = TRACER.enabled and (
            TRACER.sample >= 1.0 or self._rng.random() < TRACER.sample
        )
        flags = (WANT_RECORDER if self.telemetry_enabled else 0) | (
            WANT_TRACE if want_trace else 0
        ) | (WANT_COST if detail is not None and "attr" in detail else 0)
        if not flags:
            return False
        rec = HotRecord(HOP_GEN_STEP, flags)
        rec.kind = kind
        rec.rows = int(active)
        rec.requests = int(waiting)
        rec.start_s = time.time() - duration_s
        rec.duration_s = float(duration_s)
        rec.executable = executable
        rec.trace_id = trace_id
        rec.gen = (int(admitted), int(retired), int(blocks_used),
                   int(blocks_total), int(tokens))
        rec.gen_detail = detail
        return self._append(rec)

    def record_quality(self, node: str, X, Y,
                       real_rows: Optional[int] = None) -> bool:
        """Host-mode / unit-pod quality hop: per-node batch references,
        folded off-path (the device->host conversion of X happens in the
        drainer, not the serving coroutine)."""
        wants = self.dispatch_wants()
        if not wants.quality:
            return False
        rec = HotRecord(HOP_QUALITY, WANT_QUALITY)
        rec.quality_node = node
        rec.batch_x = X
        rec.batch_y = Y
        rec.real_rows = -1 if real_rows is None else int(real_rows)
        return self._append(rec)

    # -- drain (the off-path consumers) ------------------------------------

    def _retire_dead(self, rings: List[ThreadRing]) -> None:
        """Drop fully-drained rings of dead threads (their accounting
        rolls into the retired totals, so drop counts stay monotone) —
        thread churn must not grow the ring list forever."""
        dead = [
            r for r in rings
            if r.head == r.tail
            and (r.owner() is None or not r.owner().is_alive())
        ]
        if not dead:
            return
        with self._rings_lock:
            for r in dead:
                if r in self._rings:
                    self._rings.remove(r)
                    self._retired_dropped += r.dropped
                    self._retired_writes += r.writes

    def drain(self) -> int:
        """Fold every pending record into TRACER / OBSERVATORY / QUALITY
        / RECORDER.  Called by the drainer thread on an interval and by
        every query surface before it reads (so reads are current even
        between ticks).  Reentrant-safe; never raises.

        Fast path: Engine.stats() and the four snapshot walks it runs
        each drain defensively, so back-to-back calls with nothing
        pending are the COMMON case — they return after a lock-free
        cursor scan instead of serializing scrapers on the drain lock."""
        with self._rings_lock:
            rings = list(self._rings)
        if all(r.head == r.tail for r in rings):
            self._retire_dead(rings)
            # totals folded just before a traffic pause must still reach
            # the gauges once the throttle window passes
            self._refresh_gauges()
            return 0
        with self._drain_lock:
            with self._rings_lock:
                rings = list(self._rings)
            records: List[HotRecord] = []
            for ring in rings:
                ring.pop_into(records)
            self._retire_dead(rings)
            with self._rings_lock:
                dropped = self._retired_dropped + sum(
                    r.dropped for r in self._rings
                )
            new_drops = dropped - self._dropped_folded
            if new_drops > 0:
                self._dropped_folded = dropped
                RECORDER.record_ring_dropped(new_drops)
            if not records:
                self._last_drain_s = time.monotonic()
                self._refresh_gauges()
                return 0
            records.sort(key=lambda r: r.seq)
            for rec in records:
                try:
                    self._fold(rec)
                except Exception:  # noqa: BLE001 - a bad record must not
                    pass           # wedge the drain behind it
                self.records_total[rec.hop] = (
                    self.records_total.get(rec.hop, 0) + 1
                )
            self.fold_generation += 1
            self._last_drain_s = time.monotonic()
            self._gauges_dirty = True
            self._refresh_gauges()
            return len(records)

    def _fold(self, rec: HotRecord) -> None:
        pc = time.perf_counter
        if rec.hop == HOP_SPAN:
            t0 = pc()
            TRACER._fold(rec.span)
            self.fold_cost["tracer"].observe(pc() - t0)
            if rec.span.kind == "request" and not rec.span.pm_only:
                # pm_only request spans exist only for the postmortem
                # pending buffer — the overhead estimator's sample set
                # must stay exactly what head sampling admitted
                self.hop_ms["request"].observe(rec.span.duration_ms)
            return
        if rec.hop == HOP_QUEUE:
            if rec.flags & WANT_RECORDER:
                t0 = pc()
                RECORDER.observe_queue_wait(rec.queue_wait_s)
                self.fold_cost["recorder"].observe(pc() - t0)
            if rec.flags & (WANT_TRACE | WANT_PM):
                t0 = pc()
                TRACER._fold(Span(
                    puid=rec.puid, name="batch_queue", kind="queue",
                    method="wait", start_s=rec.start_s,
                    duration_ms=rec.duration_s * 1e3,
                    attrs={"rows": rec.rows},
                    trace_id=rec.trace_id, span_id=rec.span_id,
                    parent_span_id=rec.parent_span_id,
                    pm_only=not (rec.flags & WANT_TRACE),
                ))
                self.fold_cost["tracer"].observe(pc() - t0)
            return
        if rec.hop == HOP_FLUSH:
            if rec.flags & WANT_RECORDER:
                t0 = pc()
                RECORDER.observe_batch(rec.rows)
                if rec.predicted_s is not None:
                    # an autopilot-planned flush: the decision counter
                    # rides the fold, never the flush path itself
                    RECORDER.record_autopilot_decision("flush")
                self.fold_cost["recorder"].observe(pc() - t0)
            if rec.flags & WANT_TRACE:
                t0 = pc()
                attrs = {"rows": rec.rows, "requests": rec.requests}
                if rec.predicted_s is not None:
                    attrs["autopilot_predicted_ms"] = round(
                        rec.predicted_s * 1e3, 3
                    )
                TRACER._fold(Span(
                    puid="", name="flush", kind="batch", method="dispatch",
                    start_s=rec.start_s, duration_ms=rec.duration_s * 1e3,
                    attrs=attrs,
                    span_id=new_span_id(),
                ))
                self.fold_cost["tracer"].observe(pc() - t0)
            if rec.flags & WANT_COST and rec.cost is not None:
                # tenant/deployment attribution of the flush's fenced
                # wall — the resource ledger's batch lane, off-path
                t0 = pc()
                from seldon_core_tpu.utils.costledger import LEDGER

                LEDGER.fold_flush(rec.cost, rec.duration_s)
                self.fold_cost["ledger"].observe(pc() - t0)
            return
        if rec.hop == HOP_GEN_STEP:
            # gauges/counters were set by the scheduler itself (one call
            # per step); the fold's job is the TRACE face of the step —
            # plus the dispatch-latency histogram observation whose
            # bucket carries the step's trace_id as an OpenMetrics
            # exemplar (on a decode replica that joins the KV handoff's
            # federated trace to the slow bucket that served it)
            if rec.executable and rec.flags & WANT_RECORDER:
                t0 = pc()
                RECORDER.observe_dispatch(
                    rec.executable, rec.duration_s,
                    trace_id=rec.trace_id or None,
                )
                self.fold_cost["recorder"].observe(pc() - t0)
            detail = rec.gen_detail
            if detail is not None and rec.flags & WANT_RECORDER:
                # the flight recorder's per-tick decomposition: bubble
                # ledger, phase splits, KV-block ages — aggregated in
                # GENPERF (the /genperf surface) and mirrored into the
                # gen_step_seconds / gen_bubble / kv_block_age families,
                # all off-path on the drainer thread
                t0 = pc()
                from seldon_core_tpu.utils.genperf import GENPERF

                GENPERF.observe_tick(rec.kind, detail)
                dev_phases = detail.get("device_phases") or {}
                for phase, secs in (detail.get("phases") or {}).items():
                    dev = float(dev_phases.get(phase, 0.0))
                    host = max(float(secs) - dev, 0.0)
                    if host > 0:
                        RECORDER.record_gen_step_seconds(
                            rec.kind, phase, host)
                    if dev > 0:
                        RECORDER.record_gen_step_seconds(
                            rec.kind, f"{phase}_device", dev)
                bubble = float(detail.get("bubble_s", 0.0) or 0.0)
                cause = str(detail.get("bubble_cause", "") or "")
                if bubble > 0 and cause:
                    RECORDER.record_gen_bubble(cause, bubble)
                for _n_blocks, age_s in (detail.get("kv_ages") or ()):
                    RECORDER.record_gen_kv_block_age(float(age_s))
                self.fold_cost["recorder"].observe(pc() - t0)
            if detail is not None and rec.flags & WANT_COST:
                # per-tenant split of the tick's fenced device wall +
                # KV-block-seconds — the resource ledger's gen lane
                t0 = pc()
                from seldon_core_tpu.utils.costledger import LEDGER

                LEDGER.fold_gen_tick(detail)
                self.fold_cost["ledger"].observe(pc() - t0)
            if rec.flags & WANT_TRACE:
                t0 = pc()
                admitted, retired, used, total, tokens = rec.gen
                attrs = {
                    "active": rec.rows, "waiting": rec.requests,
                    "admitted": admitted, "retired": retired,
                    "kv_blocks_used": used, "kv_blocks_total": total,
                    "tokens": tokens,
                }
                if detail is not None:
                    # the tick's device/bubble face on the trace too, so
                    # a slow gen_step span decomposes without /genperf
                    attrs["device_ms"] = round(
                        float(detail.get("device_s", 0.0)) * 1e3, 3)
                    if detail.get("bubble_s"):
                        attrs["bubble_ms"] = round(
                            float(detail["bubble_s"]) * 1e3, 3)
                        attrs["bubble_cause"] = detail.get(
                            "bubble_cause", "")
                TRACER._fold(Span(
                    puid="", name="gen_step", kind="gen_step",
                    method=rec.kind, start_s=rec.start_s,
                    duration_ms=rec.duration_s * 1e3,
                    attrs=attrs,
                    span_id=new_span_id(),
                ))
                self.fold_cost["tracer"].observe(pc() - t0)
            return
        if rec.hop == HOP_QUALITY:
            t0 = pc()
            import numpy as np

            X = np.atleast_2d(np.asarray(rec.batch_x))
            QUALITY.fold_batch(
                rec.quality_node, X, rec.batch_y,
                real_rows=None if rec.real_rows < 0 else rec.real_rows,
            )
            self.fold_cost["quality"].observe(pc() - t0)
            return
        if rec.hop == HOP_DISPATCH:
            self.hop_ms["dispatch"].observe(rec.duration_s * 1e3)
            attrs: Dict[str, Any] = {"rows": rec.rows}
            if rec.flags & WANT_PERF:
                t0 = pc()
                derived = OBSERVATORY.observe_dispatch(
                    rec.executable, rec.duration_s, rows=rec.rows,
                    trace_id=rec.trace_id if rec.flags & WANT_TRACE
                    else None,
                )
                for k in ("flops", "mfu", "bound"):
                    if k in derived:
                        attrs[k] = derived[k]
                # the autopilot learns from the SAME fused record
                # (runtime/autopilot.py — no hot-path write of its own);
                # the prediction in force before this measurement lands
                # on the dispatch span so mispredictions read off traces
                from seldon_core_tpu.runtime.autopilot import AUTOPILOT

                pred = AUTOPILOT.observe(rec.executable, rec.duration_s)
                if pred is not None:
                    attrs["autopilot_predicted_ms"] = round(pred * 1e3, 3)
                # the durable perf corpus appends the SAME fused record
                # (utils/perfcorpus.py) — a disk write on the drainer
                # thread, never the dispatch path; disabled corpus is a
                # dict-miss-cheap no-op
                from seldon_core_tpu.utils.perfcorpus import CORPUS

                if CORPUS.enabled and not rec.error:
                    from seldon_core_tpu.runtime.autopilot import (
                        pad_bucket,
                    )

                    CORPUS.record(
                        rec.executable,
                        pad_bucket=pad_bucket(rec.rows),
                        tier=rec.tier,
                        wall_s=rec.duration_s,
                        rows=rec.real_rows or rec.rows,
                        features=OBSERVATORY.cost_features(
                            rec.executable),
                    )
                self.fold_cost["perf"].observe(pc() - t0)
            if rec.flags & WANT_QUALITY:
                t0 = pc()
                drift = QUALITY.fold_batch(
                    rec.quality_node, rec.batch_x, rec.batch_y,
                    real_rows=rec.real_rows,
                )
                if drift is not None:
                    attrs["drift"] = round(drift, 4)
                self.fold_cost["quality"].observe(pc() - t0)
            if rec.flags & (WANT_TRACE | WANT_PM):
                t0 = pc()
                if rec.error:
                    attrs["error"] = rec.error
                if rec.phases:
                    # fused whole-graph dispatch: the span carries the
                    # per-node phase decomposition (graph/fuse.py)
                    attrs["phases"] = dict(rec.phases)
                if rec.compile_cache:
                    attrs["compile_cache"] = rec.compile_cache
                if rec.deadline_remaining_s is not None:
                    attrs["deadline_remaining_ms"] = round(
                        rec.deadline_remaining_s * 1e3, 3
                    )
                TRACER._fold(Span(
                    puid=rec.puid, name="dispatch", kind="dispatch",
                    method=rec.method, start_s=rec.start_s,
                    duration_ms=rec.duration_s * 1e3, attrs=attrs,
                    trace_id=rec.trace_id, span_id=rec.span_id,
                    parent_span_id=rec.parent_span_id,
                    pm_only=not (rec.flags & WANT_TRACE),
                ))
                self.fold_cost["tracer"].observe(pc() - t0)

    def _refresh_gauges(self) -> None:
        """Publish the self-observed overhead figures (throttled to one
        refresh per second — gauge churn is itself overhead; ``dirty``
        tracking guarantees the LAST folds before a traffic pause still
        land once the window passes)."""
        now = time.monotonic()
        if not self._gauges_dirty or now - self._last_gauge_refresh < 1.0:
            return
        self._last_gauge_refresh = now
        self._gauges_dirty = False
        for name, res in self.fold_cost.items():
            snap = res.snapshot()
            if snap["count"]:
                RECORDER.set_framework_overhead(
                    name, snap["p50"] * 1e3
                )
        ring = self.ring_write_s.snapshot()
        if ring["count"]:
            RECORDER.set_framework_overhead("ring", ring["p50"] * 1e3)
        total = self.framework_p50_ms()
        if total is not None:
            RECORDER.set_framework_overhead("total", total)
        # the budget rides the same family so the alert rule compares
        # total against the CONFIGURED budget, not a hardcoded constant
        RECORDER.set_framework_overhead("budget", self.budget_ms)
        for hop, n in self.records_total.items():
            RECORDER.set_telemetry_records(hop, n)
        # autopilot model health shares the throttled refresh: one gauge
        # pass per second, never per observation
        try:
            from seldon_core_tpu.runtime.autopilot import AUTOPILOT

            AUTOPILOT.publish_gauges()
        except Exception:  # noqa: BLE001 - gauges must not wedge a drain
            pass
        # derived generation-lane gauges (served decode MFU) ride the
        # same throttle — computed from GENPERF's fold-side totals
        try:
            from seldon_core_tpu.utils.genperf import GENPERF

            GENPERF.publish_gauges()
        except Exception:  # noqa: BLE001 - gauges must not wedge a drain
            pass
        # durable perf-corpus accounting (rows / disk bytes / warm keys)
        try:
            from seldon_core_tpu.utils.perfcorpus import CORPUS

            CORPUS.publish_gauges()
        except Exception:  # noqa: BLE001 - gauges must not wedge a drain
            pass
        # resource-attribution counters (cost_device_seconds /
        # kv_block_seconds / pad_tax / attributed_fraction) — deltas
        # computed fold-side, pushed on the same 1/s throttle
        try:
            from seldon_core_tpu.utils.costledger import LEDGER

            LEDGER.publish_gauges()
        except Exception:  # noqa: BLE001 - gauges must not wedge a drain
            pass
        # postmortem pinned-span accounting rides the same throttle —
        # never per keep/drop
        try:
            from seldon_core_tpu.utils.postmortem import POSTMORTEM

            POSTMORTEM.publish_gauges()
        except Exception:  # noqa: BLE001 - gauges must not wedge a drain
            pass

    # -- the /overhead surface ---------------------------------------------

    def framework_p50_ms(self) -> Optional[float]:
        """Per-request framework overhead estimate from the folded
        records: request-hop p50 minus dispatch-hop p50 (the same
        subtraction bench.py's ``span_framework_p50_ms`` makes).  None
        until both hops have samples — request hops need tracing on."""
        req = self.hop_ms["request"].snapshot()
        disp = self.hop_ms["dispatch"].snapshot()
        if not req["count"] or not disp["count"]:
            return None
        return round(max(req["p50"] - disp["p50"], 0.0), 3)

    def overhead_document(self) -> Dict[str, Any]:
        """The ``GET /overhead`` body: the telemetry budget as a
        self-observed SLO, decomposed per subsystem from the records
        themselves (docs/operations.md runbook)."""
        self.drain()
        with self._rings_lock:
            rings = list(self._rings)
        dropped = self._retired_dropped + sum(r.dropped for r in rings)
        writes = self._retired_writes + sum(r.writes for r in rings)

        def us(res: Reservoir) -> Dict[str, Any]:
            s = res.snapshot()
            return {
                "count": s["count"],
                "p50_us": round(s["p50"] * 1e6, 2),
                "p99_us": round(s["p99"] * 1e6, 2),
                "mean_us": round(s["mean"] * 1e6, 2),
            }

        framework = self.framework_p50_ms()
        req = self.hop_ms["request"].snapshot()
        disp = self.hop_ms["dispatch"].snapshot()
        return {
            "budget_ms": self.budget_ms,
            "framework_p50_ms": framework,
            "within_budget": (
                None if framework is None else framework <= self.budget_ms
            ),
            "needs_tracing": not req["count"],
            "hops_ms": {
                "request_p50": round(req["p50"] * 1.0, 3),
                "dispatch_p50": round(disp["p50"] * 1.0, 3),
                "request_count": req["count"],
                "dispatch_count": disp["count"],
            },
            "off_path_fold": {k: us(v) for k, v in self.fold_cost.items()},
            "ring": {
                "threads": len(rings),
                "capacity": self.ring_capacity,
                "writes": writes,
                "dropped_total": dropped,
                "write_cost": us(self.ring_write_s),
                "test_delay_ms": round(self.test_delay_s * 1e3, 3),
            },
            "records_folded": dict(self.records_total),
            "consumers": {
                "recorder": self.telemetry_enabled,
                "tracer": TRACER.enabled,
                "perf": OBSERVATORY.enabled,
                "quality": QUALITY.enabled,
            },
            "sampling": {
                "unified": True,
                "trace": TRACER.sample,
                "quality": QUALITY.sample,
            },
        }

    def reset(self) -> None:
        """Drop pending records and overhead accounting — tests only."""
        with self._drain_lock:
            with self._rings_lock:
                rings = list(self._rings)
            scratch: List[HotRecord] = []
            for ring in rings:
                ring.pop_into(scratch)
            self._dropped_folded = self._retired_dropped + sum(
                r.dropped for r in rings
            )
            self.records_total = {}
            self.fold_cost = {
                k: Reservoir(1024) for k in self.fold_cost
            }
            self.ring_write_s = Reservoir(1024)
            self.hop_ms = {
                "request": Reservoir(2048), "dispatch": Reservoir(2048)
            }


SPINE = TelemetrySpine()
atexit.register(SPINE.quiesce)

# wire the off-path consumers: the singletons' spans route through the
# ring, and every query surface drains before reading.  Local instances
# (tests construct their own Tracer/observatories) keep their inline
# synchronous behaviour — sink/drain hooks default to None.
TRACER.sink = SPINE.offer_span
TRACER.drain_hook = SPINE.drain
RECORDER.drain_hook = SPINE.drain
OBSERVATORY.drain_hook = SPINE.drain
QUALITY.drain_hook = SPINE.drain

# tail-sampled postmortem capture (utils/postmortem.py): every folded
# span — sampled or pm_only — is offered to the pending buffer so the
# keep/drop verdict can wait for request completion.  The kill switch
# (SELDON_TPU_POSTMORTEM=0) leaves pm_hook None, which restores head
# sampling bit-for-bit: no pm_only spans are ever recorded.
from seldon_core_tpu.utils.postmortem import POSTMORTEM  # noqa: E402

if POSTMORTEM.enabled:
    TRACER.pm_hook = POSTMORTEM.offer
