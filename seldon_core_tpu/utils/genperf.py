"""Generation-lane flight recorder — the aggregator behind ``GET /genperf``.

The continuous-batching scheduler (runtime/genserver.py) stamps ONE fused
record per tick into the telemetry spine (utils/hotrecord.py
HOP_GEN_STEP).  This PR enriches that record with a full per-tick
decomposition — host-schedule wall vs fenced device wall, admit/prefill/
decode/retire phase splits, real-vs-padded rows, KV blocks touched, and
an explicit **bubble ledger** (device-idle time between consecutive
ticks, classified by cause) — and the spine's off-path drainer folds it
HERE.  Nothing in this module ever runs on the scheduler's hot path: the
tick loop's only added cost is a handful of ``perf_counter()`` stamps
and the ``block_until_ready`` fence around work it was about to
host-sync anyway.

What the aggregator answers (docs/operations.md "reading the /genperf
page"):

  * per-tick-kind latency percentiles (prefill / decode / spec / mixed /
    idle) and per-phase host/device totals;
  * the bubble ledger — seconds of scheduler wall not covered by any
    tick, by cause:
      - ``host``: the scheduler loop's own bookkeeping between ticks;
      - ``admission_stall``: sequences were waiting but none admitted
        (slots full);
      - ``pool_exhaustion``: admission broke on a dry KV pool;
      - ``idle``: no work anywhere (the 5 ms backoff / blocking wait);
  * served decode MFU and HBM-BW utilization — the perf observatory's
    analytic cost features for the decode step
    (``OBSERVATORY.cost_features("gen_decode_step")``, registered by the
    scheduler at device init) priced against REAL (unpadded) tokens over
    the fenced decode device time, normalized by ``OBSERVATORY.peaks()``;
  * an idle-poll duty cycle (idle tick wall / scheduler wall) so a
    hot-spinning scheduler reads as a bubble, not as silence;
  * a KV-block age histogram (block residency at release) for pool
    sizing.

The host+device+bubble ledger accounts for scheduler wall BY
CONSTRUCTION: per-tick host time is defined as tick wall minus fenced
device time, and the bubble is the inter-tick gap — the demo artifact's
>= 95 % accounting criterion checks the arithmetic stayed wired, not a
lucky measurement.

Kill switches: ``SELDON_TPU_TELEMETRY=0`` stops the spine record at the
source (``record_gen_step`` returns before any ring write), and
``SELDON_TPU_GEN_CONTINUOUS=0`` removes the scheduler entirely — either
way this module sees zero observations.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from seldon_core_tpu.utils.telemetry import RECORDER, Reservoir

__all__ = ["GenPerf", "GENPERF", "BUBBLE_CAUSES", "TICK_PHASES"]

#: the bubble ledger's closed cause vocabulary (labels on
#: seldon_tpu_gen_bubble_seconds_total)
BUBBLE_CAUSES = ("host", "admission_stall", "pool_exhaustion", "idle")

#: per-tick phase vocabulary (labels on seldon_tpu_gen_step_seconds)
TICK_PHASES = ("admit", "prefill", "decode", "retire", "host_other")


class GenPerf:
    """Process-global per-tick generation-lane accounting.  All observe
    methods are called from the telemetry spine's off-path drainer only;
    they are cheap and never raise."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.ticks: Dict[str, int] = {}             # kind -> count
        self.tick_wall: Dict[str, Reservoir] = {}   # kind -> wall seconds
        #: host/device seconds by (kind, phase); "host_other" is the
        #: tick-wall residual no named phase covers
        self.phase_host_s: Dict[Tuple[str, str], float] = {}
        self.phase_device_s: Dict[Tuple[str, str], float] = {}
        self.wall_s = 0.0            # sum of tick walls
        self.host_s = 0.0            # wall - fenced device time
        self.device_s = 0.0          # fenced device time
        self.bubble_s: Dict[str, float] = {}        # cause -> seconds
        self.bubble_ticks: Dict[str, int] = {}
        self.idle_ticks = 0
        self.idle_wall_s = 0.0
        self.rows = 0                # padded rows dispatched
        self.real_rows = 0           # real rows dispatched
        self.kv_blocks_touched = 0
        # served-decode accounting (decode/spec/mixed ticks only)
        self.decode_device_s = 0.0
        self.decode_tokens = 0       # REAL tokens emitted by decode ticks
        self.decode_steps = 0        # single-token device steps run
        self.decode_kv_positions = 0  # cache positions streamed per step
        self.kv_block_age = Reservoir(1024)   # seconds held at release
        self.kv_blocks_released = 0
        self.tick_errors = 0

    # -- feeding (spine drainer only) ------------------------------------

    def observe_tick(self, kind: str, detail: Dict[str, Any]) -> None:
        """Fold one enriched HOP_GEN_STEP record.  ``detail`` is the
        dict the scheduler attached to ``SPINE.record_gen_step`` — see
        runtime/genserver.py ``_publish`` for the producing side."""
        wall = float(detail.get("wall_s", 0.0))
        device = float(detail.get("device_s", 0.0))
        host = max(wall - device, 0.0)
        bubble = float(detail.get("bubble_s", 0.0))
        cause = str(detail.get("bubble_cause", "") or "")
        phases = detail.get("phases") or {}
        dev_phases = detail.get("device_phases") or {}
        kv_ages = detail.get("kv_ages") or ()
        with self._lock:
            self.ticks[kind] = self.ticks.get(kind, 0) + 1
            res = self.tick_wall.get(kind)
            if res is None:
                res = self.tick_wall[kind] = Reservoir(512)
            self.wall_s += wall
            self.host_s += host
            self.device_s += device
            if kind == "idle":
                self.idle_ticks += 1
                self.idle_wall_s += wall
            if cause in BUBBLE_CAUSES and bubble > 0:
                self.bubble_s[cause] = self.bubble_s.get(cause, 0.0) + bubble
                self.bubble_ticks[cause] = self.bubble_ticks.get(cause, 0) + 1
            named_host = 0.0
            for phase, secs in phases.items():
                dev = float(dev_phases.get(phase, 0.0))
                h = max(float(secs) - dev, 0.0)
                named_host += float(secs)
                key = (kind, phase)
                self.phase_host_s[key] = self.phase_host_s.get(key, 0.0) + h
                if dev > 0:
                    self.phase_device_s[key] = (
                        self.phase_device_s.get(key, 0.0) + dev)
            residual = max(wall - named_host, 0.0)
            if residual > 0:
                key = (kind, "host_other")
                self.phase_host_s[key] = (
                    self.phase_host_s.get(key, 0.0) + residual)
            self.rows += int(detail.get("rows", 0) or 0)
            self.real_rows += int(detail.get("real_rows", 0) or 0)
            self.kv_blocks_touched += int(detail.get("kv_blocks", 0) or 0)
            if kind in ("decode", "spec", "mixed"):
                self.decode_device_s += float(
                    dev_phases.get("decode", 0.0))
                self.decode_tokens += int(detail.get("tokens", 0) or 0)
                self.decode_steps += int(detail.get("steps", 0) or 0)
                self.decode_kv_positions += int(
                    detail.get("kv_positions", 0) or 0)
            for n_blocks, age_s in kv_ages:
                self.kv_blocks_released += int(n_blocks)
                self.kv_block_age.observe(float(age_s))
        # reservoirs take their own lock; observe outside ours
        res.observe(wall)

    def observe_tick_error(self) -> None:
        with self._lock:
            self.tick_errors += 1

    # -- derived figures --------------------------------------------------

    def served_decode(self) -> Dict[str, Any]:
        """Served decode MFU / HBM-BW utilization over the fenced decode
        device time, priced with the perf observatory's registered
        decode-step cost features against REAL tokens.  All-null when the
        scheduler never registered features or no decode tick ran."""
        from seldon_core_tpu.utils.perf import OBSERVATORY

        with self._lock:
            dev_s = self.decode_device_s
            tokens = self.decode_tokens
            steps = self.decode_steps
            kv_pos = self.decode_kv_positions
        out: Dict[str, Any] = {
            "decode_device_s": round(dev_s, 4),
            "real_tokens": tokens,
            "device_steps": steps,
            "served_decode_mfu_pct": None,
            "served_decode_hbm_bw_util_pct": None,
            "served_decode_tok_s_device": (
                round(tokens / dev_s, 1) if dev_s > 0 else None
            ),
        }
        cost = OBSERVATORY.cost_features("gen_decode_step")
        if not cost or dev_s <= 0 or tokens <= 0:
            return out
        peaks = OBSERVATORY.peaks()
        flops = tokens * float(cost.get("flops", 0.0))
        if flops > 0 and peaks.get("peak_bf16_tflops"):
            out["served_decode_mfu_pct"] = round(
                100.0 * flops / dev_s / (peaks["peak_bf16_tflops"] * 1e12),
                4)
        # bytes: every device step streams the matmul'd weights once,
        # plus the cache positions the batch's block tables cover
        nbytes = (steps * float(cost.get("bytes_accessed", 0.0))
                  + kv_pos * float(cost.get("kv_bytes_per_position", 0.0)))
        if nbytes > 0 and peaks.get("peak_hbm_gbs"):
            out["served_decode_hbm_bw_util_pct"] = round(
                100.0 * nbytes / dev_s / (peaks["peak_hbm_gbs"] * 1e9), 4)
        return out

    def bubble_fraction(self) -> Optional[float]:
        """Bubble seconds / (tick wall + bubble seconds) — the share of
        scheduler wall the device spent waiting between ticks."""
        with self._lock:
            bubble = sum(self.bubble_s.values())
            total = self.wall_s + bubble
        if total <= 0:
            return None
        return bubble / total

    def document(self) -> Dict[str, Any]:
        """The aggregator's half of the ``GET /genperf`` body."""
        with self._lock:
            bubble = sum(self.bubble_s.values())
            total_wall = self.wall_s + bubble
            doc: Dict[str, Any] = {
                "ticks": dict(self.ticks),
                "tick_wall_ms": {
                    kind: {
                        k: round(v * 1e3, 3)
                        for k, v in res.snapshot().items()
                        if k in ("mean", "p50", "p95", "p99", "max")
                    }
                    for kind, res in self.tick_wall.items()
                },
                "phases": {
                    "host_s": {
                        f"{kind}/{phase}": round(v, 4)
                        for (kind, phase), v in self.phase_host_s.items()
                    },
                    "device_s": {
                        f"{kind}/{phase}": round(v, 4)
                        for (kind, phase), v in self.phase_device_s.items()
                    },
                },
                "accounting": {
                    # host + device + bubble vs scheduler wall — the demo
                    # artifact's >= 95 % criterion reads this block
                    "scheduler_wall_s": round(total_wall, 4),
                    "host_s": round(self.host_s, 4),
                    "device_s": round(self.device_s, 4),
                    "bubble_s": round(bubble, 4),
                    "accounted_fraction": (
                        round((self.host_s + self.device_s + bubble)
                              / total_wall, 4)
                        if total_wall > 0 else None
                    ),
                },
                "bubbles": {
                    "by_cause_s": {
                        k: round(v, 4) for k, v in self.bubble_s.items()
                    },
                    "by_cause_ticks": dict(self.bubble_ticks),
                    "fraction": (
                        round(bubble / total_wall, 4)
                        if total_wall > 0 else None
                    ),
                },
                "idle": {
                    "ticks": self.idle_ticks,
                    "wall_s": round(self.idle_wall_s, 4),
                    # a hot-spinning scheduler pushes this toward 1.0
                    "duty_cycle": (
                        round(self.idle_wall_s / total_wall, 4)
                        if total_wall > 0 else None
                    ),
                },
                "rows": {
                    "padded_total": self.rows,
                    "real_total": self.real_rows,
                    "real_fraction": (
                        round(self.real_rows / self.rows, 4)
                        if self.rows > 0 else None
                    ),
                },
                "kv": {
                    "blocks_touched_total": self.kv_blocks_touched,
                    "blocks_released_total": self.kv_blocks_released,
                    "block_age_s": self.kv_block_age.snapshot(),
                },
                "tick_errors_total": self.tick_errors,
            }
        doc["served_decode"] = self.served_decode()
        return doc

    def publish_gauges(self) -> None:
        """Refresh the derived Prometheus gauges — called from the
        spine's throttled ``_refresh_gauges`` (~1/s), never per tick."""
        served = self.served_decode()
        mfu = served.get("served_decode_mfu_pct")
        if mfu is not None:
            RECORDER.set_gen_served_mfu(mfu / 100.0)

    def reset(self) -> None:
        """Fresh state — tests only."""
        with self._lock:
            self.ticks = {}
            self.tick_wall = {}
            self.phase_host_s = {}
            self.phase_device_s = {}
            self.wall_s = 0.0
            self.host_s = 0.0
            self.device_s = 0.0
            self.bubble_s = {}
            self.bubble_ticks = {}
            self.idle_ticks = 0
            self.idle_wall_s = 0.0
            self.rows = 0
            self.real_rows = 0
            self.kv_blocks_touched = 0
            self.decode_device_s = 0.0
            self.decode_tokens = 0
            self.decode_steps = 0
            self.decode_kv_positions = 0
            self.kv_block_age = Reservoir(1024)
            self.kv_blocks_released = 0
            self.tick_errors = 0


GENPERF = GenPerf()
