"""Utilities: metrics, puid, config."""
