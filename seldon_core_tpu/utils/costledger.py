"""Resource-attribution ledger — the aggregator behind ``GET /costs``.

Every observability layer so far answers "how is the system doing";
this one answers "**who** is consuming the fleet".  Producers (the
micro-batcher's flush record, the continuous-batching scheduler's tick
record, the BlockAllocator's release path, the wire/REST byte counters)
attach a small attribution payload to records they ALREADY stamp into
the telemetry spine (utils/hotrecord.py), and the spine's off-path
drainer folds them HERE — the PR-6 pattern: zero hot-path work beyond
fields the records mostly already carry.

Attribution rule (docs/operations.md "reading the /costs page"):

  * each dispatch/tick's **fenced device wall** splits across its
    constituent requests proportional to real units — prefill: real
    tokens; decode: live sequences; micro-batch: real rows;
  * the padded remainder (pow-2 bucket capacity minus real units) is
    booked to a per-tenant **pad-tax** bucket, split by the same real
    shares — you pay for the padding your batch shape caused;
  * inter-tick bubbles (the PR-16 bubble ledger) are booked to
    ``idle`` — nobody's fault, still somebody's chip;
  * device wall that arrives with NO attribution payload (a lane not
    yet wired, or a tick raced past the producer) is booked to
    ``unattributed`` and *lowers* ``accounted_fraction`` — the
    Prometheus gauge ``seldon_tpu_cost_attributed_fraction`` reads
    below 1.0 exactly when the ledger is lying by omission.

So the accounting identity

    sum(attributed) + pad_tax + idle + unattributed == device wall

holds BY CONSTRUCTION, and ``accounted_fraction`` is 1.0 whenever every
fold carried attribution (asserted in ``make cost-demo``'s artifact).

Beyond device-seconds the ledger integrates per-sequence
**KV-block-seconds** (blocks x held-time, stamped by the scheduler at
retire/preempt) and tenant/deployment-attributed **bytes** per ingress
lane, and prices a ``capacity`` block (consumed vs available
chip-seconds) that scale-ahead and model-density admission can steer
by.

Optional consumer (``SELDON_TPU_QOS_USAGE_WEIGHTED=1``): the QoS WFQ
virtual clock (runtime/qos.py) advances by attributed cost instead of
request count via :meth:`CostLedger.usage_advance`, so a 10-token
tenant and a 10k-token tenant stop being "equal".

Kill switch: ``SELDON_TPU_COSTLEDGER=0`` — producers skip building the
attribution payload, records fold without the WANT_COST bit, and this
module sees zero observations; serving is bit-identical.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "CostLedger",
    "LEDGER",
    "costledger_enabled",
    "usage_weighted_enabled",
    "merge_cost_documents",
]

#: closed phase vocabulary for device-seconds attribution (Prometheus
#: label values on seldon_tpu_cost_device_seconds_total{phase=...})
COST_PHASES = ("batch", "prefill", "decode")


def costledger_enabled() -> bool:
    """Kill switch — read dynamically so tests can flip it per-case."""
    return os.environ.get("SELDON_TPU_COSTLEDGER", "1") != "0"


def usage_weighted_enabled() -> bool:
    """Opt-in: WFQ virtual clock advances by attributed cost."""
    return os.environ.get("SELDON_TPU_QOS_USAGE_WEIGHTED", "0") == "1"


class CostLedger:
    """Lock-protected fold target for attribution payloads.

    All ``fold_*`` methods run on the spine's drainer thread only;
    ``note_bytes`` is the one producer-side entry point (a dict
    increment under the lock, same price as the MetricsRecorder
    counters it rides next to).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._t0 = time.time()
        # (tenant, deployment, phase) -> attributed device seconds
        self.device_s: Dict[Tuple[str, str, str], float] = {}
        # (tenant, deployment) -> pad-tax seconds
        self.pad_tax_s: Dict[Tuple[str, str], float] = {}
        # (tenant, deployment) -> KV block-seconds (blocks x held-time)
        self.kv_block_s: Dict[Tuple[str, str], float] = {}
        # (tenant, deployment, lane) -> bytes
        self.bytes_by: Dict[Tuple[str, str, str], int] = {}
        # (tenant, deployment, phase) -> served tokens
        self.served_tokens: Dict[Tuple[str, str, str], int] = {}
        # (tier, phase) -> (device seconds incl. pad share, served tokens)
        self.tier_device_s: Dict[Tuple[str, str], float] = {}
        self.tier_tokens: Dict[Tuple[str, str], int] = {}
        # tenant -> [attributed seconds incl. pad share, request count]
        self._usage: Dict[str, List[float]] = {}
        self.idle_s = 0.0
        self.unattributed_s = 0.0
        self.wall_s = 0.0
        self.folds = 0
        #: chips this process drives (engine stamps it at device init);
        #: prices the capacity block's available chip-seconds
        self.devices = 1
        # deltas already pushed to Prometheus (publish_gauges)
        self._pub: Dict[Tuple[str, str, str], float] = {}
        self._pub_kv: Dict[Tuple[str, str], float] = {}
        self._pub_pad: Dict[Tuple[str, str], float] = {}

    # ---- fold side (drainer thread) ---------------------------------

    def _fold_phase(
        self,
        deployment: str,
        phase: str,
        device_s: float,
        padded_units: float,
        tenants: Iterable[Tuple[str, str, float, float, float]],
    ) -> None:
        """Split one dispatch's fenced device wall.

        ``tenants`` rows are ``(tenant, tier, real_units, requests,
        served_tokens)``; ``padded_units`` is the dispatched capacity
        (pow-2 bucket) the real units were padded up to.
        """
        rows = list(tenants)
        real = sum(t[2] for t in rows)
        with self._lock:
            self.wall_s += device_s
            self.folds += 1
            attributable = real > 0
            if device_s > 0 and not attributable:
                self.unattributed_s += device_s
            if not rows:
                return
            cap = max(float(padded_units), float(real), 1.0)
            pad_s = (device_s * (cap - real) / cap
                     if attributable else 0.0)
            for tenant, tier, units, requests, toks in rows:
                # zero-unit rows still book their request/served-token
                # counts (token emission is noted separately from the
                # device dispatch that produced it)
                share = (device_s * units / cap) if attributable else 0.0
                pad_share = (pad_s * units / real) if attributable else 0.0
                self.device_s[(tenant, deployment, phase)] = (
                    self.device_s.get((tenant, deployment, phase), 0.0)
                    + share
                )
                if pad_share > 0:
                    self.pad_tax_s[(tenant, deployment)] = (
                        self.pad_tax_s.get((tenant, deployment), 0.0)
                        + pad_share
                    )
                if toks:
                    self.served_tokens[(tenant, deployment, phase)] = (
                        self.served_tokens.get(
                            (tenant, deployment, phase), 0)
                        + int(toks)
                    )
                tier = tier or "batch"
                self.tier_device_s[(tier, phase)] = (
                    self.tier_device_s.get((tier, phase), 0.0)
                    + share + pad_share
                )
                if toks:
                    self.tier_tokens[(tier, phase)] = (
                        self.tier_tokens.get((tier, phase), 0) + int(toks)
                    )
                u = self._usage.setdefault(tenant, [0.0, 0.0])
                u[0] += share + pad_share
                u[1] += float(requests)

    def fold_flush(self, cost: Dict[str, Any],
                   device_s: float) -> None:
        """One micro-batcher flush (HOP_FLUSH with WANT_COST).

        The flush wall is readback-synced (the dispatch helper fetches
        outputs before the bracket closes), so it is this lane's honest
        device wall.
        """
        self._fold_phase(
            cost.get("dep", "") or "",
            "batch",
            float(device_s),
            float(cost.get("padded", 0.0)),
            cost.get("tenants") or (),
        )

    def fold_gen_tick(self, detail: Dict[str, Any]) -> None:
        """One scheduler tick (HOP_GEN_STEP with WANT_COST).

        ``detail["attr"]`` carries per-phase tenant splits and the
        tick's KV releases; ``detail["device_phases"]`` is the fenced
        per-phase device wall; ``detail["bubble_s"]`` is the inter-tick
        gap (booked to idle whatever its bubble-ledger cause).
        """
        attr = detail.get("attr") or {}
        deployment = attr.get("dep", "") or ""
        phases = attr.get("phases") or {}
        for phase, dev in (detail.get("device_phases") or {}).items():
            dev = float(dev)
            if dev <= 0:
                continue
            pa = phases.get(phase)
            if pa:
                self._fold_phase(deployment, phase, dev,
                                 float(pa.get("padded", 0.0)),
                                 pa.get("tenants") or ())
            else:
                with self._lock:
                    self.wall_s += dev
                    self.unattributed_s += dev
        bubble = float(detail.get("bubble_s") or 0.0)
        kv = attr.get("kv") or ()
        with self._lock:
            if bubble > 0:
                self.wall_s += bubble
                self.idle_s += bubble
            for tenant, block_s in kv:
                if block_s > 0:
                    self.kv_block_s[(tenant, deployment)] = (
                        self.kv_block_s.get((tenant, deployment), 0.0)
                        + float(block_s)
                    )

    # ---- producer side ----------------------------------------------

    def note_bytes(self, tenant: str, deployment: str, lane: str,
                   n: int) -> None:
        """Attribute ingress/egress bytes.  Hot-path-cheap; callers
        gate on :func:`costledger_enabled`."""
        if n <= 0:
            return
        key = (tenant or "", deployment or "", lane)
        with self._lock:
            self.bytes_by[key] = self.bytes_by.get(key, 0) + int(n)

    def usage_advance(self, tenant: str) -> float:
        """Normalized per-request WFQ advance for ``tenant``.

        Ratio of the tenant's attributed cost per request to the
        process-wide mean, clamped to [0.25, 20] — heavy tenants'
        virtual clocks run faster, so WFQ stops treating a 10-token and
        a 10k-token request as equal.  1.0 until the ledger has data.
        """
        with self._lock:
            u = self._usage.get(tenant or "")
            if not u or u[1] <= 0:
                return 1.0
            g_cost = sum(v[0] for v in self._usage.values())
            g_req = sum(v[1] for v in self._usage.values())
            if g_cost <= 0 or g_req <= 0:
                return 1.0
            ratio = (u[0] / u[1]) / (g_cost / g_req)
        return min(20.0, max(0.25, ratio))

    # ---- read side --------------------------------------------------

    def _accounting_locked(self) -> Dict[str, Any]:
        attributed = sum(self.device_s.values())
        pad = sum(self.pad_tax_s.values())
        wall = self.wall_s
        frac = 1.0
        if wall > 0:
            frac = (attributed + pad + self.idle_s) / wall
        return {
            "device_wall_s": round(wall, 6),
            "attributed_s": round(attributed, 6),
            "pad_tax_s": round(pad, 6),
            "idle_s": round(self.idle_s, 6),
            "unattributed_s": round(self.unattributed_s, 6),
            "accounted_fraction": round(frac, 6),
            "folds": self.folds,
        }

    def document(self) -> Dict[str, Any]:
        """The ``GET /costs`` body (engine-local; the gateway federates
        these with :func:`merge_cost_documents`)."""
        with self._lock:
            elapsed = max(time.time() - self._t0, 1e-9)
            rows: Dict[Tuple[str, str], Dict[str, Any]] = {}

            def row(tenant: str, dep: str) -> Dict[str, Any]:
                r = rows.get((tenant, dep))
                if r is None:
                    r = rows[(tenant, dep)] = {
                        "tenant": tenant,
                        "deployment": dep,
                        "device_s": {},
                        "pad_tax_s": 0.0,
                        "kv_block_s": 0.0,
                        "bytes": {},
                        "served_tokens": {},
                    }
                return r

            for (t, d, ph), v in self.device_s.items():
                row(t, d)["device_s"][ph] = round(v, 6)
            for (t, d), v in self.pad_tax_s.items():
                row(t, d)["pad_tax_s"] = round(v, 6)
            for (t, d), v in self.kv_block_s.items():
                row(t, d)["kv_block_s"] = round(v, 3)
            for (t, d, lane), v in self.bytes_by.items():
                row(t, d)["bytes"][lane] = v
            for (t, d, ph), v in self.served_tokens.items():
                row(t, d)["served_tokens"][ph] = v
            for r in rows.values():
                toks = sum(r["served_tokens"].values())
                cost = sum(r["device_s"].values()) + r["pad_tax_s"]
                r["cost_per_1k_served_tokens_s"] = (
                    round(1000.0 * cost / toks, 6) if toks else None
                )
            acct = self._accounting_locked()
            busy = (acct["attributed_s"] + acct["pad_tax_s"]
                    + acct["unattributed_s"])
            tiers = {
                f"{tier}/{ph}": {
                    "device_s": round(v, 6),
                    "served_tokens": self.tier_tokens.get((tier, ph), 0),
                }
                for (tier, ph), v in self.tier_device_s.items()
            }
        return {
            "enabled": costledger_enabled(),
            "window_s": round(elapsed, 3),
            "tenants": sorted(
                rows.values(),
                key=lambda r: (r["tenant"], r["deployment"]),
            ),
            "tiers": tiers,
            "accounting": acct,
            "capacity": {
                "chips": self.devices,
                "available_chip_s": round(self.devices * elapsed, 3),
                "consumed_chip_s": round(busy, 6),
                "utilization": round(
                    busy / (self.devices * elapsed), 6),
            },
        }

    def publish_gauges(self) -> None:
        """Push monotone deltas into the MetricsRecorder (called from
        the spine's throttled gauge refresh, ~1/s)."""
        from seldon_core_tpu.utils.telemetry import RECORDER
        with self._lock:
            dev = [(k, v - self._pub.get(k, 0.0))
                   for k, v in self.device_s.items()]
            for k, v in self.device_s.items():
                self._pub[k] = v
            kv = [(k, v - self._pub_kv.get(k, 0.0))
                  for k, v in self.kv_block_s.items()]
            for k, v in self.kv_block_s.items():
                self._pub_kv[k] = v
            pad = [(k, v - self._pub_pad.get(k, 0.0))
                   for k, v in self.pad_tax_s.items()]
            for k, v in self.pad_tax_s.items():
                self._pub_pad[k] = v
            frac = self._accounting_locked()["accounted_fraction"]
        for (tenant, dep, phase), d in dev:
            if d > 0:
                RECORDER.record_cost_device_seconds(tenant, dep, phase, d)
        for (tenant, dep), d in kv:
            if d > 0:
                RECORDER.record_cost_kv_block_seconds(tenant, dep, d)
        for (tenant, dep), d in pad:
            if d > 0:
                RECORDER.record_cost_pad_tax_seconds(tenant, dep, d)
        RECORDER.record_cost_attributed_fraction(frac)

    def reset(self) -> None:
        with self._lock:
            self._t0 = time.time()
            self.device_s.clear()
            self.pad_tax_s.clear()
            self.kv_block_s.clear()
            self.bytes_by.clear()
            self.served_tokens.clear()
            self.tier_device_s.clear()
            self.tier_tokens.clear()
            self._usage.clear()
            self._pub.clear()
            self._pub_kv.clear()
            self._pub_pad.clear()
            self.idle_s = 0.0
            self.unattributed_s = 0.0
            self.wall_s = 0.0
            self.folds = 0


def merge_cost_documents(
    docs: Iterable[Optional[Dict[str, Any]]],
) -> Dict[str, Any]:
    """Fold N ``/costs`` documents into one fleet rollup.

    Pure summation over the tenant table, accounting block, and
    capacity block — so a single-engine fleet's federated rollup equals
    the engine's own document (modulo the gateway's empty local rows),
    which the acceptance test pins.
    """
    rows: Dict[Tuple[str, str], Dict[str, Any]] = {}
    acct = {"device_wall_s": 0.0, "attributed_s": 0.0, "pad_tax_s": 0.0,
            "idle_s": 0.0, "unattributed_s": 0.0, "folds": 0}
    cap = {"chips": 0, "available_chip_s": 0.0, "consumed_chip_s": 0.0}
    tiers: Dict[str, Dict[str, Any]] = {}
    window = 0.0
    for doc in docs:
        if not doc:
            continue
        window = max(window, float(doc.get("window_s") or 0.0))
        for r in doc.get("tenants") or ():
            key = (r.get("tenant", ""), r.get("deployment", ""))
            out = rows.setdefault(key, {
                "tenant": key[0], "deployment": key[1],
                "device_s": {}, "pad_tax_s": 0.0, "kv_block_s": 0.0,
                "bytes": {}, "served_tokens": {},
            })
            for ph, v in (r.get("device_s") or {}).items():
                out["device_s"][ph] = round(
                    out["device_s"].get(ph, 0.0) + v, 6)
            out["pad_tax_s"] = round(
                out["pad_tax_s"] + (r.get("pad_tax_s") or 0.0), 6)
            out["kv_block_s"] = round(
                out["kv_block_s"] + (r.get("kv_block_s") or 0.0), 3)
            for lane, v in (r.get("bytes") or {}).items():
                out["bytes"][lane] = out["bytes"].get(lane, 0) + v
            for ph, v in (r.get("served_tokens") or {}).items():
                out["served_tokens"][ph] = (
                    out["served_tokens"].get(ph, 0) + v)
        a = doc.get("accounting") or {}
        for k in acct:
            acct[k] = round(acct[k] + (a.get(k) or 0), 6)
        c = doc.get("capacity") or {}
        cap["chips"] += int(c.get("chips") or 0)
        cap["available_chip_s"] = round(
            cap["available_chip_s"] + (c.get("available_chip_s") or 0.0), 3)
        cap["consumed_chip_s"] = round(
            cap["consumed_chip_s"] + (c.get("consumed_chip_s") or 0.0), 6)
        for name, t in (doc.get("tiers") or {}).items():
            out_t = tiers.setdefault(
                name, {"device_s": 0.0, "served_tokens": 0})
            out_t["device_s"] = round(
                out_t["device_s"] + (t.get("device_s") or 0.0), 6)
            out_t["served_tokens"] += int(t.get("served_tokens") or 0)
    for r in rows.values():
        toks = sum(r["served_tokens"].values())
        cost = sum(r["device_s"].values()) + r["pad_tax_s"]
        r["cost_per_1k_served_tokens_s"] = (
            round(1000.0 * cost / toks, 6) if toks else None
        )
    wall = acct["device_wall_s"]
    acct["accounted_fraction"] = round(
        (acct["attributed_s"] + acct["pad_tax_s"] + acct["idle_s"]) / wall,
        6) if wall > 0 else 1.0
    cap["utilization"] = round(
        cap["consumed_chip_s"] / cap["available_chip_s"], 6
    ) if cap["available_chip_s"] > 0 else 0.0
    return {
        "tenants": sorted(rows.values(),
                          key=lambda r: (r["tenant"], r["deployment"])),
        "tiers": tiers,
        "accounting": acct,
        "capacity": cap,
        "window_s": round(window, 3),
    }


#: process-global ledger (the spine drainer folds into it; /costs reads it)
LEDGER = CostLedger()
