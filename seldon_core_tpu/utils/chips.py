"""Chip spec table — advertised per-chip peaks, shared by bench and runtime.

One table, two consumers: ``bench.py`` normalizes its measured MFU against
these peaks, and the runtime performance observatory (``utils/perf.py``)
normalizes live per-dispatch MFU/roofline figures against the SAME
numbers — extracting the table here is what guarantees bench MFU and
serving MFU can never disagree about what "peak" means.

Values are public spec-sheet figures; matching is by substring of
``device.device_kind`` (e.g. "TPU v5 lite").  Unknown device kinds (CPU
backend, future chips) fall back to a conservative default flagged
``assumed`` so downstream figures are labelled honest rather than wrong.
"""

from __future__ import annotations

from typing import Tuple

__all__ = [
    "PEAK_BF16_TFLOPS",
    "PEAK_HBM_GBS",
    "chip_peak_tflops",
    "chip_peak_hbm_gbs",
]

#: advertised peak dense bf16 matmul throughput per chip, TFLOP/s (public
#: spec sheets; device_kind substring -> peak).  MFU divides by the bf16
#: peak even for int8 paths, so int8 "MFU" can legitimately exceed the
#: bf16-normalized number — ratio keys are the honest comparison.
PEAK_BF16_TFLOPS = (
    ("v6 lite", 918.0), ("v6e", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0), ("v5e", 197.0),
    ("v4", 275.0),
    ("v3", 123.0), ("v2", 46.0),
)

#: advertised HBM bandwidth per chip, GB/s — the memory side of the
#: roofline.  Decode-shaped dispatches are bound by this, not by FLOPs.
PEAK_HBM_GBS = (
    ("v6 lite", 1640.0), ("v6e", 1640.0),
    ("v5p", 2765.0),
    ("v5 lite", 819.0), ("v5e", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0), ("v2", 700.0),
)

#: conservative defaults (v5e-class) used when the device kind matches no
#: table row — flagged assumed by the lookup helpers
_DEFAULT_TFLOPS = 197.0
_DEFAULT_HBM_GBS = 819.0


def _lookup(table, device_kind: str, default: float) -> Tuple[float, bool]:
    dk = (device_kind or "").lower()
    for frag, peak in table:
        if frag in dk:
            return peak, False
    return default, True  # conservative default, flagged as assumed


def chip_peak_tflops(device_kind: str) -> Tuple[float, bool]:
    """(peak dense bf16 TFLOP/s, assumed?) for a device kind string."""
    return _lookup(PEAK_BF16_TFLOPS, device_kind, _DEFAULT_TFLOPS)


def chip_peak_hbm_gbs(device_kind: str) -> Tuple[float, bool]:
    """(peak HBM GB/s, assumed?) for a device kind string."""
    return _lookup(PEAK_HBM_GBS, device_kind, _DEFAULT_HBM_GBS)
