"""TPU performance observatory — per-executable cost analysis, MFU and
roofline accounting, HBM watermarks, and metric↔trace exemplars.

The flight recorder (utils/telemetry.py) says how many requests flow and
the causal tracer (utils/tracing.py) says where time goes, but neither
says whether the TPU itself is being used well: a dispatch running at 4%
MFU looks identical to one at 55%.  This module closes that gap with the
cost features XLA already computes for free:

  * **Compile time**: every compiled executable's static cost features —
    FLOPs, bytes accessed, output bytes — come from
    ``lowered.compile().cost_analysis()`` ("A Learned Performance Model
    for TPUs", arxiv 2008.01040, and "TpuGraphs", arxiv 2308.13490, both
    show these graph-level features predict real latency well).  Backends
    where cost analysis yields nothing degrade to latency-only rows.
    Compile wall time is recorded per executable alongside.
  * **Dispatch time**: measured wall time combines with the static
    features into achieved TFLOP/s, achieved GB/s, MFU against the
    device-kind-matched advertised peak (utils/chips.py — the SAME table
    bench.py normalizes against), and a roofline classification:
    compute-bound vs memory-bound by which peak binds first,
    overhead-bound when measured time exceeds the roofline prediction by
    ``SELDON_TPU_PERF_OVERHEAD_X`` (the dispatch is dominated by
    host/relay overhead, not device work).
  * **Anomalies**: ``seldon_tpu_perf_anomaly_total{kind}`` fires when a
    dispatch drifts past ``SELDON_TPU_PERF_ANOMALY_FACTOR`` x its own
    executable's rolling p50 (``kind="slow_dispatch"``) or its rolling
    measured/predicted ratio (``kind="ratio_drift"``) — per-executable
    baselines, so the detector needs no hardware-specific tuning.
  * **HBM watermarks**: ``device.memory_stats()`` (bytes in use, peak,
    limit) polled into ``seldon_tpu_hbm_*`` gauges, tolerating backends
    (CPU) where it returns nothing.

Surfaces: ``GET /perf`` (engine + unit, every REST lane) renders the
per-executable table; ``seldon_tpu_dispatch_seconds`` histogram
observations carry OpenMetrics exemplars with the active ``trace_id`` so
a slow bucket links straight to its PR-3 trace; dispatch spans gain
``flops`` / ``mfu`` / ``bound`` attributes so ``/trace`` critical paths
show hardware efficiency inline.

Everything is process-global (module global ``OBSERVATORY``, the
``RECORDER``/``TRACER`` pattern) and never raises into the hot path.
``SELDON_TPU_PERF=0`` disables capture entirely.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from seldon_core_tpu.utils.chips import chip_peak_hbm_gbs, chip_peak_tflops
from seldon_core_tpu.utils.telemetry import RECORDER, Reservoir

__all__ = [
    "PerfObservatory",
    "OBSERVATORY",
    "executable_key",
    "extract_cost_features",
]


@functools.lru_cache(maxsize=1024)
def executable_key(name: str, shape, dtype) -> str:
    """Canonical per-executable identity: program name + input shape +
    post-canonicalization dtype (x64 demotion means the dtype that actually
    compiled, not the dtype the client sent).  Shared by the compile-time
    capture (graph/compiled.py) and the dispatch-time observation
    (runtime/engine.py) so both sides name the same executable.  Cached:
    the dispatch hot path names its executable twice per batch (once per
    side), and dtype canonicalization + string building should cost a
    dict hit, not a jax call."""
    try:
        from jax import dtypes as _jdt

        dtype = _jdt.canonicalize_dtype(np.dtype(dtype))
    except Exception:  # noqa: BLE001 - jax unavailable: raw dtype is fine
        pass
    return "%s[%s/%s]" % (
        name, "x".join(str(int(d)) for d in shape), np.dtype(dtype).name
    )


def extract_cost_features(cost: Any) -> Optional[Dict[str, float]]:
    """Normalize whatever ``cost_analysis()`` returned — a dict, a list of
    dicts (one per partition), or nothing — into
    ``{flops, bytes_accessed, output_bytes}``.  Returns None when the
    backend yields no usable features (the caller degrades to
    latency-only accounting); negative/zero FLOPs count as absent (some
    backends report -1 for "unknown")."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    flops = cost.get("flops")
    bytes_accessed = cost.get("bytes accessed")
    output_bytes = None
    for k in ("bytes accessed output", "bytes accessedout{}"):
        if k in cost:
            output_bytes = cost[k]
            break
    out: Dict[str, float] = {}
    if flops is not None and float(flops) > 0:
        out["flops"] = float(flops)
    if bytes_accessed is not None and float(bytes_accessed) > 0:
        out["bytes_accessed"] = float(bytes_accessed)
    if output_bytes is not None and float(output_bytes) > 0:
        out["output_bytes"] = float(output_bytes)
    return out or None


class _ExecutableStats:
    """Everything the observatory knows about one compiled executable."""

    __slots__ = (
        "key", "cost", "compile_s", "calls", "rows_total", "latency",
        "ratio", "calibration", "last", "anomalies", "phases",
    )

    def __init__(self, key: str):
        self.key = key
        self.cost: Optional[Dict[str, float]] = None
        self.compile_s: Optional[float] = None
        #: fused-graph per-node phase decomposition ({node: share of the
        #: program's FLOPs}, graph/fuse.py) — how a one-program-per-graph
        #: executable still itemizes on the /perf table
        self.phases: Optional[Dict[str, float]] = None
        self.calls = 0
        self.rows_total = 0
        self.latency = Reservoir(512)
        #: rolling measured/predicted ratios — the drift baseline
        self.ratio = Reservoir(512)
        #: rolling measured / (overhead-adjusted roofline) ratios — the
        #: per-pad-bucket calibration the autopilot's seed prior uses
        self.calibration = Reservoir(256)
        #: most recent derived figures (mfu, tflops, gbs, bound, ratio)
        self.last: Dict[str, Any] = {}
        self.anomalies = 0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class PerfObservatory:
    """Process-global per-executable performance accounting.  All record
    methods are cheap and never raise — instrumentation must not grow
    failure modes on the dispatch hot path."""

    #: bounded executable table: an exploding shape set must not grow
    #: memory; overflow dispatches aggregate under one key
    MAX_EXECUTABLES = 64
    OVERFLOW_KEY = "other"

    def __init__(
        self,
        enabled: Optional[bool] = None,
        anomaly_factor: Optional[float] = None,
        overhead_x: Optional[float] = None,
        min_calls: int = 10,
        hbm_poll_interval_s: float = 5.0,
    ):
        if enabled is None:
            enabled = os.environ.get("SELDON_TPU_PERF", "1") != "0"
        self.enabled = bool(enabled)
        #: a dispatch beyond factor x its executable's rolling p50 (or
        #: rolling ratio median) is an anomaly
        self.anomaly_factor = (
            anomaly_factor
            if anomaly_factor is not None
            else _env_float("SELDON_TPU_PERF_ANOMALY_FACTOR", 3.0)
        )
        #: measured/predicted beyond this classifies overhead-bound: the
        #: device work the roofline prices is a sliver of the wall time
        self.overhead_x = (
            overhead_x
            if overhead_x is not None
            else _env_float("SELDON_TPU_PERF_OVERHEAD_X", 10.0)
        )
        self.min_calls = int(min_calls)
        self.hbm_poll_interval_s = float(hbm_poll_interval_s)
        self._lock = threading.Lock()
        self._execs: Dict[str, _ExecutableStats] = {}
        #: micro-batcher padding accounting (runtime/batching.py): pad rows
        #: are pure waste FLOPs — the compiler fodder share of device work
        self.real_rows_total = 0
        self.pad_rows_total = 0
        self._peaks: Optional[Dict[str, Any]] = None
        self._hbm_last_poll = 0.0
        self._hbm_last: List[Dict[str, Any]] = []
        #: telemetry-spine wiring (utils/hotrecord.py), set on the global
        #: OBSERVATORY only: dispatch observations arrive via the fused
        #: per-hop record, so query surfaces fold pending records first
        self.drain_hook = None

    def _drain(self) -> None:
        if self.drain_hook is not None:
            self.drain_hook()

    # -- device peaks ------------------------------------------------------

    def peaks(self) -> Dict[str, Any]:
        """Device identity + advertised peaks (lazy; cached).  Tolerates a
        missing/unimportable jax backend — figures then normalize against
        the conservative assumed defaults."""
        if self._peaks is not None:
            return self._peaks
        device_kind, platform = "", ""
        try:
            import jax

            dev = jax.devices()[0]
            device_kind = str(getattr(dev, "device_kind", dev))
            platform = str(getattr(dev, "platform", ""))
        except Exception:  # noqa: BLE001 - no backend: assumed peaks
            pass
        tflops, tflops_assumed = chip_peak_tflops(device_kind)
        hbm_gbs, hbm_assumed = chip_peak_hbm_gbs(device_kind)
        self._peaks = {
            "device_kind": device_kind,
            "platform": platform,
            "peak_bf16_tflops": tflops,
            "peak_hbm_gbs": hbm_gbs,
            "peak_assumed": bool(tflops_assumed or hbm_assumed),
        }
        return self._peaks

    # -- recording ---------------------------------------------------------

    def _entry(self, key: str) -> _ExecutableStats:
        ent = self._execs.get(key)
        if ent is None:
            with self._lock:
                ent = self._execs.get(key)
                if ent is None:
                    if len(self._execs) >= self.MAX_EXECUTABLES:
                        key = self.OVERFLOW_KEY
                        ent = self._execs.get(key)
                        if ent is None:
                            ent = self._execs[key] = _ExecutableStats(key)
                        return ent
                    ent = self._execs[key] = _ExecutableStats(key)
        return ent

    def record_compile(
        self,
        key: str,
        cost: Optional[Dict[str, float]],
        compile_s: Optional[float],
    ) -> None:
        """Static cost features + compile wall time for one executable
        (called once per compiled shape, graph/compiled.py)."""
        if not self.enabled:
            return
        ent = self._entry(key)
        with self._lock:
            # the shared overflow entry must not carry any one shape's
            # cost features — derived figures for unrelated shapes would
            # divide by the wrong FLOP count
            if cost is not None and ent.key != self.OVERFLOW_KEY:
                ent.cost = dict(cost)
            if compile_s is not None:
                ent.compile_s = float(compile_s)
        if compile_s is not None:
            # when the jax.monitoring DURATION listener is live it already
            # observed this backend compile — recording here too would
            # double-count every AOT compile in seldon_tpu_compile_seconds
            # (older jax builds lack that listener; then this is the only
            # source)
            from seldon_core_tpu.utils import telemetry as _telemetry

            if not _telemetry._compile_duration_listener_installed:
                RECORDER.record_compile_seconds(compile_s)

    def note_phases(self, key: str, phases: Dict[str, float]) -> None:
        """Attach a fused graph's per-node phase decomposition to one
        executable row (graph/fuse.py) so the /perf table itemizes a
        one-program-per-graph dispatch per node."""
        if not self.enabled or not phases:
            return
        ent = self._entry(key)
        with self._lock:
            if ent.key != self.OVERFLOW_KEY:
                ent.phases = dict(phases)

    def observe_dispatch(
        self,
        key: str,
        seconds: float,
        rows: Optional[int] = None,
        trace_id: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Combine one measured dispatch with the executable's static cost
        features.  Returns the derived figures (mfu/bound/flops/...) so
        the caller can stamp them onto its dispatch span; {} when the
        observatory is disabled."""
        if not self.enabled or seconds <= 0:
            return {}
        ent = self._entry(key)
        overflow = ent.key == self.OVERFLOW_KEY
        # anomaly baselines BEFORE this observation joins the window
        base = ent.latency.snapshot() if ent.calls >= self.min_calls else None
        ratio_base = (
            ent.ratio.snapshot() if len(ent.ratio) >= self.min_calls else None
        )
        ent.latency.observe(seconds)
        with self._lock:
            ent.calls += 1
            if rows:
                ent.rows_total += int(rows)
            cost = None if overflow else ent.cost
        derived: Dict[str, Any] = {}
        slowdown = None  # measured time as a multiple of the roofline
        peaks = self.peaks()
        if cost:
            flops = cost.get("flops", 0.0)
            nbytes = cost.get("bytes_accessed", 0.0)
            peak_flops_s = peaks["peak_bf16_tflops"] * 1e12
            peak_bytes_s = peaks["peak_hbm_gbs"] * 1e9
            t_compute = flops / peak_flops_s if flops else 0.0
            t_memory = nbytes / peak_bytes_s if nbytes else 0.0
            predicted_s = max(t_compute, t_memory)
            if flops:
                derived["flops"] = flops
                derived["achieved_tflops"] = flops / seconds / 1e12
                derived["mfu"] = flops / seconds / peak_flops_s
            if nbytes:
                derived["achieved_gbs"] = nbytes / seconds / 1e9
                if flops:
                    derived["arithmetic_intensity"] = flops / nbytes
            if predicted_s > 0:
                slowdown = seconds / predicted_s
                derived["predicted_s"] = predicted_s
                # the WALL-time prior is the overhead-adjusted roofline:
                # raw roofline prices device work only, and overhead_x is
                # already the configured device-vs-wall factor (the same
                # one the overhead-bound classification below uses).
                # Using it on BOTH sides keeps this ratio, the per-bucket
                # calibration, and the autopilot's seed prior
                # (seed_predicted_s) in agreement — before this fix the
                # /perf page showed raw-roofline ratios while the
                # overhead classification judged the adjusted time
                adjusted_s = predicted_s * self.overhead_x
                derived["adjusted_predicted_s"] = adjusted_s
                # reads in name order: predicted over measured, 1.0 =
                # wall time exactly at the overhead-adjusted roofline
                derived["predicted_vs_measured"] = adjusted_s / seconds
                ent.calibration.observe(seconds / adjusted_s)
                ent.ratio.observe(slowdown)
                if slowdown > self.overhead_x:
                    derived["bound"] = "overhead"
                else:
                    derived["bound"] = (
                        "compute" if t_compute >= t_memory else "memory"
                    )
        RECORDER.observe_dispatch(
            ent.key, seconds,
            mfu=derived.get("mfu"), trace_id=trace_id,
        )
        # drift detection against the executable's OWN history — no
        # hardware-dependent thresholds.  The shared overflow entry mixes
        # unrelated shapes, so its baselines mean nothing: never fire
        anomaly = None
        if overflow:
            base = ratio_base = None
        if base is not None and base["p50"] > 0:
            if (
                seconds > self.anomaly_factor * base["p50"]
                and seconds - base["p50"] > 1e-3
            ):
                anomaly = "slow_dispatch"
        if (
            anomaly is None
            and slowdown is not None
            and ratio_base is not None
            and ratio_base["p50"] > 0
            and slowdown > self.anomaly_factor * ratio_base["p50"]
        ):
            anomaly = "ratio_drift"
        if anomaly is not None:
            with self._lock:
                ent.anomalies += 1
            derived["anomaly"] = anomaly
            RECORDER.record_perf_anomaly(anomaly)
        with self._lock:
            ent.last = dict(derived)
        return derived

    def seed_predicted_s(self, key: str) -> Optional[float]:
        """The autopilot's seed prior for one executable/pad bucket:
        overhead-adjusted roofline time (``cost_analysis()`` features x
        ``SELDON_TPU_PERF_OVERHEAD_X`` — the same adjusted time
        ``predicted_vs_measured`` reports) scaled by the measured
        calibration ratio — this key's own rolling median when it has
        dispatched, else the median across every calibrated executable
        (so a never-dispatched pad bucket inherits the box's measured
        wall-vs-roofline behaviour).  None when the key has no cost
        features (the autopilot then waits for measurements)."""
        if not self.enabled:
            return None
        ent = self._execs.get(key)
        if ent is None or ent.key == self.OVERFLOW_KEY or not ent.cost:
            return None
        cost = ent.cost
        peaks = self.peaks()
        t_compute = cost.get("flops", 0.0) / (
            peaks["peak_bf16_tflops"] * 1e12
        )
        t_memory = cost.get("bytes_accessed", 0.0) / (
            peaks["peak_hbm_gbs"] * 1e9
        )
        roofline = max(t_compute, t_memory)
        if roofline <= 0:
            return None
        adjusted = roofline * self.overhead_x
        cal = ent.calibration.snapshot()
        if cal["count"]:
            return adjusted * cal["p50"]
        # cross-bucket transfer: the median of every calibrated key's
        # median — one slow shape cannot skew it the way a mean would
        with self._lock:
            entries = list(self._execs.values())
        medians = sorted(
            c["p50"] for c in (e.calibration.snapshot() for e in entries)
            if c["count"]
        )
        if medians:
            return adjusted * medians[len(medians) // 2]
        return adjusted

    def cost_features(self, key: str) -> Optional[Dict[str, float]]:
        """One executable's registered static cost features (or None) —
        the read side of ``record_compile`` for derived-figure consumers
        (the generation flight recorder prices served decode MFU off the
        ``gen_decode_step`` features the scheduler registers)."""
        if not self.enabled:
            return None
        ent = self._execs.get(key)
        if ent is None or not ent.cost:
            return None
        with self._lock:
            return dict(ent.cost)

    def note_padding(self, real_rows: int, padded_rows: int) -> None:
        """Micro-batcher padding accounting: pad rows burn FLOPs without
        serving traffic (runtime/batching.py reports each padded chunk)."""
        if not self.enabled:
            return
        with self._lock:
            self.real_rows_total += int(real_rows)
            self.pad_rows_total += int(padded_rows) - int(real_rows)

    # -- HBM watermarks ----------------------------------------------------

    def hbm_watermarks(self, force: bool = False) -> List[Dict[str, Any]]:
        """Per-device HBM watermarks from ``device.memory_stats()``,
        throttled (memory_stats can be a backend round-trip; scrapes and
        /perf polls share one cached reading per interval).  Backends
        without memory stats (CPU) report ``memory_stats: null`` rows and
        set no gauges — never raises.  ``SELDON_TPU_PERF=0`` really is
        the kill switch: disabled, no backend call happens even from the
        scrape path."""
        if not self.enabled:
            return []
        now = time.monotonic()
        if not force and now - self._hbm_last_poll < self.hbm_poll_interval_s:
            return self._hbm_last
        self._hbm_last_poll = now
        out: List[Dict[str, Any]] = []
        try:
            import jax

            devices = jax.devices()
        except Exception:  # noqa: BLE001 - no backend at all
            self._hbm_last = out
            return out
        for dev in devices:
            label = f"{getattr(dev, 'platform', 'dev')}:{getattr(dev, 'id', 0)}"
            stats = None
            try:
                stats = dev.memory_stats()
            except Exception:  # noqa: BLE001 - backend without memory stats
                stats = None
            if not stats:
                out.append({"device": label, "memory_stats": None})
                continue
            row = {
                "device": label,
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(stats.get("bytes_limit", 0)),
            }
            out.append(row)
            RECORDER.set_hbm(
                label,
                bytes_in_use=row["bytes_in_use"],
                peak_bytes_in_use=row["peak_bytes_in_use"],
                bytes_limit=row["bytes_limit"],
            )
        self._hbm_last = out
        return out

    # -- snapshots ---------------------------------------------------------

    def _row(self, ent: _ExecutableStats) -> Dict[str, Any]:
        lat = ent.latency.snapshot()
        row: Dict[str, Any] = {
            "executable": ent.key,
            "calls": ent.calls,
            "rows": ent.rows_total,
            "latency_ms": {
                k: round(lat[k] * 1e3, 3)
                for k in ("mean", "p50", "p95", "p99", "max")
            },
            "compile_s": (
                None if ent.compile_s is None else round(ent.compile_s, 4)
            ),
            "anomalies": ent.anomalies,
        }
        if ent.phases:
            row["phases"] = dict(ent.phases)
        cost = ent.cost
        if cost:
            row["flops"] = cost.get("flops")
            row["bytes_accessed"] = cost.get("bytes_accessed")
            row["output_bytes"] = cost.get("output_bytes")
            if cost.get("flops") and cost.get("bytes_accessed"):
                row["arithmetic_intensity"] = round(
                    cost["flops"] / cost["bytes_accessed"], 3
                )
        cal = ent.calibration.snapshot()
        if cal["count"]:
            # measured wall / overhead-adjusted roofline, rolling median
            # per pad bucket — 1.0 = the adjusted prior prices this
            # bucket exactly; the autopilot seed (seed_predicted_s) and
            # this figure agree by construction
            row["calibration_ratio"] = float("%.4g" % cal["p50"])
        last = ent.last
        if last:
            for k in ("mfu", "achieved_tflops", "achieved_gbs",
                      "predicted_vs_measured"):
                if k in last:
                    # significant figures, not decimal places: CPU-backend
                    # MFU is legitimately ~1e-8 and must not round to 0
                    row[k] = float("%.4g" % float(last[k]))
            if "bound" in last:
                row["bound"] = last["bound"]
        return row

    def document(self) -> Dict[str, Any]:
        """The ``GET /perf`` body: device identity + peaks, per-executable
        table (calls, latency percentiles, MFU, arithmetic intensity,
        predicted-vs-measured, compile time), batching pad overhead, and
        HBM watermarks."""
        self._drain()
        with self._lock:
            entries = list(self._execs.values())
            real, pad = self.real_rows_total, self.pad_rows_total
        rows = sorted(
            (self._row(e) for e in entries),
            key=lambda r: r["calls"], reverse=True,
        )
        doc: Dict[str, Any] = {
            "enabled": self.enabled,
            "device": self.peaks(),
            "executables": rows,
            "hbm": self.hbm_watermarks(),
            "anomaly_factor": self.anomaly_factor,
            "overhead_x": self.overhead_x,
        }
        if real or pad:
            doc["batching"] = {
                "real_rows_total": real,
                "pad_rows_total": pad,
                "pad_overhead_pct": round(100.0 * pad / max(real + pad, 1), 2),
            }
        return doc

    def snapshot(self) -> Dict[str, Any]:
        """Compact health block for ``/stats`` — the full table lives on
        ``/perf``."""
        self._drain()
        with self._lock:
            n = len(self._execs)
            calls = sum(e.calls for e in self._execs.values())
            anomalies = sum(e.anomalies for e in self._execs.values())
        return {
            "enabled": self.enabled,
            "executables": n,
            "dispatches": calls,
            "anomalies": anomalies,
        }

    def reset(self) -> None:
        """Fresh state — tests only."""
        self._drain()  # pending records fold into the pre-reset state
        with self._lock:
            self._execs = {}
            self.real_rows_total = 0
            self.pad_rows_total = 0
            self._hbm_last_poll = 0.0
            self._hbm_last = []


OBSERVATORY = PerfObservatory()
