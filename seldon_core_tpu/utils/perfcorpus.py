"""Durable perf corpus — the on-disk ledger behind warm autopilots and
the learned cost model.

Every other observatory in this tree is a window: the hotrecord spine's
rings, the perf observatory's reservoirs and the autopilot's EWMA table
all live in process memory and evaporate on restart, so a rebooted
engine re-learns the latency of every (executable, pad-bucket) key from
zero — cold deployments price shapes off the roofline prior until five
dispatches have burned real traffic (ROADMAP item 4 names the missing
training substrate; "A Learned Performance Model for TPUs", arxiv
2008.01040, and TpuGraphs, arxiv 2308.13490, describe what should train
on it).  This module is the ledger those consumers were missing:

  * **One compact row per dispatch**, appended by the spine's drainer
    fold (utils/hotrecord.py) — executable key, pad bucket, QoS tier,
    the perf observatory's static cost features (FLOPs / bytes / rows)
    and the measured wall.  The write rides the fold, never the
    dispatch path: with the telemetry kill switches off there are no
    ring writes, no folds, and therefore zero corpus I/O (the
    overhead-gate's corpus-on arm pins the budget with writes on).
  * **Size-bounded segments + compacted sketches.**  Rows append to
    ``corpus-<seq>.jsonl``; when a segment passes
    ``SELDON_TPU_CORPUS_SEGMENT_BYTES`` it rotates: the in-memory
    per-key sketches (bounded recent-wall sample rings — enough to read
    p50/p90 and a robust spread) persist atomically to ``sketch.json``
    with a ``compacted_through`` watermark, and raw segments beyond
    ``SELDON_TPU_CORPUS_MAX_SEGMENTS`` are unlinked.  Disk is bounded
    by ``max_segments x segment_bytes`` plus one sketch file; history
    survives in the sketches after the raw rows age out.
  * **Restart warm-start.**  On boot the corpus loads ``sketch.json``
    and replays only the raw segments NEWER than the watermark (so a
    crash between rotation never double-counts), then seeds the
    autopilot's model table (``Autopilot.warm_start``) — a restarted
    engine prices previously-seen keys before its first dispatch.
  * **``GET /corpus``** exposes the accumulated corpus per engine, and
    the gateway federates the per-replica documents into one fleet view
    (gateway/fleet.py) — the dataset ROADMAP item 4 trains against.

The corpus is per-process: point each engine process at its own
``SELDON_TPU_CORPUS_DIR`` (unset = disabled; ``SELDON_TPU_CORPUS=0`` is
the kill switch with the directory still configured).  All file I/O
happens on the drainer thread under the corpus lock; an I/O error
disables the corpus for the process (counted, logged once) rather than
wedging the drain behind a sick disk."""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["CORPUS", "PerfCorpus", "corpus_enabled"]

logger = logging.getLogger(__name__)

_SKETCH_FILE = "sketch.json"
_SEGMENT_PREFIX = "corpus-"
_SEGMENT_SUFFIX = ".jsonl"
#: per-key recent-wall sample ring — enough for stable p50/p90 reads
#: while keeping sketch.json O(keys), not O(dispatches)
_SAMPLE_CAP = 64


def corpus_enabled() -> bool:
    """On only when a directory is configured AND the kill switch is not
    thrown — the same off-unless-configured posture as the audit log."""
    if os.environ.get("SELDON_TPU_CORPUS", "1") == "0":
        return False
    return bool(os.environ.get("SELDON_TPU_CORPUS_DIR", "").strip())


def _env_int(name: str, default: int) -> int:
    try:
        return int(float(os.environ.get(name, "") or default))
    except ValueError:
        return default


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


class _KeySketch:
    """Compacted history of one (executable, pad-bucket) key: lifetime
    count, a bounded ring of recent measured walls (the quantile
    sketch), last static cost features and a tier census."""

    __slots__ = ("key", "n", "samples", "ring_pos", "pad_bucket",
                 "flops", "bytes_accessed", "tiers", "last_s", "last_ts")

    def __init__(self, key: str):
        self.key = key
        self.n = 0
        self.samples: List[float] = []
        self.ring_pos = 0
        self.pad_bucket = 0
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.tiers: Dict[str, int] = {}
        self.last_s = 0.0
        self.last_ts = 0.0

    def fold(self, pad_bucket: int, tier: str, flops: float,
             bytes_accessed: float, wall_s: float, ts: float) -> None:
        self.n += 1
        if len(self.samples) < _SAMPLE_CAP:
            self.samples.append(wall_s)
        else:
            self.samples[self.ring_pos] = wall_s
            self.ring_pos = (self.ring_pos + 1) % _SAMPLE_CAP
        if pad_bucket:
            self.pad_bucket = pad_bucket
        if flops:
            self.flops = flops
        if bytes_accessed:
            self.bytes_accessed = bytes_accessed
        if tier and len(self.tiers) < 8:
            self.tiers[tier] = self.tiers.get(tier, 0) + 1
        elif tier in self.tiers:
            self.tiers[tier] += 1
        self.last_s = wall_s
        self.last_ts = ts

    def quantiles(self) -> Dict[str, float]:
        vals = sorted(self.samples)
        return {
            "p50": _quantile(vals, 0.50),
            "p90": _quantile(vals, 0.90),
            "p99": _quantile(vals, 0.99),
        }

    def spread_s(self) -> float:
        """Median absolute deviation around p50 — the warm-start seed
        for the autopilot's EWMA scale estimate."""
        vals = sorted(self.samples)
        if not vals:
            return 0.0
        p50 = _quantile(vals, 0.50)
        dev = sorted(abs(v - p50) for v in vals)
        return _quantile(dev, 0.50)

    def to_json_dict(self) -> Dict[str, Any]:
        return {
            "n": self.n,
            "samples": [round(s, 9) for s in self.samples],
            "pad_bucket": self.pad_bucket,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "tiers": dict(self.tiers),
            "last_s": self.last_s,
            "last_ts": self.last_ts,
        }

    @classmethod
    def from_json_dict(cls, key: str, doc: Dict[str, Any]) -> "_KeySketch":
        sk = cls(key)
        sk.n = int(doc.get("n", 0))
        sk.samples = [float(s) for s in doc.get("samples", [])][:_SAMPLE_CAP]
        sk.pad_bucket = int(doc.get("pad_bucket", 0))
        sk.flops = float(doc.get("flops", 0.0))
        sk.bytes_accessed = float(doc.get("bytes_accessed", 0.0))
        sk.tiers = {
            str(k): int(v) for k, v in (doc.get("tiers") or {}).items()
        }
        sk.last_s = float(doc.get("last_s", 0.0))
        sk.last_ts = float(doc.get("last_ts", 0.0))
        return sk


class PerfCorpus:
    """Process-global durable dispatch ledger.  ``record`` is called
    ONLY from the spine's drainer fold (already serialized under the
    drain lock); loads, documents and gauge publishes take the corpus
    lock so any thread can read."""

    #: bounded key census — an exploding shape set must not grow the
    #: sketch file without limit; keys beyond the cap are dropped
    #: (counted) exactly like the autopilot's MAX_KEYS rule
    MAX_KEYS = 512

    def __init__(self):
        self._lock = threading.RLock()
        self.reconfigure()

    # -- configuration -----------------------------------------------------

    def reconfigure(self) -> None:
        """Re-read the environment and drop all in-memory state (tests
        and the corpus demo flip env between 'processes'; production
        calls this once via import)."""
        with self._lock:
            fh = getattr(self, "_fh", None)
            if fh is not None:
                try:
                    fh.close()
                except Exception:  # noqa: BLE001
                    pass
            self.dir = os.environ.get(
                "SELDON_TPU_CORPUS_DIR", "").strip()
            self.segment_bytes = max(
                _env_int("SELDON_TPU_CORPUS_SEGMENT_BYTES", 262144), 4096)
            self.max_segments = max(
                _env_int("SELDON_TPU_CORPUS_MAX_SEGMENTS", 4), 1)
            self._sketches: Dict[str, _KeySketch] = {}
            self._fh = None
            self._seq = 0
            self._active_bytes = 0
            self._compacted_through = 0
            self._loaded = False
            self._warmed = False
            self._broken = False
            self.rows_total = 0
            self.rotations = 0
            self.keys_capped = 0
            self.io_errors = 0
            self.skipped_rows = 0
            self.warm_keys = 0

    @property
    def enabled(self) -> bool:
        return corpus_enabled() and not self._broken

    # -- disk layout -------------------------------------------------------

    def _segment_path(self, seq: int) -> str:
        return os.path.join(
            self.dir, f"{_SEGMENT_PREFIX}{seq:06d}{_SEGMENT_SUFFIX}")

    def _segment_seqs(self) -> List[int]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        seqs = []
        for name in names:
            if (name.startswith(_SEGMENT_PREFIX)
                    and name.endswith(_SEGMENT_SUFFIX)):
                try:
                    seqs.append(int(
                        name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]))
                except ValueError:
                    continue
        return sorted(seqs)

    def disk_bytes(self) -> int:
        total = 0
        for seq in self._segment_seqs():
            try:
                total += os.path.getsize(self._segment_path(seq))
            except OSError:
                pass
        try:
            total += os.path.getsize(os.path.join(self.dir, _SKETCH_FILE))
        except OSError:
            pass
        return total

    def _fail(self, what: str, exc: Exception) -> None:
        """One sick disk must not wedge the drain: disable and count."""
        self.io_errors += 1
        if not self._broken:
            logger.warning("perf corpus disabled (%s): %s", what, exc)
        self._broken = True

    # -- load / replay -----------------------------------------------------

    def _ensure_loaded(self) -> bool:
        """Load sketch.json + replay post-watermark segments once per
        (re)configuration.  Malformed lines and a corrupt sketch file
        are skipped (counted) — the corrupt-corpus runbook in
        docs/operations.md is 'delete the file, lose only history'."""
        if self._loaded:
            return True
        if not self.enabled:
            return False
        try:
            os.makedirs(self.dir, exist_ok=True)
        except OSError as exc:
            self._fail("mkdir", exc)
            return False
        sketch_path = os.path.join(self.dir, _SKETCH_FILE)
        if os.path.exists(sketch_path):
            try:
                with open(sketch_path) as f:
                    doc = json.load(f)
                self._compacted_through = int(
                    doc.get("compacted_through", 0))
                for key, ent in (doc.get("keys") or {}).items():
                    if len(self._sketches) >= self.MAX_KEYS:
                        break
                    self._sketches[key] = _KeySketch.from_json_dict(
                        key, ent)
            except Exception:  # noqa: BLE001 - corrupt sketch = no history
                self.skipped_rows += 1
                self._compacted_through = 0
                self._sketches = {}
        seqs = self._segment_seqs()
        for seq in seqs:
            if seq <= self._compacted_through:
                continue
            try:
                with open(self._segment_path(seq)) as f:
                    for line in f:
                        self._replay_line(line)
            except OSError:
                continue
        self._seq = (seqs[-1] + 1) if seqs else 1
        try:
            self._fh = open(self._segment_path(self._seq), "a")
            self._active_bytes = self._fh.tell()
        except OSError as exc:
            self._fail("open segment", exc)
            return False
        self._loaded = True
        return True

    def _replay_line(self, line: str) -> None:
        line = line.strip()
        if not line:
            return
        try:
            row = json.loads(line)
            key = row["k"]
        except Exception:  # noqa: BLE001 - torn tail line after a crash
            self.skipped_rows += 1
            return
        sk = self._sketch_for(key)
        if sk is None:
            return
        sk.fold(int(row.get("pb", 0)), str(row.get("tier", "")),
                float(row.get("fl", 0.0)), float(row.get("by", 0.0)),
                float(row.get("w", 0.0)), float(row.get("ts", 0.0)))

    def _sketch_for(self, key: str) -> Optional[_KeySketch]:
        sk = self._sketches.get(key)
        if sk is None:
            if len(self._sketches) >= self.MAX_KEYS:
                self.keys_capped += 1
                return None
            sk = self._sketches[key] = _KeySketch(key)
        return sk

    # -- the write path (drainer fold only) --------------------------------

    def record(self, key: str, *, pad_bucket: int, tier: str,
               wall_s: float, rows: int,
               features: Optional[Dict[str, float]] = None) -> bool:
        """Append one dispatch row and fold it into the key's sketch.
        Called from the spine drainer's HOP_DISPATCH fold — never from a
        serving thread — so the file write is off-path by construction."""
        if not key or wall_s <= 0:
            return False
        with self._lock:
            if not self._ensure_loaded():
                return False
            ts = time.time()
            flops = float((features or {}).get("flops", 0.0) or 0.0)
            nbytes = float(
                (features or {}).get("bytes_accessed", 0.0) or 0.0)
            row = {
                "k": key, "pb": int(pad_bucket), "tier": tier or "",
                "fl": flops, "by": nbytes, "r": int(rows),
                "w": round(float(wall_s), 9), "ts": round(ts, 3),
            }
            try:
                line = json.dumps(row, separators=(",", ":")) + "\n"
                self._fh.write(line)
                # flush the userspace buffer (no fsync): a crash loses at
                # most the OS page cache, and a sibling reader (restart
                # replay, tests) sees every appended row.  Off-path — the
                # drainer is the only writer
                self._fh.flush()
                self._active_bytes += len(line)
            except Exception as exc:  # noqa: BLE001
                self._fail("append", exc)
                return False
            self.rows_total += 1
            sk = self._sketch_for(key)
            if sk is not None:
                sk.fold(int(pad_bucket), tier or "", flops, nbytes,
                        float(wall_s), ts)
            if self._active_bytes >= self.segment_bytes:
                self._rotate()
            return True

    def _rotate(self) -> None:
        """Close the active segment, persist the sketches with the
        watermark advanced past it, and drop raw segments beyond the
        retention window — this is the ONLY place disk shrinks, and it
        always persists before it prunes (no row is ever only in a file
        that just got unlinked)."""
        try:
            self._fh.flush()
            self._fh.close()
        except Exception:  # noqa: BLE001
            pass
        self._compacted_through = self._seq
        self._persist_sketches()
        seqs = self._segment_seqs()
        for seq in seqs[:-self.max_segments] if (
                len(seqs) > self.max_segments) else []:
            try:
                os.unlink(self._segment_path(seq))
            except OSError:
                pass
        self._seq += 1
        try:
            self._fh = open(self._segment_path(self._seq), "a")
            self._active_bytes = 0
            self.rotations += 1
        except OSError as exc:
            self._fail("rotate", exc)

    def _persist_sketches(self) -> None:
        """Atomic tmp+rename write of sketch.json."""
        path = os.path.join(self.dir, _SKETCH_FILE)
        tmp = path + ".tmp"
        doc = {
            "version": 1,
            "compacted_through": self._compacted_through,
            "keys": {
                k: sk.to_json_dict() for k, sk in self._sketches.items()
            },
        }
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, separators=(",", ":"))
            os.replace(tmp, path)
        except OSError as exc:
            self._fail("persist sketches", exc)

    def flush(self) -> None:
        """Force a rotation (tests + the corpus demo's 'clean shutdown'):
        everything in memory reaches sketch.json."""
        with self._lock:
            if self._loaded and self._fh is not None:
                self._rotate()

    # -- restart warm-start ------------------------------------------------

    def warm_start_autopilot(self) -> int:
        """Seed the process-global autopilot from the corpus — called
        once per process at engine boot (idempotent; re-armed by
        ``reconfigure``).  Returns the number of keys seeded."""
        with self._lock:
            if self._warmed:
                return self.warm_keys
            self._warmed = True
            if not self._ensure_loaded():
                return 0
            entries = []
            for sk in self._sketches.values():
                if sk.n <= 0 or not sk.samples:
                    continue
                q = sk.quantiles()
                entries.append({
                    "key": sk.key,
                    "n": sk.n,
                    "est_s": q["p50"],
                    "scale_s": sk.spread_s(),
                    "last_s": sk.last_s,
                })
            if not entries:
                return 0
        from seldon_core_tpu.runtime.autopilot import AUTOPILOT

        seeded = AUTOPILOT.warm_start(entries)
        with self._lock:
            self.warm_keys = seeded
        return seeded

    # -- surfaces ----------------------------------------------------------

    def publish_gauges(self) -> None:
        """seldon_tpu_corpus_{rows,bytes,warm_keys} — called from the
        spine's throttled gauge refresh, never per-row."""
        from seldon_core_tpu.utils.telemetry import RECORDER

        with self._lock:
            if not self.enabled or not self._loaded:
                return
            RECORDER.set_corpus(
                rows=self.rows_total,
                disk_bytes=self.disk_bytes(),
                warm_keys=self.warm_keys,
            )

    def document(self) -> Dict[str, Any]:
        """The ``GET /corpus`` body: knobs, disk layout, and the per-key
        sketch table (the training substrate for ROADMAP item 4)."""
        with self._lock:
            loaded = self._ensure_loaded()
            keys: List[Dict[str, Any]] = []
            for sk in self._sketches.values():
                q = sk.quantiles()
                keys.append({
                    "key": sk.key,
                    "n": sk.n,
                    "pad_bucket": sk.pad_bucket,
                    "p50_ms": round(q["p50"] * 1e3, 4),
                    "p90_ms": round(q["p90"] * 1e3, 4),
                    "p99_ms": round(q["p99"] * 1e3, 4),
                    "spread_ms": round(sk.spread_s() * 1e3, 4),
                    "flops": sk.flops,
                    "bytes_accessed": sk.bytes_accessed,
                    "tiers": dict(sk.tiers),
                    "last_ms": round(sk.last_s * 1e3, 4),
                    "last_ts": round(sk.last_ts, 3),
                })
            keys.sort(key=lambda r: r["n"], reverse=True)
            segments = []
            if loaded:
                for seq in self._segment_seqs():
                    try:
                        size = os.path.getsize(self._segment_path(seq))
                    except OSError:
                        size = 0
                    segments.append({"seq": seq, "bytes": size})
            return {
                "enabled": self.enabled,
                "dir": self.dir or None,
                "knobs": {
                    "kill_switch": "SELDON_TPU_CORPUS",
                    "dir": "SELDON_TPU_CORPUS_DIR",
                    "segment_bytes": self.segment_bytes,
                    "max_segments": self.max_segments,
                    "max_keys": self.MAX_KEYS,
                },
                "rows_total": self.rows_total,
                "disk_bytes": self.disk_bytes() if loaded else 0,
                "segments": segments,
                "compacted_through": self._compacted_through,
                "rotations": self.rotations,
                "warm_keys": self.warm_keys,
                "keys_capped": self.keys_capped,
                "skipped_rows": self.skipped_rows,
                "io_errors": self.io_errors,
                "keys": keys,
            }

    def snapshot(self) -> Dict[str, Any]:
        """Compact health block — the full table lives on /corpus."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "rows_total": self.rows_total,
                "keys": len(self._sketches),
                "warm_keys": self.warm_keys,
            }


CORPUS = PerfCorpus()
