"""Generated protobuf bindings (protoc --python_out from proto/prediction.proto); regenerate via make proto."""
