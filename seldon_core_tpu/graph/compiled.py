"""Compiled-graph executor — the whole inference graph as ONE XLA program.

This is the TPU-native answer to the reference engine's per-node microservice
hops (engine PredictiveUnitBean.java:69-124 fans out over HTTP/gRPC with
per-call JSON marshalling): when every graph node is an in-process *pure*
JAX unit, the recursive evaluation

    transform_input -> route -> children -> aggregate -> transform_output

is traced once into a single jitted function over an explicit state pytree.
ROUTER branch choice becomes ``lax.switch`` (one branch executes on device,
no host round-trip), COMBINER fan-out becomes a stacked evaluation XLA is
free to fuse/parallelise, and unit state transitions (bandit counters, PRNG
keys, streaming statistics) thread functionally through the program.  The
feedback pass compiles the same way: ``meta.routing`` replays as traced
branch gates (``lax.cond``), so online learning updates also run on-device.

Structure conventions inside the traced program:
  * ``states``  — dict node-name -> state pytree, threaded through every call;
    all ``lax.switch`` branches return the full dict so structures match.
  * ``routing`` — dict router-name -> int32; routers not on the executed path
    report the sentinel ``NOT_ROUTED`` (-2), filtered out host-side (the
    reference only records visited routers in ``meta.routing``).
  * ``tags``    — flat dict tag-name -> traced value, later writers win
    (the reference's tag-merge rule, engine PredictiveUnitBean.java:252-264).
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from seldon_core_tpu.messages import Meta, SeldonMessage, Status
from seldon_core_tpu.graph.interpreter import (
    effective_type,
    methods_for,
    pythonize_tags,
    unit_rngs,
)
from seldon_core_tpu.graph.spec import (
    GraphSpecError,
    PredictiveUnit,
    PredictorSpec,
    UnitMethod,
    UnitType,
    params_to_kwargs,
)
from seldon_core_tpu.graph.units import (
    Unit,
    UNIT_REGISTRY,
    normalize_output,
    resolve_unit_class,
)

__all__ = ["CompiledGraph", "NOT_ROUTED", "build_units"]

# sentinel for "router not on executed path" — far outside any plausible
# branch index so a buggy router's negative return can't collide with it
NOT_ROUTED = -(2**30)


def _set_state(states: Dict[str, Any], name: str, new_state) -> Dict[str, Any]:
    """Functional state write.  The states-dict *structure* must be stable
    across traced branches, so a unit may only write state if it declared one
    via ``init_state`` (its key already exists)."""
    if new_state is None:
        return states
    if name not in states:
        raise GraphSpecError(
            f"unit {name!r} returned a state update but init_state() was None; "
            f"declare initial state so the compiled program can thread it"
        )
    out = dict(states)
    out[name] = new_state
    return out


def build_units(predictor: PredictorSpec, rng=None) -> Dict[str, Unit]:
    """Instantiate a pure in-process Unit for every graph node that needs one.
    Raises if any node is remote or impure — such graphs must use the host
    interpreter."""
    units: Dict[str, Unit] = {}
    comp_map = predictor.component_map()
    for node in predictor.graph.walk():
        unit: Optional[Unit] = None
        if node.implementation.value in UNIT_REGISTRY:
            unit = UNIT_REGISTRY[node.implementation.value](
                **params_to_kwargs(node.parameters)
            )
        else:
            binding = comp_map.get(node.name)
            if binding is None or binding.runtime != "inprocess":
                raise GraphSpecError(
                    f"node {node.name!r} is not an in-process unit; compiled mode "
                    f"requires every node in-process (use the host interpreter)"
                )
            from seldon_core_tpu.graph.units import instantiate_bound_unit

            unit = instantiate_bound_unit(binding, node)
        if not unit.pure:
            raise GraphSpecError(
                f"unit {node.name!r} ({type(unit).__name__}) is not pure; compiled "
                f"mode requires traceable units"
            )
        units[node.name] = unit
    return units


def _routers_in(node: PredictiveUnit) -> List[str]:
    return [
        u.name for u in node.walk() if UnitMethod.ROUTE in methods_for(u) and u.children
    ]


class CompiledGraph:
    """Compile a PredictorSpec's graph into jitted predict/feedback programs.

    Usage::

        cg = CompiledGraph(predictor)
        y, routing, tags = cg.predict_arrays(x)     # updates cg.states
        cg.feedback_arrays(x, routing, reward)      # on-device state update
        resp = cg.predict(msg)                      # SeldonMessage in/out
    """

    def __init__(self, predictor: PredictorSpec, rng=None, mesh=None):
        self.predictor = predictor
        self.units = build_units(predictor, rng)
        rngs = unit_rngs(list(self.units), rng)
        self.states: Dict[str, Any] = {}
        for name, unit in sorted(self.units.items()):
            st = unit.init_state(rngs[name])
            if st is not None:
                self.states[name] = st
        self._all_routers = _routers_in(predictor.graph)
        self._router_children = {
            u.name: len(u.children)
            for u in predictor.graph.walk()
            if u.name in self._all_routers
        }
        self.mesh = mesh

        predict_fn = self._build_predict(predictor.graph)

        def run(states, X):
            y, states2, routing, tags = predict_fn(states, X)
            routing = {
                r: routing.get(r, jnp.int32(NOT_ROUTED)) for r in self._all_routers
            }
            return y, states2, routing, tags

        feedback_fn = self._build_feedback(predictor.graph)

        def run_fb(states, X, routing, reward, truth):
            return feedback_fn(states, X, routing, reward, truth)

        #: pure (states, X) -> (Y, states', routing, tags); re-jittable by
        #: callers that want custom shardings/donation
        self.predict_fn = run
        self.feedback_fn = run_fb
        self._jit_predict = jax.jit(run)
        self._jit_feedback = jax.jit(run_fb)
        # performance observatory (utils/perf.py): per-shape AOT-compiled
        # executables, keyed by executable_key.  The explicit
        # lower().compile() path measures the compile wall time and owns
        # the executable whose cost_analysis() yields the static FLOP /
        # byte features — None marks a shape where AOT failed and
        # dispatch stays on _jit_predict
        self._aot: Dict[str, Optional[Any]] = {}
        self._aot_building: set = set()
        self._aot_lock = threading.Lock()
        # bounded like the observatory's executable table: an exploding
        # shape set (including adversarial bad widths, which cache a
        # failed None) must not grow memory — past the cap novel shapes
        # ride the jit path uncaptured
        self._aot_cap = 128

    # ------------------------------------------------------------------
    # trace-time builders
    # ------------------------------------------------------------------

    def _build_predict(
        self, node: PredictiveUnit
    ) -> Callable[[Dict[str, Any], Any], Tuple[Any, Dict, Dict, Dict]]:
        unit = self.units[node.name]
        methods = methods_for(node)
        is_model = effective_type(node) is UnitType.MODEL
        child_fns = [self._build_predict(c) for c in node.children]
        name = node.name
        static_tags = dict(unit.static_tags or {})

        def fn(states, X):
            routing: Dict[str, Any] = {}
            tags: Dict[str, Any] = dict(static_tags)
            y = X
            if UnitMethod.TRANSFORM_INPUT in methods:
                m = unit.predict if is_model else unit.transform_input
                out = m(states.get(name), y)
                y, new_state, t = normalize_output(out, states.get(name))
                states = _set_state(states, name, new_state)
                tags.update(t)

            if node.children:
                if UnitMethod.ROUTE in methods:
                    out = unit.route(states.get(name), y)
                    branch, new_state, _ = normalize_output(out, states.get(name))
                    states = _set_state(states, name, new_state)
                    # record the RAW branch (predict_arrays raises post-hoc on
                    # out-of-range / broadcast values — XLA can't raise
                    # mid-program); clamp only the switch index
                    raw_branch = jnp.asarray(branch, dtype=jnp.int32)
                    branch = jnp.clip(raw_branch, 0, len(child_fns) - 1)
                    sub_routers = sorted(
                        {r for c in node.children for r in _routers_in(c)}
                    )

                    def make_branch(cf):
                        def bf(operand):
                            states_, x_ = operand
                            yc, s2, r, t = cf(states_, x_)
                            full_r = {
                                rn: r.get(rn, jnp.int32(NOT_ROUTED))
                                for rn in sub_routers
                            }
                            return yc, s2, full_r, t
                        return bf

                    try:
                        y, states, child_routing, child_tags = jax.lax.switch(
                            branch,
                            [make_branch(cf) for cf in child_fns],
                            (states, y),
                        )
                    except TypeError as e:
                        if "structure" in str(e) or "pytree" in str(e):
                            raise GraphSpecError(
                                f"router {name!r}: children return mismatched "
                                f"structures (shapes/tags must agree across "
                                f"branches for compiled routing): {e}"
                            ) from e
                        raise GraphSpecError(f"in subgraph of {name!r}: {e}") from e
                    routing[name] = raw_branch
                    routing.update(child_routing)
                    tags.update(child_tags)
                else:
                    ys = []
                    for cf in child_fns:
                        yc, states, r, t = cf(states, y)
                        ys.append(yc)
                        routing.update(r)
                        tags.update(t)
                    if UnitMethod.AGGREGATE in methods:
                        stacked = jnp.stack(ys, axis=0)
                        out = unit.aggregate(states.get(name), stacked)
                        y, new_state, t = normalize_output(out, states.get(name))
                        states = _set_state(states, name, new_state)
                        tags.update(t)
                    elif len(ys) == 1:
                        y = ys[0]
                    else:
                        raise GraphSpecError(
                            f"node {name!r} has {len(ys)} children but no "
                            f"AGGREGATE method to merge them"
                        )

            if UnitMethod.TRANSFORM_OUTPUT in methods:
                out = unit.transform_output(states.get(name), y)
                y, new_state, t = normalize_output(out, states.get(name))
                states = _set_state(states, name, new_state)
                tags.update(t)
            return y, states, routing, tags

        return fn

    def _build_feedback(self, node: PredictiveUnit):
        unit = self.units[node.name]
        methods = methods_for(node)
        child_fbs = [self._build_feedback(c) for c in node.children]
        name = node.name
        is_router = UnitMethod.ROUTE in methods and bool(node.children)

        def fn(states, X, routing, reward, truth):
            if UnitMethod.SEND_FEEDBACK in methods:
                branch = routing.get(name, jnp.int32(-1))
                new_state = unit.send_feedback(
                    states.get(name), X, branch, reward, truth
                )
                states = _set_state(states, name, new_state)
            for idx, cfb in enumerate(child_fbs):
                if is_router:
                    branch = routing.get(name, jnp.int32(-1))
                    selected = jnp.logical_or(branch == idx, branch == -1)
                    states = jax.lax.cond(
                        selected,
                        lambda s: cfb(s, X, routing, reward, truth),
                        lambda s: s,
                        states,
                    )
                else:
                    states = cfb(states, X, routing, reward, truth)
            return states

        return fn

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------

    def executable_key(self, X) -> str:
        """Stable per-shape executable identity (perf observatory key) —
        reads only ``.shape``/``.dtype`` metadata, so naming a device
        array's executable never forces a device-to-host transfer."""
        from seldon_core_tpu.utils.perf import executable_key

        dtype = getattr(X, "dtype", None)
        if dtype is None:  # plain lists etc. — cold paths only
            dtype = np.asarray(X).dtype
        return executable_key("predict", np.shape(X), dtype)

    def _ensure_executable(self, X):
        """AOT-compile this shape once (measuring true compile wall time
        and capturing ``compile().cost_analysis()`` features into the
        observatory); returns (key, executable-or-None).  None means a
        concurrent build is in flight or AOT failed — the caller
        dispatches through ``_jit_predict`` with identical semantics."""
        from seldon_core_tpu.utils.perf import OBSERVATORY

        if not OBSERVATORY.enabled:
            return "", None
        key = self.executable_key(X)
        return key, self._aot_build(key, self._jit_predict, (self.states, X))

    def _aot_build(self, key: str, jitted, args: tuple):
        """The shared per-shape AOT path: ``jitted(*args)`` lowered and
        compiled once under ``key``, compile wall + cost features folded
        into the perf observatory, result cached in the bounded ``_aot``
        table.  Shared by this executor and the fused executor
        (graph/fuse.py) so both ride one compile-cache discipline."""
        from seldon_core_tpu.utils.perf import (
            OBSERVATORY,
            extract_cost_features,
        )

        with self._aot_lock:
            if key in self._aot:
                return self._aot[key]
            if key in self._aot_building or len(self._aot) >= self._aot_cap:
                # first dispatch of this shape is mid-compile in another
                # thread (ride the jit path rather than wait), or the
                # bounded cache is full (novel shapes go uncaptured)
                return None
            self._aot_building.add(key)
        compiled = None
        features = None
        compile_s = None
        try:
            t0 = time.perf_counter()
            lowered = jitted.lower(*args)
            compiled = lowered.compile()
            compile_s = time.perf_counter() - t0
            try:
                features = extract_cost_features(compiled.cost_analysis())
            except Exception:  # noqa: BLE001 - backend without the API
                features = None
            if features is None:
                # pre-optimization HLO features beat no features at all
                try:
                    features = extract_cost_features(lowered.cost_analysis())
                except Exception:  # noqa: BLE001
                    features = None
        except Exception:  # noqa: BLE001 - AOT unsupported: jit path serves
            compiled = None
        finally:
            with self._aot_lock:
                self._aot[key] = compiled
                self._aot_building.discard(key)
        OBSERVATORY.record_compile(key, features, compile_s)
        return compiled

    def predict_arrays(
        self, X, update_states=True
    ) -> Tuple[Any, Dict[str, int], Dict[str, Any]]:
        """Run the compiled graph; returns (Y, routing, tags) and advances the
        held unit states.

        ``update_states=False`` skips the state write-back: when no unit
        updates state on predict the returned states equal the inputs, and
        skipping the read-modify-write lets the engine pipeline several
        in-flight dispatches without a stale write-back clobbering a
        concurrent feedback update.  A callable is evaluated AFTER the
        device round-trip, letting the engine veto a write-back whose
        request already timed out (the client saw a 504 — a late state
        update would double-apply on retry)."""
        X = jnp.asarray(X)
        key, executable = self._ensure_executable(X)
        if executable is not None:
            try:
                y, new_states, routing, tags = executable(self.states, X)
            except Exception:  # noqa: BLE001 - aval drift (e.g. weak-typed
                # state leaves strengthened by an update): permanently fall
                # back to the jit path for this shape, same program
                with self._aot_lock:
                    self._aot[key] = None
                y, new_states, routing, tags = self._jit_predict(
                    self.states, X
                )
        else:
            y, new_states, routing, tags = self._jit_predict(self.states, X)
        routing_py = {
            k: int(v) for k, v in routing.items() if int(v) != NOT_ROUTED
        }
        # compiled routing cannot broadcast (-1) or raise mid-program; surface
        # invalid branches here instead of returning clamped garbage (the host
        # interpreter raises the same error inline,
        # interpreter.GraphExecutor._get_output)
        for r, v in routing_py.items():
            if v < 0 or v >= self._router_children[r]:
                raise GraphSpecError(
                    f"router {r!r} chose branch {v} but has "
                    f"{self._router_children[r]} children (broadcast routing is "
                    f"host-mode only)"
                )
        if callable(update_states):
            # the gate decides based on wall time AFTER the device work
            # finished — JAX dispatch is async, so without forcing here the
            # gate would fire microseconds after enqueue and always pass
            jax.block_until_ready(new_states)
            do_update = update_states()
        else:
            do_update = update_states
        if do_update:
            self.states = new_states
        return y, routing_py, tags

    def feedback_arrays(
        self,
        X,
        routing: Dict[str, int],
        reward: float,
        truth=None,
    ) -> None:
        """On-device feedback/state update, replaying the recorded routing."""
        routing_traced = {
            r: jnp.int32(routing.get(r, -1)) for r in self._all_routers
        }
        if X is not None:
            X = jnp.asarray(X)
        self.states = self._jit_feedback(
            self.states, X, routing_traced, jnp.float32(reward), truth
        )

    # -- SeldonMessage API (drop-in for GraphExecutor at the edge) ----------

    def predict(self, msg: SeldonMessage) -> SeldonMessage:
        # 1-D wire payloads mean a single sample; units assume a leading
        # batch axis (same normalisation as the micro-batched engine path)
        y, routing, tags = self.predict_arrays(
            jnp.atleast_2d(jnp.asarray(msg.array()))
        )
        leaf_names = self._output_names(self.predictor.graph, routing)
        resp = msg.with_array(y, names=leaf_names)
        resp.meta = Meta(
            puid=msg.meta.puid,
            tags={**msg.meta.tags, **pythonize_tags(tags)},
            routing={**msg.meta.routing, **routing},
            requestPath=dict(msg.meta.requestPath),
        )
        resp.status = Status()
        return resp

    def _output_names(
        self, node: PredictiveUnit, routing: Dict[str, int]
    ) -> Optional[list]:
        """Names of the unit that actually produced the output, following the
        recorded routing — matches the host interpreter, where each response
        carries the names set by the last unit on the executed path."""
        unit = self.units[node.name]
        methods = methods_for(node)
        names: Optional[list] = None
        if UnitMethod.TRANSFORM_INPUT in methods and unit.class_names is not None:
            names = list(unit.class_names)
        if node.children:
            if UnitMethod.ROUTE in methods and node.name in routing:
                child = node.children[routing[node.name]]
                names = self._output_names(child, routing) or names
            elif UnitMethod.AGGREGATE in methods:
                if unit.class_names is not None:
                    names = list(unit.class_names)
                else:
                    names = self._output_names(node.children[0], routing) or names
            else:
                names = self._output_names(node.children[0], routing) or names
        if UnitMethod.TRANSFORM_OUTPUT in methods and unit.class_names is not None:
            names = list(unit.class_names)
        return names

    # -- compilation introspection ------------------------------------------

    def lower_text(self, X) -> str:
        """StableHLO of the predict program (debugging/benchmark evidence)."""
        return self._jit_predict.lower(self.states, jnp.asarray(X)).as_text()
