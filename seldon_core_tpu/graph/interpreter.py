"""Host-mode graph interpreter — the engine's request loop.

Async recursive evaluation of the inference graph with exactly the reference
engine's semantics (engine PredictiveUnitBean.java:58-168):

    transform_input -> route (-1 = broadcast) -> children concurrently
        -> aggregate -> transform_output

with per-node routing recorded into ``meta.routing``, tags merged across
nodes (later writers win), and the feedback pass replaying ``meta.routing``
so only the branch that served a request is trained.

This interpreter is the *host* path: any node may be an in-process JAX unit
or a remote microservice (a ``NodeRuntime``).  When every node is in-process
and pure, use ``graph.compiled.CompiledGraph`` instead, which lowers the whole
recursion into one XLA program — this module is then only the fallback for
graphs that genuinely span processes.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from seldon_core_tpu.messages import (
    DeadlineExceededError,
    Feedback,
    Meta,
    SeldonMessage,
    SeldonMessageError,
    Status,
)
from seldon_core_tpu.runtime.resilience import current_deadline
from seldon_core_tpu.utils.quality import QUALITY
from seldon_core_tpu.utils.telemetry import RECORDER
from seldon_core_tpu.graph.spec import (
    ComponentBinding,
    GraphSpecError,
    PredictiveUnit,
    PredictorSpec,
    UnitImplementation,
    UnitMethod,
    UnitType,
)
from seldon_core_tpu.graph.units import (
    Unit,
    UNIT_REGISTRY,
    normalize_output,
    resolve_unit_class,
)
from seldon_core_tpu.graph.spec import params_to_kwargs

__all__ = [
    "NodeRuntime",
    "InProcessNodeRuntime",
    "GraphExecutor",
    "methods_for",
    "unit_rngs",
]


def unit_rngs(names, rng=None):
    """Deterministic per-unit PRNG keys, shared convention between the host
    interpreter and the compiled executor so routing decisions are identical
    in both modes for a given seed.

    Keys are derived from the unit's NAME (crc32 fold), not its position
    in the sorted name list: a unit's state must not depend on which
    OTHER units share its graph, or a sharded node engine
    (graph/sharding.py node_subspec — one leaf served standalone) would
    train different weights than the same leaf inside the collapsed
    engine, turning a pure topology change into a silent numerics
    change."""
    import zlib

    import jax

    if rng is None:
        rng = jax.random.key(0)
    return {
        name: jax.random.fold_in(rng, zlib.crc32(name.encode()))
        for name in names
    }


# ---------------------------------------------------------------------------
# Method dispatch table (engine PredictorConfigBean.java:33-82)
# ---------------------------------------------------------------------------

_TYPE_METHODS = {
    UnitType.MODEL: [UnitMethod.TRANSFORM_INPUT],
    UnitType.ROUTER: [UnitMethod.ROUTE, UnitMethod.SEND_FEEDBACK],
    UnitType.COMBINER: [UnitMethod.AGGREGATE],
    UnitType.TRANSFORMER: [UnitMethod.TRANSFORM_INPUT],
    UnitType.OUTPUT_TRANSFORMER: [UnitMethod.TRANSFORM_OUTPUT],
}

_IMPL_TYPES = {
    UnitImplementation.SIMPLE_MODEL: UnitType.MODEL,
    UnitImplementation.SIMPLE_ROUTER: UnitType.ROUTER,
    UnitImplementation.RANDOM_ABTEST: UnitType.ROUTER,
    UnitImplementation.AVERAGE_COMBINER: UnitType.COMBINER,
}


def effective_type(node: PredictiveUnit) -> Optional[UnitType]:
    if node.type is not None:
        return node.type
    return _IMPL_TYPES.get(node.implementation)


def methods_for(node: PredictiveUnit) -> List[UnitMethod]:
    """Explicit ``methods`` win; otherwise the type's default set."""
    if node.methods is not None:
        return list(node.methods)
    t = effective_type(node)
    return list(_TYPE_METHODS.get(t, []))


def pythonize_tags(tags: Dict[str, Any]) -> Dict[str, Any]:
    """Convert traced/array tag values to JSON-safe python values."""
    out: Dict[str, Any] = {}
    for k, v in (tags or {}).items():
        a = np.asarray(v)
        if a.ndim == 0:
            out[k] = a.item()
        else:
            out[k] = a.tolist()
    return out


# ---------------------------------------------------------------------------
# Node runtimes
# ---------------------------------------------------------------------------


class NodeRuntime:
    """Transport-agnostic node interface: what the engine's
    ``InternalPredictionService`` is to the reference (per-node outbound
    calls, engine InternalPredictionService.java:132-203)."""

    async def predict(self, msg: SeldonMessage) -> SeldonMessage:
        raise NotImplementedError

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        raise NotImplementedError

    async def transform_output(self, msg: SeldonMessage) -> SeldonMessage:
        raise NotImplementedError

    async def route(self, msg: SeldonMessage) -> int:
        raise NotImplementedError

    async def aggregate(self, msgs: List[SeldonMessage]) -> SeldonMessage:
        raise NotImplementedError

    async def send_feedback(self, feedback: Feedback, branch: int) -> None:
        raise NotImplementedError


class InProcessNodeRuntime(NodeRuntime):
    """A graph node backed by an in-process JAX ``Unit``.

    Holds the unit's state pytree and threads it through every call — the
    functional replacement for the reference wrappers' mutable user objects
    (wrappers/python/persistence.py kept those alive via Redis pickling; here
    state is an explicit pytree, checkpointable via orbax)."""

    def __init__(self, node: PredictiveUnit, unit: Unit, rng=None):
        self.node = node
        self.unit = unit
        self.state = unit.init_state(rng)

    # -- helpers ------------------------------------------------------------

    def _respond(self, req: SeldonMessage, y, tags) -> SeldonMessage:
        names = self.unit.class_names if self.unit.class_names is not None else None
        resp = req.with_array(y, names=names)
        all_tags = dict(self.unit.static_tags or {})
        all_tags.update(pythonize_tags(tags))
        # outlier TRANSFORMER scores (models/outlier.py) bridge out of the
        # response tags into the seldon_tpu_outlier_score family here —
        # every unit method's tags pass through this one spot
        QUALITY.record_outlier_tags(all_tags)
        if all_tags:
            resp.meta = Meta(
                puid=req.meta.puid,
                tags={**req.meta.tags, **all_tags},
                routing=dict(req.meta.routing),
                requestPath=dict(req.meta.requestPath),
            )
        return resp

    def _input_array(self, msg: SeldonMessage):
        return jnp.asarray(msg.array())

    def _call(self, method: str, msg: SeldonMessage, X):
        """Dispatch to the unit; units with ``accepts_names = True`` (the
        reference-style user-object adapter) also receive feature names."""
        fn = getattr(self.unit, method)
        if getattr(self.unit, "accepts_names", False):
            return fn(self.state, X, msg.names())
        return fn(self.state, X)

    # -- NodeRuntime API ----------------------------------------------------

    async def predict(self, msg: SeldonMessage) -> SeldonMessage:
        X = self._input_array(msg)
        out = self._call("predict", msg, X)
        y, self.state, tags = normalize_output(out, self.state)
        # per-node quality identity: host-mode engines and unit pods see
        # each MODEL node's own inputs/predictions, so the drift table
        # resolves to the node that drifted (the compiled lane, one fused
        # program, keys on the graph root instead).  One telemetry-spine
        # record per sampled batch (the unified verdict decides here);
        # the device->host conversion and the fused summarize both run in
        # the drainer, off the serving coroutine (utils/hotrecord.py)
        if QUALITY.enabled:
            from seldon_core_tpu.utils.hotrecord import SPINE

            SPINE.record_quality(self.node.name, X, y)
        return self._respond(msg, y, tags)

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        out = self._call("transform_input", msg, self._input_array(msg))
        y, self.state, tags = normalize_output(out, self.state)
        return self._respond(msg, y, tags)

    async def transform_output(self, msg: SeldonMessage) -> SeldonMessage:
        out = self._call("transform_output", msg, self._input_array(msg))
        y, self.state, tags = normalize_output(out, self.state)
        return self._respond(msg, y, tags)

    async def route(self, msg: SeldonMessage) -> int:
        out = self._call("route", msg, self._input_array(msg))
        branch, self.state, _ = normalize_output(out, self.state)
        return int(np.asarray(branch))

    async def aggregate(self, msgs: List[SeldonMessage]) -> SeldonMessage:
        arrays = [jnp.asarray(m.array()) for m in msgs]
        shapes = {tuple(a.shape) for a in arrays}
        if len(shapes) != 1:
            # the reference's per-row shape check (AverageCombinerUnit.java:44-68)
            raise GraphSpecError(
                f"combiner {self.node.name!r}: child output shapes differ: {sorted(shapes)}"
            )
        if getattr(self.unit, "accepts_names", False):
            out = self.unit.aggregate(
                self.state, jnp.stack(arrays, axis=0), [m.names() for m in msgs]
            )
        else:
            out = self.unit.aggregate(self.state, jnp.stack(arrays, axis=0))
        y, self.state, tags = normalize_output(out, self.state)
        return self._respond(msgs[0], y, tags)

    async def send_feedback(self, feedback: Feedback, branch: int) -> None:
        X = None
        names: list = []
        if feedback.request is not None and feedback.request.data is not None:
            X = jnp.asarray(feedback.request.array())
            names = feedback.request.names()
        truth = None
        if feedback.truth is not None and feedback.truth.data is not None:
            truth = jnp.asarray(feedback.truth.array())
        if getattr(self.unit, "accepts_names", False):
            self.state = self.unit.send_feedback(
                self.state, X, branch, feedback.reward, truth, names
            )
        else:
            self.state = self.unit.send_feedback(
                self.state, X, branch, feedback.reward, truth
            )


# ---------------------------------------------------------------------------
# Graph executor
# ---------------------------------------------------------------------------


def _impl_unit(node: PredictiveUnit) -> Optional[Unit]:
    """Instantiate a hardcoded implementation (the engine's built-in beans)."""
    if node.implementation is UnitImplementation.UNKNOWN_IMPLEMENTATION:
        return None
    cls = UNIT_REGISTRY.get(node.implementation.value)
    if cls is None:
        raise GraphSpecError(f"no registered unit for {node.implementation.value}")
    return cls(**params_to_kwargs(node.parameters))


class GraphExecutor:
    """Builds per-node runtimes from a PredictorSpec and executes the graph —
    the reference's PredictorBean + PredictiveUnitBean pair
    (engine PredictorBean.java:50-80, PredictiveUnitBean.java:58-168)."""

    def __init__(
        self,
        predictor: PredictorSpec,
        extra_runtimes: Optional[Dict[str, NodeRuntime]] = None,
        rng=None,
        tracer=None,
        fuse: bool = False,
    ):
        from seldon_core_tpu.utils.tracing import TRACER

        self.predictor = predictor
        self.tracer = tracer if tracer is not None else TRACER
        self.runtimes: Dict[str, NodeRuntime] = {}
        # partial fusion (graph/fuse.py): every maximal fuse-eligible
        # subtree collapses into ONE device dispatch; the recursion in
        # _get_output/_send_feedback stops at a fused root.  Opt-in
        # (the engine turns it on) so a directly-constructed executor
        # stays the pure per-node interpreter — the fallback/kill-switch
        # semantics every fused path is pinned against.
        self.fused: Dict[str, Any] = {}
        self.fusion_plan = None
        if fuse:
            from seldon_core_tpu.graph.fuse import build_partial_fusion

            self.fused, self.fusion_plan = build_partial_fusion(
                predictor, skip=set(extra_runtimes or ()), rng=rng
            )
        covered = {
            u.name
            for frt in self.fused.values()
            for u in frt.root.walk()
        }
        comp_map = predictor.component_map()
        rngs = unit_rngs([u.name for u in predictor.graph.walk()], rng)
        for node in predictor.graph.walk():
            if node.name in covered:
                continue  # the fused subtree runtime owns this node
            if extra_runtimes and node.name in extra_runtimes:
                self.runtimes[node.name] = extra_runtimes[node.name]
                continue
            unit = _impl_unit(node)
            if unit is not None:
                self.runtimes[node.name] = InProcessNodeRuntime(
                    node, unit, rngs[node.name]
                )
                continue
            binding = comp_map.get(node.name)
            if binding is None:
                raise GraphSpecError(
                    f"node {node.name!r} has no implementation, binding, or runtime"
                )
            if binding.runtime == "inprocess":
                from seldon_core_tpu.graph.units import instantiate_bound_unit

                self.runtimes[node.name] = InProcessNodeRuntime(
                    node, instantiate_bound_unit(binding, node),
                    rngs[node.name]
                )
            else:
                # remote runtimes are attached by the engine service
                # (runtime/client.py) via extra_runtimes
                raise GraphSpecError(
                    f"node {node.name!r} is remote ({binding.runtime}) but no "
                    f"remote runtime was provided"
                )

    # -- predict path -------------------------------------------------------

    async def predict(self, msg: SeldonMessage) -> SeldonMessage:
        out = await self._get_output(self.predictor.graph, msg)
        # puid is preserved onto the final response (PredictionService.java:69-90)
        out.meta.puid = msg.meta.puid
        if out.status is None:
            out.status = Status()
        return out

    async def _get_output(
        self, node: PredictiveUnit, msg: SeldonMessage
    ) -> SeldonMessage:
        # deadline propagation: the request-level budget (set at the edge,
        # runtime/resilience.py) is checked at every node hop — an expired
        # budget fails fast here instead of starting work the caller has
        # already given up on (gRPC-style deadline semantics)
        dl = current_deadline()
        if dl is not None and dl.expired:
            RECORDER.record_deadline_exceeded(f"node:{node.name}")
            raise DeadlineExceededError(
                f"request deadline exhausted before node {node.name!r}"
            )

        frt = self.fused.get(node.name)
        if frt is not None:
            # fused subtree: one device dispatch replaces the recursion
            # below for every node under this root (graph/fuse.py)
            with self.tracer.span(msg.meta.puid, node.name, method="fused"):
                return await frt.run(msg)

        methods = methods_for(node)
        rt = self.runtimes[node.name]
        tracer = self.tracer
        puid = msg.meta.puid

        # 1. transform input (MODEL dispatches its predict here, mirroring
        #    InternalPredictionService.transformInput's type switch,
        #    engine InternalPredictionService.java:132-161)
        if UnitMethod.TRANSFORM_INPUT in methods:
            if effective_type(node) is UnitType.MODEL:
                with tracer.span(puid, node.name, method="predict"):
                    msg = await rt.predict(msg)
            else:
                with tracer.span(puid, node.name, method="transform_input"):
                    msg = await rt.transform_input(msg)

        # 2. route + children (engine PredictiveUnitBean.java:91-112)
        if node.children:
            routed_branch: Optional[int] = None
            if UnitMethod.ROUTE in methods:
                with tracer.span(puid, node.name, method="route") as sp:
                    branch = await rt.route(msg)
                    if isinstance(sp, dict):
                        sp["branch"] = branch
                if branch >= len(node.children) or branch < -1:
                    # routing sanity check (PredictiveUnitBean.java:244-250);
                    # -1 means broadcast, other negatives are bugs (python
                    # negative indexing must never pick a child silently)
                    raise GraphSpecError(
                        f"router {node.name!r} chose branch {branch} but has "
                        f"{len(node.children)} children"
                    )
                if branch != -1:
                    # cost-aware routing (runtime/autopilot.py): demote a
                    # branch predicted to blow the request deadline to the
                    # fastest predicted branch that fits — the predictive
                    # counterpart of the reactive fallback-on-failure
                    branch = self._autopilot_branch(node, msg, branch)
                msg.meta.routing[node.name] = branch
                routed_branch = branch
                selected = node.children if branch == -1 else [node.children[branch]]
            else:
                selected = node.children

            t_children = time.perf_counter()
            child_msgs = await self._dispatch_children(
                node, msg, selected, routed_branch, methods
            )
            if routed_branch is not None and routed_branch != -1:
                # per-branch latency learning for the SHAPE that rode it
                # (a reactive fallback mid-dispatch updates meta.routing,
                # so the branch that actually served gets the sample)
                from seldon_core_tpu.runtime.autopilot import (
                    AUTOPILOT,
                    branch_key,
                )

                AUTOPILOT.observe(
                    branch_key(
                        node.name,
                        msg.meta.routing.get(node.name, routed_branch),
                        _msg_rows(msg),
                    ),
                    time.perf_counter() - t_children,
                )

            # 3. merge (engine PredictiveUnitBean.java:115-124)
            if UnitMethod.AGGREGATE in methods:
                merged_meta = msg.meta
                for cm in child_msgs:
                    merged_meta = merged_meta.merged_with(cm.meta)
                with tracer.span(puid, node.name, method="aggregate"):
                    out = await rt.aggregate(list(child_msgs))
                out.meta = merged_meta.merged_with(out.meta)
            else:
                if len(child_msgs) != 1:
                    raise GraphSpecError(
                        f"node {node.name!r} fanned out to {len(child_msgs)} children "
                        f"but has no AGGREGATE method to merge them"
                    )
                out = child_msgs[0]
                out.meta = msg.meta.merged_with(out.meta)
        else:
            out = msg

        # 4. transform output (engine PredictiveUnitBean.java:115-124)
        if UnitMethod.TRANSFORM_OUTPUT in methods:
            with tracer.span(puid, node.name, method="transform_output"):
                out = await rt.transform_output(out)
        return out

    def _autopilot_branch(self, node: PredictiveUnit, msg: SeldonMessage,
                          branch: int) -> int:
        """Cost-aware routing for ROUTER/ensemble nodes: price the routed
        branch with its learned per-branch latency for THIS request's
        shape bucket (runtime/autopilot.py).  When a deadline budget is
        in force and the prediction says the chosen branch cannot answer
        inside it while another branch can, demote to the fastest
        predicted branch that fits — stamped into ``meta.tags`` and a
        span event so the decision is auditable, and recorded into
        ``meta.routing`` so feedback trains the branch that actually
        served.  No deadline, no predictions, or the kill switch off:
        the router's own choice stands untouched."""
        from seldon_core_tpu.runtime.autopilot import (
            AUTOPILOT,
            autopilot_enabled,
            branch_key,
            shed_margin,
        )

        if not autopilot_enabled():
            return branch
        dl = current_deadline()
        if dl is None:
            return branch
        rows = _msg_rows(msg)
        pred = AUTOPILOT.predict_s(branch_key(node.name, branch, rows))
        rem = dl.remaining_s()
        margin = shed_margin()
        if pred is None or pred <= rem * margin:
            return branch
        best = None
        for b in range(len(node.children)):
            if b == branch:
                continue
            p = AUTOPILOT.predict_s(branch_key(node.name, b, rows))
            if p is not None and p <= rem * margin and (
                best is None or p < best[1]
            ):
                best = (b, p)
        if best is None:
            return branch  # nothing predicted to fit: let the pick ride
        RECORDER.record_autopilot_decision("route")
        self.tracer.event(
            "autopilot_reroute", node=node.name,
            from_branch=int(branch), to_branch=int(best[0]),
            predicted_ms=round(pred * 1e3, 3),
            to_predicted_ms=round(best[1] * 1e3, 3),
            remaining_ms=round(rem * 1e3, 3),
        )
        msg.meta.tags[f"seldon.autopilot.reroute.{node.name}"] = int(best[0])
        return best[0]

    # -- graceful degradation (resilience layer) ----------------------------

    @staticmethod
    def _degradable(exc: BaseException) -> bool:
        """Call failures a declared degradation policy may absorb: remote
        call errors, breaker-open refusals, deadline/timeout expiry,
        transport-level OS errors.  GraphSpecError (misconfiguration) and
        anything unexpected always propagate — degrading over a bug would
        hide it."""
        if isinstance(exc, GraphSpecError):
            return False
        return isinstance(
            exc, (SeldonMessageError, asyncio.TimeoutError, OSError)
        )

    async def _dispatch_children(
        self,
        node: PredictiveUnit,
        msg: SeldonMessage,
        selected: List[PredictiveUnit],
        routed_branch: Optional[int],
        methods: List[UnitMethod],
    ) -> List[SeldonMessage]:
        """Fan out to the selected children, applying the node's declared
        degradation policy (COMBINER ``quorum`` / ROUTER ``fallback``)."""
        if (
            node.quorum is not None
            and UnitMethod.AGGREGATE in methods
            and len(selected) > 1
        ):
            return await self._gather_quorum(node, msg, selected)

        fallback = node.fallback
        if (
            fallback is not None
            and routed_branch is not None
            and routed_branch != -1
            and 0 <= fallback < len(node.children)
            and fallback != routed_branch
        ):
            try:
                return [await self._get_output(selected[0], _fork_message(msg))]
            except BaseException as e:  # noqa: BLE001 - filtered below
                if not self._degradable(e):
                    raise
                # the routed branch failed (or its breaker is open): try
                # the declared fallback branch.  The fork's routing names
                # the fallback so the child-side merge carries it; the
                # degradation is only RECORDED (metric, parent routing,
                # tags) once the fallback actually served — a failed
                # fallback is a failed request, not a degraded serve
                fb_msg = _fork_message(msg)
                fb_msg.meta.routing[node.name] = fallback
                out = await self._get_output(node.children[fallback], fb_msg)
                RECORDER.record_degraded("fallback")
                self.tracer.event(
                    "fallback", node=node.name,
                    from_branch=routed_branch, to_branch=int(fallback),
                    reason=f"{type(e).__name__}: {str(e)[:120]}",
                )
                msg.meta.routing[node.name] = fallback
                msg.meta.tags[f"seldon.fallback.{node.name}"] = int(fallback)
                msg.meta.tags[f"seldon.fallback.{node.name}.reason"] = (
                    f"branch {routed_branch}: {type(e).__name__}: {str(e)[:160]}"
                )
                return [out]

        return list(
            await asyncio.gather(
                *[self._get_output(c, _fork_message(msg)) for c in selected]
            )
        )

    async def _gather_quorum(
        self,
        node: PredictiveUnit,
        msg: SeldonMessage,
        selected: List[PredictiveUnit],
    ) -> List[SeldonMessage]:
        """COMBINER quorum: aggregate over the children that answered when
        at least ``node.quorum`` succeed; dropped branches are annotated in
        ``meta.tags['seldon.degraded.<node>']``.  Below quorum, the first
        child failure propagates unchanged."""
        results = await asyncio.gather(
            *[self._get_output(c, _fork_message(msg)) for c in selected],
            return_exceptions=True,
        )
        ok_msgs: List[SeldonMessage] = []
        dropped: List[str] = []
        first_err: Optional[BaseException] = None
        for child, res in zip(selected, results):
            if isinstance(res, BaseException):
                if not self._degradable(res):
                    raise res
                dropped.append(child.name)
                if first_err is None:
                    first_err = res
            elif res.data is None:
                # a payload-free/malformed child answer would poison the
                # aggregate — under a declared quorum it is a failed branch
                dropped.append(child.name)
                if first_err is None:
                    first_err = SeldonMessageError(
                        f"combiner {node.name!r}: child {child.name!r} "
                        f"returned no tensor payload"
                    )
            else:
                ok_msgs.append(res)
        if len(ok_msgs) < int(node.quorum):
            assert first_err is not None
            raise first_err
        if dropped:
            RECORDER.record_degraded("quorum")
            msg.meta.tags[f"seldon.degraded.{node.name}"] = sorted(dropped)
            self.tracer.event(
                "quorum_degraded", node=node.name, dropped=sorted(dropped)
            )
        return ok_msgs

    # -- feedback path ------------------------------------------------------

    async def send_feedback(self, feedback: Feedback) -> SeldonMessage:
        await self._send_feedback(self.predictor.graph, feedback)
        ack = SeldonMessage(status=Status())
        if feedback.response is not None:
            ack.meta.puid = feedback.response.meta.puid
        return ack

    async def _send_feedback(self, node: PredictiveUnit, feedback: Feedback) -> None:
        frt = self.fused.get(node.name)
        if frt is not None:
            # on-device feedback for the whole fused subtree, replaying
            # the recorded routing exactly like the compiled executor
            await frt.feedback(feedback)
            return
        methods = methods_for(node)
        rt = self.runtimes[node.name]
        routing = (
            feedback.response.meta.routing if feedback.response is not None else {}
        )
        branch = int(routing.get(node.name, -1))

        if UnitMethod.SEND_FEEDBACK in methods:
            await rt.send_feedback(feedback, branch)

        if not node.children:
            return
        if UnitMethod.ROUTE in methods:
            # replay the recorded route: only the serving branch trains
            # (engine PredictiveUnitBean.java:141-149)
            if branch >= len(node.children) or branch < -1:
                raise GraphSpecError(
                    f"feedback routing for {node.name!r} names branch {branch} "
                    f"but node has {len(node.children)} children"
                )
            selected = node.children if branch == -1 else [node.children[branch]]
        else:
            selected = node.children
        await asyncio.gather(*[self._send_feedback(c, feedback) for c in selected])

    # -- state access (persistence / compiled-mode handoff) -----------------

    def states(self) -> Dict[str, Any]:
        out = {
            name: rt.state
            for name, rt in self.runtimes.items()
            if isinstance(rt, InProcessNodeRuntime) and rt.state is not None
        }
        for frt in self.fused.values():
            out.update(frt.graph.states)
        return out

    def load_states(self, states: Dict[str, Any]) -> None:
        for name, st in states.items():
            rt = self.runtimes.get(name)
            if isinstance(rt, InProcessNodeRuntime):
                rt.state = st
        for frt in self.fused.values():
            for name in list(frt.graph.states):
                if name in states:
                    frt.graph.states[name] = states[name]


def _msg_rows(msg: SeldonMessage) -> Optional[int]:
    """Row count for per-branch latency bucketing — the shared rule
    (runtime/autopilot.py message_rows), so interpreter branch buckets
    match gateway p2c buckets."""
    from seldon_core_tpu.runtime.autopilot import message_rows

    return message_rows(msg)


def _fork_message(msg: SeldonMessage) -> SeldonMessage:
    """Child calls get their own meta copy so sibling branches can't race on
    the shared dicts; merge happens explicitly afterwards."""
    return SeldonMessage(
        data=msg.data,
        bin_data=msg.bin_data,
        str_data=msg.str_data,
        meta=Meta(
            puid=msg.meta.puid,
            tags=dict(msg.meta.tags),
            routing=dict(msg.meta.routing),
            requestPath=dict(msg.meta.requestPath),
        ),
        status=msg.status,
    )
