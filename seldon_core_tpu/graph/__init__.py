"""Inference-graph layer: spec (CRD-equivalent), defaulting/validation,
built-in units, host interpreter and compiled-graph executor."""

from seldon_core_tpu.graph.spec import (  # noqa: F401
    ComponentBinding,
    Endpoint,
    EndpointType,
    GraphSpecError,
    Parameter,
    PredictiveUnit,
    PredictorSpec,
    SeldonDeploymentSpec,
    UnitImplementation,
    UnitMethod,
    UnitType,
)
