"""Inference-graph layer: spec (CRD-equivalent), defaulting/validation,
built-in units, host interpreter, compiled-graph executor, and the
whole-graph fusion pass (graph/fuse.py: one XLA program per predictor,
partial fusion of eligible subtrees, SELDON_TPU_GRAPH_FUSE kill
switch)."""

from seldon_core_tpu.graph.spec import (  # noqa: F401
    ComponentBinding,
    Endpoint,
    EndpointType,
    GraphSpecError,
    Parameter,
    PredictiveUnit,
    PredictorSpec,
    SeldonDeploymentSpec,
    UnitImplementation,
    UnitMethod,
    UnitType,
)
