"""Predictive-unit implementations: the functional, JAX-first unit protocol
plus the reference's built-in (hardcoded) units.

The reference models a unit as a stateful object with request/response methods
(engine PredictiveUnitImpl subclasses; wrappers' user classes).  TPU-first, a
unit is a **pure function bundle over an explicit state pytree**, so any unit
can be traced into the graph's single XLA program and any state update
(bandit counters, streaming statistics) is an explicit ``state -> state``
transition that the executor threads — there is no hidden Python mutation to
break under ``jit``.

Method protocol (all arrays are jax arrays, leading batch axis):

    init_state(rng)                  -> state pytree (None if stateless)
    predict(state, X)                -> Y            | (Y, UnitAux)
    transform_input(state, X)        -> X'           | (X', UnitAux)
    transform_output(state, Y)       -> Y'           | (Y', UnitAux)
    route(state, X)                  -> branch int32 | (branch, UnitAux)
    aggregate(state, Ys)             -> Y            | (Y, UnitAux)   # Ys stacked [n_children, ...]
    send_feedback(state, X, branch, reward, truth) -> state

``UnitAux(state=..., tags=...)`` lets a method update unit state and/or attach
data-dependent meta tags (e.g. an outlier score) without breaking purity: both
travel as traced pytrees.  Built-ins mirrored from the reference:

  * SimpleModelUnit  — fixed [0.1, 0.9, 0.5] / class0..2 stub
    (engine SimpleModelUnit.java:29-44)
  * SimpleRouterUnit — always branch 0 (engine SimpleRouterUnit.java:24-31)
  * RandomABTestUnit — uniform draw <= ratioA => branch 0, exactly 2 children
    (engine RandomABTestUnit.java:27-58); PRNG is a threaded jax.random key
    instead of a hidden java.util.Random(1337)
  * AverageCombinerUnit — shape-checked element-wise mean over child outputs
    (engine AverageCombinerUnit.java:30-95); on an ensemble mesh axis this
    lowers to a psum over ICI (see parallel/ensemble.py)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple, Type

import jax
import jax.numpy as jnp

__all__ = [
    "UnitAux",
    "Unit",
    "normalize_output",
    "register_unit",
    "resolve_unit_class",
    "instantiate_bound_unit",
    "UNIT_REGISTRY",
    "SimpleModelUnit",
    "SimpleRouterUnit",
    "RandomABTestUnit",
    "AverageCombinerUnit",
]


class UnitAux(NamedTuple):
    """Optional second return value of any unit method."""

    state: Any = None  # replacement state pytree, or None = unchanged
    tags: Optional[Dict[str, Any]] = None  # data-dependent meta tags


def normalize_output(out, old_state):
    """Normalize ``Y`` or ``(Y, UnitAux)`` to ``(Y, state, tags)``."""
    if isinstance(out, tuple) and len(out) == 2 and isinstance(out[1], UnitAux):
        y, aux = out
        state = aux.state if aux.state is not None else old_state
        return y, state, (aux.tags or {})
    return out, old_state, {}


class Unit:
    """Base class for in-process units.  Subclasses override the methods for
    their unit type; unimplemented methods raise, which the engine surfaces
    as a graph-spec error (the reference's dispatch table guards the same way,
    engine PredictorConfigBean.java:33-96)."""

    #: True if every implemented method is jax-traceable (pure); the compiled
    #: executor refuses impure units, the host interpreter accepts both.
    pure: bool = True
    #: True when the predict/transform path returns state updates that depend
    #: on the rows seen (streaming statistics).  Engines must not pad batches
    #: through such units (padding rows would enter the statistics).
    updates_state_on_predict: bool = False
    #: True when outputs couple rows across the batch (cross-row reductions,
    #: e.g. batch-global min/max normalisation).  Engines must not coalesce
    #: concurrent requests through such units — one caller's rows would
    #: change another caller's answer.
    batch_coupled: bool = False
    #: optional output feature names (the wrappers' class_names)
    class_names: Optional[list] = None
    #: static meta tags merged into every response this unit touches
    static_tags: Optional[dict] = None

    def init_state(self, rng) -> Any:
        return None

    # -- request-path methods (pure, traceable) -----------------------------

    def predict(self, state, X):
        raise NotImplementedError(f"{type(self).__name__} does not implement predict")

    def transform_input(self, state, X):
        raise NotImplementedError(
            f"{type(self).__name__} does not implement transform_input"
        )

    def transform_output(self, state, Y):
        raise NotImplementedError(
            f"{type(self).__name__} does not implement transform_output"
        )

    def route(self, state, X):
        raise NotImplementedError(f"{type(self).__name__} does not implement route")

    def aggregate(self, state, Ys):
        raise NotImplementedError(f"{type(self).__name__} does not implement aggregate")

    # -- feedback path (pure state transition) ------------------------------

    def send_feedback(self, state, X, branch, reward, truth):
        """Return the new state.  ``branch`` is the child index this unit
        routed the original request to (-1 if not a router)."""
        return state


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

UNIT_REGISTRY: Dict[str, Type[Unit]] = {}


def register_unit(name: str) -> Callable[[Type[Unit]], Type[Unit]]:
    def deco(cls: Type[Unit]) -> Type[Unit]:
        UNIT_REGISTRY[name] = cls
        return cls

    return deco


def resolve_unit_class(class_path: str) -> Type[Unit]:
    """Resolve ``registered-name`` or ``module:Class`` to a unit class —
    the TPU equivalent of the wrappers' importlib loading
    (wrappers/python/microservice.py:154-155)."""
    if class_path not in UNIT_REGISTRY:
        # built-in model families register on import; load them lazily so a
        # bare registered name like "MnistClassifier" resolves
        import importlib

        importlib.import_module("seldon_core_tpu.models")
    if class_path in UNIT_REGISTRY:
        return UNIT_REGISTRY[class_path]
    if ":" in class_path:
        mod_name, _, cls_name = class_path.partition(":")
        import importlib

        try:
            mod = importlib.import_module(mod_name)
        except ImportError as e:
            raise ValueError(f"cannot import unit module {mod_name!r}: {e}") from e
        try:
            return getattr(mod, cls_name)
        except AttributeError as e:
            raise ValueError(f"module {mod_name!r} has no class {cls_name!r}") from e
    raise ValueError(
        f"unknown unit {class_path!r}: not registered and not a module:Class path"
    )


def instantiate_bound_unit(binding, node) -> Unit:
    """Build the in-process Unit for a component binding, honouring the
    binding's ``mesh_axes``: a declared mesh (e.g. ``{"tp": 4}`` or
    ``{"ens": 8}``) is constructed over the local devices and handed to the
    unit, so one graph node spans a multi-chip mesh through the standard
    deployment JSON (SURVEY.md §2.7's graph-node-spans-a-mesh design).
    Units that cannot shard reject the binding loudly."""
    from seldon_core_tpu.graph.spec import GraphSpecError, params_to_kwargs

    cls = resolve_unit_class(binding.class_path)
    kwargs = params_to_kwargs(binding.parameters or node.parameters)
    if binding.mesh_axes:
        import inspect

        from seldon_core_tpu.parallel.mesh import build_mesh

        if "mesh" not in inspect.signature(cls.__init__).parameters:
            raise GraphSpecError(
                f"component {binding.name!r} declares mesh_axes "
                f"{dict(binding.mesh_axes)} but unit {cls.__name__} takes no "
                f"mesh; drop mesh_axes or use a mesh-capable unit"
            )
        kwargs["mesh"] = build_mesh(dict(binding.mesh_axes))
    # reference-style plain user objects (predict(X, names) / route / ...)
    # get the SAME adapter the microservice wrapper applies (as_unit), so
    # ANY model-library class (torch/sklearn-style) binds inprocess too —
    # the engine serves it host-mode (UserObjectUnit.pure = False keeps it
    # out of the compiled XLA program, exactly like a remote wrapper node)
    from seldon_core_tpu.graph.interpreter import effective_type
    from seldon_core_tpu.runtime.microservice import as_unit

    # effective_type resolves implementation-implied types the same way
    # the interpreter's method dispatch does (a node may omit `type`)
    etype = effective_type(node)
    service_type = etype.name if etype is not None else "MODEL"
    return as_unit(cls(**kwargs), service_type)


# ---------------------------------------------------------------------------
# Built-in (hardcoded) units
# ---------------------------------------------------------------------------


@register_unit("SIMPLE_MODEL")
class SimpleModelUnit(Unit):
    """Test stub: returns the fixed row [0.1, 0.9, 0.5] per batch element
    (engine SimpleModelUnit.java:33-44)."""

    values = (0.1, 0.9, 0.5)
    class_names = ["class0", "class1", "class2"]

    def predict(self, state, X):
        batch = X.shape[0] if X.ndim >= 1 else 1
        row = jnp.asarray(self.values, dtype=jnp.float32)
        return jnp.tile(row[None, :], (batch, 1))

    # MODEL nodes are dispatched via TRANSFORM_INPUT in the reference
    # (PredictorConfigBean: MODEL => [TRANSFORM_INPUT]); the engine maps that
    # to predict for MODEL-typed units, so only predict needs implementing.


@register_unit("SIMPLE_ROUTER")
class SimpleRouterUnit(Unit):
    """Always routes to child 0 (engine SimpleRouterUnit.java:24-31)."""

    def route(self, state, X):
        return jnp.int32(0)


@register_unit("RANDOM_ABTEST")
class RandomABTestUnit(Unit):
    """Seeded random A/B split: uniform <= ratioA => branch 0
    (engine RandomABTestUnit.java:35-58).  State is the PRNG key, threaded
    explicitly — deterministic for a fixed seed like the reference's
    ``Random(1337)``."""

    def __init__(self, ratioA: float = 0.5, seed: int = 1337):
        self.ratioA = float(ratioA)
        self.seed = int(seed)

    def init_state(self, rng):
        if rng is None:
            rng = jax.random.key(self.seed)
        return rng

    def route(self, state, X):
        key, sub = jax.random.split(state)
        comparator = jax.random.uniform(sub)
        branch = jnp.where(comparator <= self.ratioA, jnp.int32(0), jnp.int32(1))
        return branch, UnitAux(state=key)


@register_unit("AVERAGE_COMBINER")
class AverageCombinerUnit(Unit):
    """Element-wise mean over child outputs (engine AverageCombinerUnit.java:30-95).
    ``Ys`` arrives stacked on a leading children axis; the shape agreement the
    reference checks row-by-row is enforced structurally by the stacking."""

    def aggregate(self, state, Ys):
        return jnp.mean(Ys, axis=0)
