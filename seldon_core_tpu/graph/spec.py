"""Inference-graph specification — the CRD-equivalent schema.

Re-designs the reference's ``SeldonDeployment`` custom resource
(reference: proto/seldon_deployment.proto:10-125) as plain dataclasses that
parse the same JSON the reference accepts (``apiVersion
machinelearning.seldon.io/v1alpha2``, ``spec.predictors[].graph`` tree of
``PredictiveUnit``s), plus a TPU-native extension: a graph node can bind to an
**in-process JAX callable** (``runtime: inprocess``) instead of a remote
microservice container — in that case the engine compiles the node into the
graph's XLA program rather than dialing it over the network.

Mirrored semantics:
  * unit types  ROUTER/COMBINER/MODEL/TRANSFORMER/OUTPUT_TRANSFORMER
    (seldon_deployment.proto:63-71)
  * hardcoded implementations SIMPLE_MODEL/SIMPLE_ROUTER/RANDOM_ABTEST/
    AVERAGE_COMBINER (seldon_deployment.proto:73-80)
  * methods TRANSFORM_INPUT/TRANSFORM_OUTPUT/ROUTE/AGGREGATE/SEND_FEEDBACK
    (seldon_deployment.proto:82-88)
  * ``Endpoint{service_host, service_port, type REST|GRPC}``
    (seldon_deployment.proto:99-109)
  * typed ``Parameter{name, value, type}`` (seldon_deployment.proto:111-125),
    parsed identically to the python wrapper (wrappers/python/microservice.py:122-136)
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Iterator, List, Mapping, Optional

__all__ = [
    "GraphSpecError",
    "UnitType",
    "UnitImplementation",
    "UnitMethod",
    "EndpointType",
    "Endpoint",
    "Parameter",
    "PredictiveUnit",
    "ComponentBinding",
    "PredictorSpec",
    "SeldonDeploymentSpec",
]


class GraphSpecError(ValueError):
    """Invalid graph/deployment spec (the reference's SeldonDeploymentException).

    ``http_code`` lets the serving edge map it to a FAILURE status without
    special-casing (same contract as messages.SeldonMessageError)."""

    http_code = 400


class UnitType(Enum):
    UNKNOWN_TYPE = "UNKNOWN_TYPE"
    ROUTER = "ROUTER"
    COMBINER = "COMBINER"
    MODEL = "MODEL"
    TRANSFORMER = "TRANSFORMER"
    OUTPUT_TRANSFORMER = "OUTPUT_TRANSFORMER"


class UnitImplementation(Enum):
    UNKNOWN_IMPLEMENTATION = "UNKNOWN_IMPLEMENTATION"
    SIMPLE_MODEL = "SIMPLE_MODEL"
    SIMPLE_ROUTER = "SIMPLE_ROUTER"
    RANDOM_ABTEST = "RANDOM_ABTEST"
    AVERAGE_COMBINER = "AVERAGE_COMBINER"


class UnitMethod(Enum):
    TRANSFORM_INPUT = "TRANSFORM_INPUT"
    TRANSFORM_OUTPUT = "TRANSFORM_OUTPUT"
    ROUTE = "ROUTE"
    AGGREGATE = "AGGREGATE"
    SEND_FEEDBACK = "SEND_FEEDBACK"


class EndpointType(Enum):
    REST = "REST"
    GRPC = "GRPC"


@dataclass
class Endpoint:
    service_host: str = ""
    service_port: int = 0
    type: EndpointType = EndpointType.REST

    def to_json_dict(self) -> dict:
        out: dict = {"type": self.type.value}
        if self.service_host:
            out["service_host"] = self.service_host
        if self.service_port:
            out["service_port"] = self.service_port
        return out

    @staticmethod
    def from_json_dict(d: Mapping[str, Any]) -> "Endpoint":
        return Endpoint(
            service_host=str(d.get("service_host", "") or ""),
            service_port=int(d.get("service_port", 0) or 0),
            type=EndpointType(str(d.get("type", "REST") or "REST")),
        )


_PARAM_CASTS = {
    "INT": int,
    "FLOAT": float,
    "DOUBLE": float,
    "STRING": str,
    "BOOL": lambda v: str(v).lower() in ("true", "1"),
}


@dataclass
class Parameter:
    """Typed unit parameter; ``value`` is a string on the wire, cast on read
    (wrappers/python/microservice.py:122-136)."""

    name: str
    value: str
    type: str = "STRING"  # INT | FLOAT | DOUBLE | STRING | BOOL

    def typed_value(self):
        try:
            return _PARAM_CASTS[self.type](self.value)
        except KeyError as e:
            raise GraphSpecError(f"unknown parameter type {self.type!r}") from e
        except (TypeError, ValueError) as e:
            raise GraphSpecError(
                f"parameter {self.name!r}: cannot cast {self.value!r} to {self.type}"
            ) from e

    def to_json_dict(self) -> dict:
        return {"name": self.name, "value": str(self.value), "type": self.type}

    @staticmethod
    def from_json_dict(d: Mapping[str, Any]) -> "Parameter":
        if "name" not in d:
            raise GraphSpecError("parameter missing 'name'")
        return Parameter(
            name=str(d["name"]),
            value=str(d.get("value", "")),
            type=str(d.get("type", "STRING") or "STRING"),
        )


def params_to_kwargs(params: List[Parameter]) -> dict:
    """Typed parameters -> constructor kwargs for a unit implementation."""
    return {p.name: p.typed_value() for p in params}


@dataclass
class PredictiveUnit:
    """One node of the inference graph (seldon_deployment.proto:90-97).

    TPU-native degradation extensions (runtime resilience layer):

    * ``quorum`` (COMBINER/AGGREGATE nodes): aggregate over the children
      that answered when at least ``quorum`` of them succeed, instead of
      failing the whole request on the first child error; dropped branches
      are annotated into ``meta.tags``.
    * ``fallback`` (ROUTER nodes): child index served when the routed
      branch's call fails or its circuit breaker is open.
    """

    name: str
    children: List["PredictiveUnit"] = field(default_factory=list)
    type: Optional[UnitType] = None
    implementation: UnitImplementation = UnitImplementation.UNKNOWN_IMPLEMENTATION
    methods: Optional[List[UnitMethod]] = None
    endpoint: Optional[Endpoint] = None
    parameters: List[Parameter] = field(default_factory=list)
    quorum: Optional[int] = None
    fallback: Optional[int] = None

    # -- traversal ----------------------------------------------------------

    def walk(self) -> Iterator["PredictiveUnit"]:
        yield self
        for c in self.children:
            yield from c.walk()

    def find(self, name: str) -> Optional["PredictiveUnit"]:
        for u in self.walk():
            if u.name == name:
                return u
        return None

    # -- codecs -------------------------------------------------------------

    def to_json_dict(self) -> dict:
        out: dict = {"name": self.name}
        if self.children:
            out["children"] = [c.to_json_dict() for c in self.children]
        if self.type is not None:
            out["type"] = self.type.value
        if self.implementation is not UnitImplementation.UNKNOWN_IMPLEMENTATION:
            out["implementation"] = self.implementation.value
        if self.methods is not None:
            out["methods"] = [m.value for m in self.methods]
        if self.endpoint is not None:
            out["endpoint"] = self.endpoint.to_json_dict()
        if self.parameters:
            out["parameters"] = [p.to_json_dict() for p in self.parameters]
        if self.quorum is not None:
            out["quorum"] = int(self.quorum)
        if self.fallback is not None:
            out["fallback"] = int(self.fallback)
        return out

    @staticmethod
    def from_json_dict(d: Mapping[str, Any]) -> "PredictiveUnit":
        if not isinstance(d, Mapping) or "name" not in d:
            raise GraphSpecError("graph node missing 'name'")
        try:
            unit_type = UnitType(d["type"]) if "type" in d else None
            impl = (
                UnitImplementation(d["implementation"])
                if "implementation" in d
                else UnitImplementation.UNKNOWN_IMPLEMENTATION
            )
            methods = (
                [UnitMethod(m) for m in d["methods"]] if "methods" in d else None
            )
            quorum = int(d["quorum"]) if d.get("quorum") is not None else None
            fallback = (
                int(d["fallback"]) if d.get("fallback") is not None else None
            )
        except (ValueError, TypeError) as e:
            raise GraphSpecError(f"graph node {d['name']!r}: {e}") from e
        return PredictiveUnit(
            name=str(d["name"]),
            children=[PredictiveUnit.from_json_dict(c) for c in d.get("children", []) or []],
            type=unit_type,
            implementation=impl,
            methods=methods,
            endpoint=Endpoint.from_json_dict(d["endpoint"]) if d.get("endpoint") else None,
            parameters=[Parameter.from_json_dict(p) for p in d.get("parameters", []) or []],
            quorum=quorum,
            fallback=fallback,
        )


@dataclass
class ComponentBinding:
    """What the reference calls a *container* (one microservice image per graph
    node, seldon_deployment.proto:55-58 componentSpecs): here, the runtime
    binding of a graph node.  Three runtimes:

    * ``inprocess`` — node is a registered/importable Python class whose
      methods are JAX callables; the engine jit-compiles it into the graph.
      ``class_path`` is ``module:Class`` or a name registered in the unit
      registry.
    * ``rest`` / ``grpc`` — node is a remote microservice, reference-style;
      ``host``/``port`` filled by defaulting.

    ``device``/``mesh_axes`` control placement for inprocess units.
    """

    name: str
    runtime: str = "inprocess"  # inprocess | rest | grpc
    class_path: str = ""
    image: str = ""
    device: str = "tpu"
    mesh_axes: Optional[dict] = None
    parameters: List[Parameter] = field(default_factory=list)
    env: dict = field(default_factory=dict)
    host: str = ""
    port: int = 0

    def to_json_dict(self) -> dict:
        out: dict = {"name": self.name, "runtime": self.runtime}
        for k in ("class_path", "image", "device", "host"):
            if getattr(self, k):
                out[k] = getattr(self, k)
        if self.mesh_axes:
            out["mesh_axes"] = dict(self.mesh_axes)
        if self.parameters:
            out["parameters"] = [p.to_json_dict() for p in self.parameters]
        if self.env:
            out["env"] = dict(self.env)
        if self.port:
            out["port"] = self.port
        return out

    @staticmethod
    def from_container_json(d: Mapping[str, Any]) -> "ComponentBinding":
        """Parse either a reference k8s container entry ({name, image, ...})
        or a TPU-native binding ({name, runtime, class_path, device, ...})."""
        if "name" not in d:
            raise GraphSpecError("component/container missing 'name'")
        runtime = str(d.get("runtime", "") or "")
        if not runtime:
            # reference-style container: remote REST microservice by default
            runtime = "inprocess" if d.get("class_path") else "rest"
        if runtime not in ("inprocess", "rest", "grpc"):
            raise GraphSpecError(f"unknown runtime {runtime!r} for {d['name']!r}")
        return ComponentBinding(
            name=str(d["name"]),
            runtime=runtime,
            class_path=str(d.get("class_path", "") or ""),
            image=str(d.get("image", "") or ""),
            device=str(d.get("device", "tpu") or "tpu"),
            mesh_axes=dict(d["mesh_axes"]) if d.get("mesh_axes") else None,
            parameters=[Parameter.from_json_dict(p) for p in d.get("parameters", []) or []],
            env={str(e["name"]): str(e.get("value", "")) for e in d.get("env", []) or []}
            if isinstance(d.get("env"), list)
            else dict(d.get("env", {}) or {}),
            host=str(d.get("host", "") or ""),
            port=int(d.get("port", 0) or 0),
        )


@dataclass
class PredictorSpec:
    """One predictor = one servable instance of the graph
    (seldon_deployment.proto:44-53).  Canary/AB setups deploy several
    predictors with different replica weights."""

    name: str
    graph: PredictiveUnit
    components: List[ComponentBinding] = field(default_factory=list)
    replicas: int = 1
    annotations: dict = field(default_factory=dict)
    labels: dict = field(default_factory=dict)

    def component_map(self) -> dict:
        return {c.name: c for c in self.components}

    def to_json_dict(self) -> dict:
        return {
            "name": self.name,
            "graph": self.graph.to_json_dict(),
            "componentSpecs": [
                {"spec": {"containers": [c.to_json_dict() for c in self.components]}}
            ]
            if self.components
            else [],
            "replicas": self.replicas,
            "annotations": dict(self.annotations),
            "labels": dict(self.labels),
        }

    @staticmethod
    def from_json_dict(d: Mapping[str, Any]) -> "PredictorSpec":
        if "name" not in d or "graph" not in d:
            raise GraphSpecError("predictor needs 'name' and 'graph'")
        components: List[ComponentBinding] = []
        # reference layout: componentSpecs[].spec.containers[]
        for cs in d.get("componentSpecs", []) or []:
            containers = (cs.get("spec", {}) or {}).get("containers", []) or []
            for c in containers:
                components.append(ComponentBinding.from_container_json(c))
        # TPU-native shorthand: components[]
        for c in d.get("components", []) or []:
            components.append(ComponentBinding.from_container_json(c))
        return PredictorSpec(
            name=str(d["name"]),
            graph=PredictiveUnit.from_json_dict(d["graph"]),
            components=components,
            replicas=int(d.get("replicas", 1) or 1),
            annotations=dict(d.get("annotations", {}) or {}),
            labels=dict(d.get("labels", {}) or {}),
        )


@dataclass
class SeldonDeploymentSpec:
    """The full deployment resource (metadata + spec.predictors[])."""

    name: str  # spec.name — the per-deployment service name
    metadata_name: str = ""  # metadata.name — the resource name
    predictors: List[PredictorSpec] = field(default_factory=list)
    annotations: dict = field(default_factory=dict)
    oauth_key: str = ""
    oauth_secret: str = ""
    labels: dict = field(default_factory=dict)
    api_version: str = "machinelearning.seldon.io/v1alpha2"

    def predictor(self, name: Optional[str] = None) -> PredictorSpec:
        if name is None:
            if not self.predictors:
                raise GraphSpecError("deployment has no predictors")
            return self.predictors[0]
        for p in self.predictors:
            if p.name == name:
                return p
        raise GraphSpecError(f"no predictor named {name!r}")

    def to_json_dict(self) -> dict:
        return {
            "apiVersion": self.api_version,
            "kind": "SeldonDeployment",
            "metadata": {"name": self.metadata_name or self.name, "labels": dict(self.labels)},
            "spec": {
                "name": self.name,
                "annotations": dict(self.annotations),
                "oauth_key": self.oauth_key,
                "oauth_secret": self.oauth_secret,
                "predictors": [p.to_json_dict() for p in self.predictors],
            },
        }

    def to_json(self) -> str:
        return json.dumps(self.to_json_dict(), separators=(",", ":"))

    @staticmethod
    def from_json_dict(d: Mapping[str, Any]) -> "SeldonDeploymentSpec":
        if not isinstance(d, Mapping):
            raise GraphSpecError("deployment JSON must be an object")
        spec = d.get("spec") if d.get("spec") is not None else d  # bare spec ok
        if not isinstance(spec, Mapping) or "predictors" not in spec:
            raise GraphSpecError("deployment spec missing 'predictors'")
        meta = d.get("metadata", {}) or {}
        # annotations live in BOTH standard places: metadata.annotations is
        # where `kubectl annotate` writes; spec.annotations is the
        # reference's location.  Merge, spec-level winning on conflict
        # (more specific to this framework's schema).
        annotations = dict(meta.get("annotations", {}) or {})
        annotations.update(dict(spec.get("annotations", {}) or {}))
        return SeldonDeploymentSpec(
            name=str(spec.get("name", meta.get("name", "")) or ""),
            metadata_name=str(meta.get("name", "") or ""),
            predictors=[PredictorSpec.from_json_dict(p) for p in spec["predictors"]],
            annotations=annotations,
            oauth_key=str(spec.get("oauth_key", "") or ""),
            oauth_secret=str(spec.get("oauth_secret", "") or ""),
            labels=dict(meta.get("labels", {}) or {}),
        )

    @staticmethod
    def from_json(s) -> "SeldonDeploymentSpec":
        try:
            d = json.loads(s)
        except json.JSONDecodeError as e:
            raise GraphSpecError(f"invalid JSON: {e}") from e
        return SeldonDeploymentSpec.from_json_dict(d)
